"""User/pool gauge sweeper.

Parity with the reference's monitor (reference: scheduler/src/cook/
monitor.clj:35-207 set-stats-counters!): per pool, compute per-user
running/waiting resource stats, derive **starved** users (waiting users
whose running usage is below their fair share on every dimension),
**waiting-under-quota** users (waiting users whose running usage is below
their quota on every dimension), **hungry** (waiting but not starved) and
**satisfied** (running and not waiting) user counts, and publish everything
as gauges — including an aggregated pseudo-user ``all`` and zeroing of
series for users that disappeared since the previous sweep
(clear-old-counters!, monitor.clj:137-156).

The sweep is also the SLO layer (config.SloConfig): per-pool pending-age
distributions vs the queue-latency objective and the flight recorder's
recent cycle durations vs the cycle-duration objective, published as
``cook_slo_objective_seconds`` / ``cook_slo_breach_ratio`` /
``cook_slo_burn_rate`` gauges plus a sampled
``cook_queue_latency_seconds`` histogram — the alerting surface every
perf PR is judged against (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..config import Config, SloConfig
from ..state.store import Store
from ..utils.metrics import LATENCY_BUCKETS, MetricsRegistry
from ..utils.metrics import registry as default_registry

_STAT_DIMS = ("cpus", "mem", "jobs")


def _job_stats(jobs_with_user: List[Tuple[str, float, float]]
               ) -> Dict[str, Dict[str, float]]:
    """[(user, cpus, mem)] -> user -> {cpus, mem, jobs} (reference:
    get-job-stats monitor.clj:40-57)."""
    stats: Dict[str, Dict[str, float]] = {}
    for user, cpus, mem in jobs_with_user:
        s = stats.setdefault(user, {"cpus": 0.0, "mem": 0.0, "jobs": 0.0})
        s["cpus"] += cpus
        s["mem"] += mem
        s["jobs"] += 1
    return stats


def _with_aggregate(stats: Dict[str, Dict[str, float]]
                    ) -> Dict[str, Dict[str, float]]:
    """Add the pseudo-user 'all' summing every user (add-aggregated-stats,
    monitor.clj:59-68)."""
    total = {"cpus": 0.0, "mem": 0.0, "jobs": 0.0}
    for s in stats.values():
        for k in _STAT_DIMS:
            total[k] += s.get(k, 0.0)
    out = dict(stats)
    out["all"] = total
    return out


def compute_starved_stats(store: Store, pool_name: str,
                          running: Dict[str, Dict[str, float]],
                          waiting: Dict[str, Dict[str, float]]
                          ) -> Dict[str, Dict[str, float]]:
    """Waiting users whose running usage is strictly below their share on
    every share dimension; starvation = min(waiting, share - running)
    (get-starved-job-stats, monitor.clj:70-90)."""
    out: Dict[str, Dict[str, float]] = {}
    for user in waiting:
        share = store.get_share(user, pool_name)
        used = running.get(user, {})
        promised = {k: share.get(k, float("inf")) for k in ("cpus", "mem")}
        if all(used.get(k, 0.0) < v for k, v in promised.items()):
            out[user] = {
                k: min(waiting[user].get(k, 0.0),
                       promised.get(k, float("inf")) - used.get(k, 0.0))
                for k in _STAT_DIMS if k != "jobs"}
            out[user]["jobs"] = waiting[user].get("jobs", 0.0)
    return out


def compute_waiting_under_quota_stats(store: Store, pool_name: str,
                                      running: Dict[str, Dict[str, float]],
                                      waiting: Dict[str, Dict[str, float]]
                                      ) -> Dict[str, Dict[str, float]]:
    """Waiting users whose running usage is strictly below quota on every
    quota dimension; amount = min(waiting, max(quota - running, 0))
    (get-waiting-under-quota-job-stats, monitor.clj:92-117)."""
    out: Dict[str, Dict[str, float]] = {}
    for user in waiting:
        quota = store.get_quota(user, pool_name)
        used = running.get(user, {})
        promised = {"cpus": quota.get("cpus", float("inf")),
                    "mem": quota.get("mem", float("inf")),
                    "jobs": quota.get("count", float("inf"))}
        if all(used.get(k, 0.0) < v for k, v in promised.items()):
            out[user] = {
                k: min(waiting[user].get(k, 0.0),
                       max(promised[k] - used.get(k, 0.0), 0.0))
                for k in _STAT_DIMS}
    return out


class Monitor:
    """Periodic stats sweeper publishing per-user per-pool gauges
    (start-collecting-stats, monitor.clj:209)."""

    def __init__(self, store: Store,
                 registry: Optional[MetricsRegistry] = None,
                 config: Optional[Config] = None):
        self.store = store
        self.registry = registry if registry is not None else default_registry
        self.slo: SloConfig = (config.slo if config is not None
                               else SloConfig())
        self.config: Config = config if config is not None else Config()
        # fleet observability plane (sched/fleet.py): the scheduler
        # wires its rate limiters in (launch-token saturation input) and
        # the daemon attaches a FleetScraper; both stay None in
        # store-only constructions (tests, the simulator)
        self.rate_limits = None
        self.read_view = None
        self.fleet = None
        # adaptive-admission control loop (sched/admission.py): the
        # scheduler wires its AdmissionController in when the admission
        # section enables it; each sweep's saturation gauges feed ONE
        # decide() step.  None = no adaptive admission (default).
        self.admission = None
        # (pool, state) -> {user -> stats} from the previous sweep, so
        # series for vanished users can be zeroed
        self._previous: Dict[Tuple[str, str], Dict[str, Dict]] = {}
        # metric-cardinality guard (utils/metrics.py): the sweep folds
        # per-user families to top-K-by-usage + an "other" bucket itself;
        # the registry cap is the hard backstop should any publisher
        # emit user-labeled series unfolded.  The window is scoped per
        # (pool, state) for cook_user_resource — the four per-state
        # publishes have DISJOINT user sets, so a shared per-pool window
        # would overflow at populations near the fold cap — and sized
        # 2*cap+16 so one sweep's own writes can never fold: the live
        # publish is <= cap+2 series (top-K + "all" + "other") and the
        # departed-user zero-writes are <= the previous sweep's cap+2.
        cap = max(int(self.slo.max_user_series), 1)
        self.registry.set_label_cap("cook_user_resource", "user",
                                    cap * 2 + 16,
                                    scope=("pool", "state"))
        self.registry.set_label_cap("cook_user_dru", "user",
                                    cap * 2 + 16, scope=("pool",))
        self.registry.set_label_cap("cook_user_global_jobs", "user",
                                    cap * 2 + 16)
        # endpoints that have ever carried traffic: quiet ones must be
        # re-published at 0 each sweep, or one slow request's burn-rate
        # gauge would stick at its breach value forever
        self._http_endpoints: Set[str] = set()
        # storage-integrity scrub cadence gate: the sweep runs every
        # monitor interval but a scrub step only at the configured
        # scrub_interval_seconds
        self._last_scrub_ts = 0.0

    # ------------------------------------------------------------- one sweep
    def sweep(self) -> Dict[str, Dict[str, int]]:
        """Recompute and publish all gauges; returns per-pool user counts
        (total/starved/hungry/satisfied/waiting_under_quota) for tests and
        structured logging."""
        out: Dict[str, Dict[str, int]] = {}
        # DRU series are re-derived whole each sweep (top-K churns):
        # clear-then-set keeps the exported set exactly the live one,
        # and the cardinality-guard admission window resets so THIS
        # sweep's top-K claims the slots (without the reset, the
        # first-ever cap*8 users would hold them forever and every later
        # heavy user would fold into "other"; utils/metrics.py contract)
        self.registry.gauge_clear("cook_user_dru")
        for metric in ("cook_user_resource", "cook_user_dru"):
            self.registry.reset_label_window(metric, "user")
        for pool in self.store.pools():
            out[pool.name] = self._sweep_pool(pool)
        self._sweep_cycle_slo()
        self._sweep_http_slo()
        self._sweep_serving()
        self._sweep_storage()
        saturation = self._sweep_saturation()
        admission = self.admission
        if admission is not None:
            # the adaptive-admission control loop runs at the sweep
            # cadence off the SAME saturation computation the gauges
            # publish — the operator's dashboard and the controller can
            # never disagree about the input signal
            admission.decide(saturation)
        fleet = self.fleet
        if fleet is not None:
            # monitor-driven federation (sched/fleet.py): the scraper
            # self-gates to its own interval, so the sweep cadence and
            # the scrape cadence stay independently configurable
            fleet.maybe_scrape()
        return out

    def _sweep_saturation(self) -> Dict[str, float]:
        """The derived 0-1 saturation layer (sched/fleet.py formulas):
        recomputed from live counters each sweep and published as
        ``cook_saturation{resource=}`` — the admission-control input
        contract (sched/admission.py consumes the returned dict), also
        surfaced on /debug/health + /debug/fleet."""
        from .fleet import compute_saturation, publish_saturation
        saturation = compute_saturation(self.config, store=self.store,
                                        read_view=self.read_view,
                                        rate_limits=self.rate_limits)
        publish_saturation(saturation, self.registry)
        return saturation

    def _sweep_storage(self) -> None:
        """Storage-integrity sweep (docs/ROBUSTNESS.md "WAL v2"): drive
        one incremental CRC32C scrub step per journal shard at the
        configured cadence (:meth:`Store.scrub`) and publish the
        verified frontier as ``cook_storage_scrub_offset_bytes`` —
        corruption/repair events count at the detection sites themselves
        (``cook_journal_corruption_total`` /
        ``cook_storage_repair_total``), so a sweep that finds nothing
        costs one bounded read per shard and no counter churn."""
        import time as _time
        scfg = getattr(self.config, "storage", None)
        if scfg is not None and not scfg.scrub_enabled:
            return
        interval = (scfg.scrub_interval_seconds if scfg is not None
                    else 30.0)
        chunk = scfg.scrub_chunk_bytes if scfg is not None else 1 << 20
        repair = (scfg.checkpoint_on_corruption if scfg is not None
                  else True)
        now = _time.time()
        if now - self._last_scrub_ts < interval:
            return
        self._last_scrub_ts = now
        from ..state.partition import substores
        shards = substores(self.store)
        partitioned = len(shards) > 1 or (
            shards and shards[0] is not self.store)
        for shard in shards:
            scrub = getattr(shard, "scrub", None)
            if scrub is None:
                continue
            doc = scrub(max_bytes=chunk, repair=repair)
            if not doc.get("enabled"):
                continue
            pl = getattr(shard, "partition_label", lambda: None)()
            labels = {"partition": pl} if partitioned and pl else None
            self.registry.gauge_set(
                "cook_storage_scrub_offset_bytes",
                float(doc.get("verified_offset", 0)), labels=labels)

    def _sweep_serving(self) -> None:
        """Leader serving-plane gauges: the journal commit position (the
        read-your-writes token's upper bound, which follower staleness
        is measured against) and the group-commit stage's live state —
        the batch-size HISTOGRAM is recorded by the committer itself
        per batch (cook_group_commit_batch_size); the sweep publishes
        the queue depth a stuck committer would show."""
        from ..state.partition import substores
        shards = substores(self.store)
        partitioned = len(shards) > 1 or (
            shards and shards[0] is not self.store)
        for shard in shards:
            # one gauge per shard, partition-labeled on the partitioned
            # plane (each partition's journal is its own offset space —
            # summing heads across partitions would be the exact
            # mis-comparison the token vector exists to prevent)
            pl = getattr(shard, "partition_label", lambda: None)()
            labels = {"partition": pl} if partitioned and pl else None
            co = getattr(shard, "commit_offset", None)
            if co is not None and co():
                self.registry.gauge_set("cook_journal_head_bytes",
                                        float(co()), labels=labels)
            gc_stats = getattr(shard, "group_commit_stats", None)
            gc = gc_stats() if gc_stats is not None else None
            if gc is not None:
                self.registry.gauge_set("cook_group_commit_pending",
                                        float(gc["pending"]),
                                        labels=labels)
        summaries = getattr(self.store, "summaries", None)
        if summaries is not None:
            # the monitor's GLOBAL view on a partitioned plane: per-user
            # total footprint across every partition, read from the
            # bounded-staleness summary exchange (counts, never job
            # state) — top-K folding is the registry cap's job here
            merged = summaries.merged()
            top = sorted(merged.items(),
                         key=lambda kv: -(kv[1]["pending"]
                                          + kv[1]["running"]))
            self.registry.gauge_clear("cook_user_global_jobs")
            for user, u in top[:self.slo.max_user_series]:
                self.registry.gauge_set(
                    "cook_user_global_jobs",
                    u["pending"] + u["running"],
                    labels={"user": user})

    def _sweep_pool(self, pool) -> Dict[str, int]:
        from ..state.schema import DruMode
        pool_name = pool.name
        # clone=False: the sweep only READS (user, resources, wait
        # ages) to fold into gauges — cloning 20k+ jobs per sweep was
        # most of the sweep's cost, and a monitor that burns half a
        # core under queue pressure is feeding the very saturation it
        # reports (store.jobs_where contract)
        pending = self.store.pending_jobs(pool_name, clone=False)
        running = self.store.running_instances(pool_name, clone=False)
        running_stats = _job_stats([
            (job.user, job.resources.cpus, job.resources.mem)
            for job, _inst in running])
        waiting_stats = _job_stats([
            (job.user, job.resources.cpus, job.resources.mem)
            for job in pending])
        self._sweep_queue_slo(pool_name, pending)
        # fairness plane (docs/OBSERVABILITY.md): per-user DRU (actual
        # usage normalized by share), published top-K + cached on the
        # audit trail for rank-event context, and the wait-phase split
        # of the pending queue (fairness vs capacity vs constraints)
        gpu_usage = None
        if pool.dru_mode is DruMode.GPU:
            # GPU pools rank/rebalance on the gpus dimension — the DRU
            # gauge must price the same dimension or it diverges from
            # what the rebalancer actually preempts against
            gpu_usage = {}
            for job, _inst in running:
                gpu_usage[job.user] = \
                    gpu_usage.get(job.user, 0.0) + job.resources.gpus
        dru = self._sweep_user_dru(pool_name, running_stats,
                                   waiting_stats, gpu_usage=gpu_usage)
        self._sweep_wait_phases(pool_name, pending, dru)
        starved = compute_starved_stats(
            self.store, pool_name, running_stats, waiting_stats)
        under_quota = compute_waiting_under_quota_stats(
            self.store, pool_name, running_stats, waiting_stats)

        running_users = set(running_stats)
        waiting_users = set(waiting_stats)
        counts = {
            "total": len(running_users | waiting_users),
            "starved": len(starved),
            "waiting_under_quota": len(under_quota),
            "hungry": len(waiting_users - set(starved)),
            "satisfied": len(running_users - waiting_users),
        }
        for state, stats in (("running", running_stats),
                             ("waiting", waiting_stats),
                             ("starved", starved),
                             ("waiting-under-quota", under_quota)):
            self._publish_state(pool_name, state, stats)
        for state, value in counts.items():
            self.registry.gauge_set(
                "cook_user_state_count", float(value),
                labels={"pool": pool_name, "state": state.replace("_", "-")})
        return counts

    def _fold_tail(self, stats: Dict[str, Dict[str, float]]
                   ) -> Dict[str, Dict[str, float]]:
        """Top-K-by-usage + an aggregated ``other`` bucket past the
        per-user series cap (SloConfig.max_user_series): the fairness
        gauges stay bounded at millions-of-users scale, with the folded
        tail still visible in aggregate
        (``cook_metrics_dropped_labels_total`` counts registry-level
        folds from any publisher that skips this)."""
        cap = max(int(self.slo.max_user_series), 1)
        if len(stats) <= cap:
            return stats
        ranked = sorted(
            stats.items(),
            key=lambda kv: -(kv[1].get("cpus", 0.0) + kv[1].get("mem", 0.0)))
        out = dict(ranked[:cap])
        other = {k: 0.0 for k in _STAT_DIMS}
        for _u, s in ranked[cap:]:
            for k in _STAT_DIMS:
                other[k] += s.get(k, 0.0)
        out["other"] = other
        return out

    def _sweep_user_dru(self, pool_name: str,
                        running_stats: Dict[str, Dict[str, float]],
                        waiting_stats: Dict[str, Dict[str, float]],
                        gpu_usage: Optional[Dict[str, float]] = None
                        ) -> Dict[str, float]:
        """Per-user DRU = usage normalized by share on the pool's DRU
        dimension(s) — the fair-share position the rebalancer prices
        preemption against (rebalancer._recompute_user), now visible as
        a gauge next to the share itself.  ``gpu_usage`` non-None marks
        a DruMode.GPU pool: DRU is gpus/share like the rebalancer's,
        not cpus/mem.  Every user's value is cached on the audit trail
        (rank events and ``cs why`` attach it); only the top-K +
        ``other`` (max of the tail) are exported as series."""
        dru: Dict[str, float] = {}
        for user in set(running_stats) | set(waiting_stats):
            share = self.store.get_share(user, pool_name)
            if gpu_usage is not None:
                sg = share.get("gpus")
                dru[user] = (gpu_usage.get(user, 0.0) / sg
                             if sg and sg != float("inf") else 0.0)
                continue
            used = running_stats.get(user, {})
            vals = [used.get(dim, 0.0) / share[dim]
                    for dim in ("cpus", "mem")
                    if share.get(dim) and share[dim] != float("inf")]
            dru[user] = max(vals) if vals else 0.0
        # wholesale replace: departed users age out of the cache instead
        # of accumulating for the leader's lifetime
        self.store.audit.set_user_dru(pool_name, dru)
        cap = max(int(self.slo.max_user_series), 1)
        top = sorted(dru.items(), key=lambda kv: -kv[1])
        for user, v in top[:cap]:
            self.registry.gauge_set("cook_user_dru", round(v, 6),
                                    {"pool": pool_name, "user": user})
        if len(top) > cap:
            self.registry.gauge_set(
                "cook_user_dru", round(top[cap][1], 6),
                {"pool": pool_name, "user": "other"})
        return dru

    def _sweep_wait_phases(self, pool_name: str, pending,
                           dru: Dict[str, float]) -> None:
        """Split the pending queue's current waits by WHY (utils/audit.
        wait_phase): ``fairness`` (quota / rate limit / gang admission /
        at-or-over share), ``constraints`` (placement-constraint or
        topology blocked), ``capacity`` (placeable, no room).  Each
        phase gets its own latency histogram + job-count gauge and its
        own queue-latency SLO breach ratio, so "users are waiting" pages
        name the mechanism before anyone opens a timeline."""
        from ..utils.audit import wait_phase
        now_ms = self.store.clock()
        # ONE lock hold for the whole queue's reasons: a per-job
        # last_reason() would pay 100k lock round-trips contending with
        # the scheduler's hot-path record() calls
        reasons = self.store.audit.last_reasons(
            [j.uuid for j in pending])
        by_phase: Dict[str, list] = {
            "fairness": [], "capacity": [], "constraints": []}
        for j in pending:
            reason = reasons.get(j.uuid)
            # the persisted placement-failure census refines "couldn't
            # place" into constraints-vs-capacity, but it is STICKY
            # (never cleared once set) — a fresher fairness-side skip
            # reason from the audit trail must win over it, or a job
            # that failed placement once and is now quota-throttled
            # would misreport as capacity forever
            if reason is None or reason == "unmatched":
                lpf = j.last_placement_failure
                if lpf:
                    reason = ("constraints" if lpf.get("constraints")
                              else "unmatched")
            phase = wait_phase(reason, dru.get(j.user, 0.0) >= 1.0)
            age = (now_ms - (j.last_waiting_start_ms
                             or j.submit_time_ms)) / 1000.0
            by_phase[phase].append(age)
        obj = self.slo.queue_latency_objective_s
        for phase, ages in by_phase.items():
            labels = {"pool": pool_name, "phase": phase}
            self.registry.gauge_set("cook_wait_phase_jobs",
                                    float(len(ages)), labels)
            self.registry.observe_many("cook_wait_phase_seconds", ages,
                                       labels, buckets=LATENCY_BUCKETS)
            breach = sum(1 for a in ages if a > obj)
            self._publish_slo(f"queue-latency-{phase}", obj,
                              breach / len(ages) if ages else 0.0,
                              pool=pool_name)

    def _publish_state(self, pool_name: str, state: str,
                       stats: Dict[str, Dict[str, float]]) -> None:
        key = (pool_name, state)
        stats = self._fold_tail(stats)
        previous: Set[str] = set(self._previous.get(key, {}))
        with_all = _with_aggregate(stats) if stats else {
            "all": {k: 0.0 for k in _STAT_DIMS}}
        # LIVE series first, vanished-user zeroing after: the
        # cardinality window admits first-come, and the zero-writes for
        # departed users must never crowd this sweep's top-K out of it
        self._previous[key] = dict(stats)
        for user, s in with_all.items():
            for dim in _STAT_DIMS:
                self.registry.gauge_set(
                    "cook_user_resource", float(s.get(dim, 0.0)),
                    labels={"pool": pool_name, "user": user, "state": state,
                            "resource": dim})
        for user in previous - set(with_all):
            for dim in _STAT_DIMS:
                self.registry.gauge_set(
                    "cook_user_resource", 0.0,
                    labels={"pool": pool_name, "user": user, "state": state,
                            "resource": dim})

    # ------------------------------------------------------------------- SLO
    def _publish_slo(self, slo_name: str, objective_s: float,
                     breach_ratio: float,
                     pool: Optional[str] = None,
                     extra: Optional[Dict[str, str]] = None) -> None:
        labels = {"slo": slo_name}
        if pool is not None:
            labels["pool"] = pool
        if extra:
            labels.update(extra)
        self.registry.gauge_set("cook_slo_objective_seconds", objective_s,
                                labels=labels)
        self.registry.gauge_set("cook_slo_breach_ratio", breach_ratio,
                                labels=labels)
        budget = max(self.slo.error_budget, 1e-9)
        self.registry.gauge_set("cook_slo_burn_rate", breach_ratio / budget,
                                labels=labels)

    def _sweep_queue_slo(self, pool_name: str, pending) -> None:
        """Pending-age distribution vs the queue-latency objective.  Ages
        are sampled at sweep time (a job still waiting counts against the
        SLO *now*, not only once it finally launches — the launch-time
        wait histogram is observed separately by the matcher).  The age
        basis is the CURRENT wait (last_waiting_start_ms, the same basis
        the store stamps queue_time_ms from): a retried job re-enters the
        queue with a fresh clock, it does not inherit hours of prior
        runtime as instant SLO breach."""
        now_ms = self.store.clock()
        ages = [(now_ms - (j.last_waiting_start_ms or j.submit_time_ms))
                / 1000.0 for j in pending]
        self.registry.observe_many("cook_queue_age_seconds", ages,
                                   labels={"pool": pool_name},
                                   buckets=LATENCY_BUCKETS)
        obj = self.slo.queue_latency_objective_s
        breach = sum(1 for a in ages if a > obj)
        ratio = breach / len(ages) if ages else 0.0
        self._publish_slo("queue-latency", obj, ratio, pool=pool_name)

    def _sweep_http_slo(self) -> None:
        """Per-endpoint request-latency burn rates off the serving
        plane's RED window (rest/instrument.py): each sweep drains the
        since-last-sweep per-endpoint (requests, over-objective) counts
        and publishes an ``endpoint-latency`` SLO series per endpoint
        template — the alerting surface ROADMAP item 1's admission
        batching will be judged against.  Endpoint labels are templates
        (bounded); quiet endpoints publish nothing this sweep."""
        from ..rest.instrument import request_log
        obj = self.slo.endpoint_latency_objective_s
        window = request_log.drain_slo_window()
        self._http_endpoints |= set(window)
        for endpoint in self._http_endpoints:
            count, breach = window.get(endpoint, (0, 0))
            # endpoints quiet since the last sweep publish a clean 0 —
            # same discipline as _sweep_queue_slo's every-pool publish
            self._publish_slo("endpoint-latency", obj,
                              breach / count if count else 0.0,
                              extra={"endpoint": endpoint})

    def _sweep_cycle_slo(self) -> None:
        """Cycle-duration burn rate over the flight recorder's recent
        window (fused/match cycles only — rank/rebalance cadences have
        their own budgets and would dilute the signal)."""
        from ..utils.flight import recorder
        obj = self.slo.cycle_duration_objective_s
        # kind-filtered BEFORE the window cut: rank/rebalance records
        # interleave with the match cadence and would otherwise silently
        # shrink the configured window
        durations = recorder.recent_durations(("fused", "match"),
                                              self.slo.cycle_window)
        breach = sum(1 for d in durations if d > obj * 1000.0)
        ratio = breach / len(durations) if durations else 0.0
        self._publish_slo("cycle-duration", obj, ratio)
