"""Executor heartbeat timeout tracker.

Mirrors the reference's heartbeat monitor (reference:
scheduler/src/cook/mesos/heartbeat.clj:66-147): executors/agents send
periodic liveness signals per task; a task silent for longer than the
timeout is presumed wedged (executor crashed but the node still reports it
running) and is killed with HEARTBEAT_LOST, which is mea-culpa — the
failure is the cluster's fault, so the user's retry budget is untouched
(reference: reason table mesos/reason.clj).

The reference tracks per-task timer channels; here a single dict of
last-beat timestamps swept on the reaper cadence is equivalent and
single-writer friendly.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class HeartbeatTracker:
    """Last-heartbeat bookkeeping with a sweep that returns expired tasks."""

    def __init__(self, timeout_ms: int = 60_000):
        self.timeout_ms = timeout_ms
        self._last: Dict[str, int] = {}
        self._lock = threading.Lock()

    def beat(self, task_id: str, now: int) -> None:
        """Record a liveness signal (progress frame, status update, or an
        explicit heartbeat message all count, matching the reference's
        'any framework message resets the timer' behavior).

        Only refreshes tasks already under watch: a stale signal arriving
        after the terminal status forgot the task must not re-track it
        (leak + spurious kill); ``watch`` is the sole insert point."""
        with self._lock:
            if task_id in self._last:
                self._last[task_id] = now

    def watch(self, task_id: str, now: int) -> None:
        """Start tracking a task at launch; the launch itself is the first
        beat so a slow-starting executor gets the full timeout."""
        with self._lock:
            self._last[task_id] = now

    def forget(self, task_id: str) -> None:
        with self._lock:
            self._last.pop(task_id, None)

    def last_beat(self, task_id: str) -> Optional[int]:
        with self._lock:
            return self._last.get(task_id)

    def expired(self, now: int) -> List[str]:
        """Task ids silent beyond the timeout. Does not forget them; the
        caller kills and the terminal status update cleans up."""
        with self._lock:
            return [t for t, ts in self._last.items()
                    if now - ts > self.timeout_ms]

    def tracked_count(self) -> int:
        with self._lock:
            return len(self._last)
