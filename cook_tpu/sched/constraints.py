"""Constraint compiler: the reference's constraint zoo lowered to a bool[J, H]
mask consumed by the match kernels.

The reference evaluates constraints as host predicates one task at a time
inside Fenzo (reference: scheduler/src/cook/scheduler/constraints.clj —
JobConstraint protocol :51, registry :459, fenzoized :466).  Here the common
constraints are *vectorized* over the jobs x hosts plane up front, which is
what lets the matcher stay a single jitted kernel (SURVEY.md section 7
"constraint extensibility on device"); anything truly dynamic (within-batch
group placement) is validated host-side post-match.

Implemented (reference locations):
  novel-host            constraints.clj:68   — never retry on a host that failed this job
  gpu-host              constraints.clj:122  — gpu jobs only on matching-gpu hosts, and
                                               non-gpu jobs never on gpu hosts
  disk-host             constraints.clj:164  — disk-type affinity
  user attribute EQUALS constraints.clj:356
  max-tasks-per-host    constraints.clj:433
  rebalancer-reservation constraints.clj:242 — reserved hosts only for their job
  checkpoint-locality   constraints.clj:218  — restarted checkpointed jobs pinned
                                               to their previous location attribute
  estimated-completion  constraints.clj:385  — don't place a job on a host
                                               expected to die before the
                                               job's estimated end time
  group unique-host / balanced / attribute-equals (running cotasks)
                        constraints.clj:586-676
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

import numpy as np

from ..cluster.base import Offer
from ..state.schema import (
    DISK_TYPE_LABEL,
    GPU_MODEL_LABEL,
    GroupPlacementType,
    Job,
)

LOCATION_ATTRIBUTE = "location"

# Topology coordinates hosts advertise for gang scheduling (docs/GANG.md):
# the slice a host belongs to and its position within it.  Gang groups
# request co-location by naming an attribute — usually SLICE_ATTRIBUTE —
# whose value must be equal across every member's host.
SLICE_ATTRIBUTE = "slice-id"
SLICE_POSITION_ATTRIBUTE = "slice-position"


def member_slots(avail4: np.ndarray, need4: np.ndarray,
                 cap: int) -> np.ndarray:
    """How many copies of a gang member's demand each host can hold,
    capped at ``cap`` (the gang size — more slots than members never
    changes a decision).  avail4 is [H,4] available cpus/mem/gpus/disk,
    need4 the member's [4] demand.  Zero-demand members fit everywhere
    (cap slots per host)."""
    pos = need4 > 0
    if not pos.any():
        return np.full(avail4.shape[0], cap, dtype=np.int64)
    fit = np.floor(avail4[:, pos] / need4[pos]).min(axis=1)
    return np.clip(fit, 0, cap).astype(np.int64)


@dataclass
class ConstraintContext:
    """Host-side facts the compiler needs beyond the job/offer lists."""

    # job uuid -> hostnames where a previous instance of this job failed
    failed_hosts: Dict[str, Set[str]] = field(default_factory=dict)
    # job uuid -> reserved hostname (rebalancer reservations,
    # rebalancer.clj:419-432, consumed at scheduler.clj:645-653)
    reserved_hosts: Dict[str, str] = field(default_factory=dict)
    # group uuid -> hostnames of *running* cotasks, WITH multiplicity (two
    # cotasks on one host count twice for BALANCED frequencies; unique-host
    # membership checks are unaffected). Any iterable works.
    group_running_hosts: Dict[str, List[str]] = field(default_factory=dict)
    # group uuid -> attribute value of running cotasks (attribute-equals)
    group_attr_values: Dict[str, str] = field(default_factory=dict)
    # group uuid -> Group entity (for placement type/attribute)
    groups: Dict[str, object] = field(default_factory=dict)
    # job uuid -> checkpoint location attribute value to pin to
    checkpoint_locations: Dict[str, str] = field(default_factory=dict)
    max_tasks_per_host: Optional[int] = None
    # hostname -> attribute map for hosts NOT in the current offer set
    # (running cotask hosts); offers take precedence
    host_attributes: Dict[str, Dict[str, str]] = field(default_factory=dict)
    # estimated-completion (constraints.clj:385): job uuid -> estimated end
    # time (epoch ms); hosts advertise "host-start-time" (epoch seconds) and
    # die host_lifetime_mins after it
    estimated_end_ms: Dict[str, int] = field(default_factory=dict)
    host_lifetime_mins: Optional[int] = None

    def host_attrs(self, hostname: str,
                   offer_attrs: Dict[str, Dict[str, str]]) -> Dict[str, str]:
        attrs = offer_attrs.get(hostname)
        return attrs if attrs is not None else \
            self.host_attributes.get(hostname, {})


def _balanced_ok(freqs: Dict[Optional[str], int], value: Optional[str],
                 minimum: int) -> bool:
    """balanced-host-placement evaluate (constraints.clj:600-627): placing on
    ``value`` keeps the group's spread over the attribute balanced; forcing
    minim to 0 while fewer than ``minimum`` distinct values are used pushes
    new tasks onto unused values first."""
    if not freqs:
        return True
    target_freq = freqs.get(value)
    if target_freq is None:
        return True
    minim = 0 if minimum > len(freqs) else min(freqs.values())
    maxim = max(freqs.values())
    return minim == maxim or target_freq < maxim


def build_constraint_mask(jobs: List[Job], offers: List[Offer],
                          ctx: ConstraintContext) -> np.ndarray:
    """Compile all active constraints into one bool[J, H] feasibility mask."""
    J, H = len(jobs), len(offers)
    mask = np.ones((J, H), dtype=bool)
    if J == 0 or H == 0:
        return mask

    host_gpu = np.array([o.capacity.gpus > 0 for o in offers], dtype=bool)
    host_gpu_model = np.array([o.gpu_model for o in offers], dtype=object)
    host_disk_type = np.array([o.disk_type for o in offers], dtype=object)
    host_names = [o.hostname for o in offers]
    host_index = {name: h for h, name in enumerate(host_names)}
    host_tasks = np.array([o.task_count for o in offers], dtype=np.int32)
    offer_attrs = {o.hostname: o.attributes for o in offers}

    # Attribute columns and (attr, value) equality masks are shared across
    # jobs; caching keeps the build O(unique-attrs x H) numpy instead of
    # O(J x H) Python (round-1 weak spot #3).
    attr_cols: Dict[str, np.ndarray] = {}
    eq_masks: Dict[tuple, np.ndarray] = {}

    def attr_col(attr: str) -> np.ndarray:
        col = attr_cols.get(attr)
        if col is None:
            col = np.array([o.attributes.get(attr) for o in offers],
                           dtype=object)
            attr_cols[attr] = col
        return col

    def cached_mask(key, compute) -> np.ndarray:
        m = eq_masks.get(key)
        if m is None:
            m = compute()
            eq_masks[key] = m
        return m

    def attr_equals(attr: str, value) -> np.ndarray:
        return cached_mask((attr, value), lambda: attr_col(attr) == value)

    # estimated-completion: epoch-ms each host is expected to die, +inf when
    # it doesn't advertise "host-start-time" (constraints.clj:392-399)
    host_death_ms = np.full(H, np.inf)
    if ctx.host_lifetime_mins is not None:
        for h, o in enumerate(offers):
            start = o.attributes.get("host-start-time")
            if start is not None:
                try:
                    host_death_ms[h] = (float(start) * 1000.0
                                        + ctx.host_lifetime_mins * 60_000.0)
                except (TypeError, ValueError):
                    pass  # unparseable attr: treat the host as immortal

    # hosts reserved for some job are off-limits to every other job;
    # precompute the reserved host indices + owners once
    reserved_idx, reserved_owner = [], []
    for owner_uuid, hname in ctx.reserved_hosts.items():
        h = host_index.get(hname)
        if h is not None:
            reserved_idx.append(h)
            reserved_owner.append(owner_uuid)
    reserved_idx = np.array(reserved_idx, dtype=np.int64)
    reserved_owner = np.array(reserved_owner, dtype=object)

    if ctx.max_tasks_per_host is not None:
        mask &= (host_tasks < ctx.max_tasks_per_host)[None, :]

    # group UNIQUE running-cotask host indices, computed once per group
    unique_group_idx: Dict[str, np.ndarray] = {}
    # gang group uuid -> member row indices (collected in the loop; the
    # topology-contiguity restriction runs after it, see below)
    gang_rows: Dict[str, List[int]] = {}

    for j, job in enumerate(jobs):
        row = mask[j]

        # novel-host: O(|failed|) lookups, not O(H)
        failed = ctx.failed_hosts.get(job.uuid)
        if failed:
            idx = [host_index[n] for n in failed if n in host_index]
            if idx:
                row[idx] = False

        # gpu-host: bidirectional isolation
        if job.resources.gpus > 0:
            row &= host_gpu
            wanted_model = job.labels.get(GPU_MODEL_LABEL)
            if wanted_model:
                row &= cached_mask(
                    ("~gpu-model", wanted_model),
                    lambda: host_gpu_model == wanted_model)
        else:
            row &= ~host_gpu

        # disk-type affinity
        wanted_disk = job.labels.get(DISK_TYPE_LABEL)
        if wanted_disk:
            row &= cached_mask(
                ("~disk-type", wanted_disk),
                lambda: host_disk_type == wanted_disk)

        # user-specified attribute constraints (EQUALS)
        for c in job.constraints:
            if c.operator.upper() == "EQUALS":
                row &= attr_equals(c.attribute, c.pattern)

        # estimated-completion: skip hosts dying before the job would finish
        est_end = ctx.estimated_end_ms.get(job.uuid)
        if est_end is not None and ctx.host_lifetime_mins is not None:
            row &= est_end < host_death_ms

        # checkpoint locality: pin to prior location
        loc = ctx.checkpoint_locations.get(job.uuid)
        if loc:
            row &= attr_equals(LOCATION_ATTRIBUTE, loc)

        # rebalancer reservations: block hosts reserved for OTHER jobs
        if reserved_idx.size:
            blocked = reserved_idx[reserved_owner != job.uuid]
            if blocked.size:
                row[blocked] = False

        # group placement vs RUNNING cotasks (within-batch handled post-match)
        if job.group is not None:
            group = ctx.groups.get(job.group)
            if getattr(group, "gang", False) \
                    and getattr(group, "gang_topology", None):
                gang_rows.setdefault(job.group, []).append(j)
            ptype = getattr(group, "placement_type", None)
            if ptype is GroupPlacementType.UNIQUE:
                idx = unique_group_idx.get(job.group)
                if idx is None:
                    running = ctx.group_running_hosts.get(job.group, ())
                    idx = np.array(
                        sorted({host_index[n] for n in set(running)
                                if n in host_index}), dtype=np.int64)
                    unique_group_idx[job.group] = idx
                if idx.size:
                    row[idx] = False
            elif ptype is GroupPlacementType.ATTRIBUTE_EQUALS:
                attr = getattr(group, "placement_attribute", None)
                if attr:
                    # allowed values: explicit pin, else the attribute values
                    # of hosts already running cotasks (constraints.clj:628)
                    want = ctx.group_attr_values.get(job.group)
                    allowed = {want} if want is not None else {
                        ctx.host_attrs(hn, offer_attrs).get(attr)
                        for hn in ctx.group_running_hosts.get(job.group, ())}
                    allowed.discard(None)
                    if allowed:
                        key = ("~in", job.group, attr)
                        m = eq_masks.get(key)
                        if m is None:
                            col = attr_col(attr)
                            m = np.zeros(H, dtype=bool)
                            for v in allowed:
                                m |= col == v
                            eq_masks[key] = m
                        row &= m
            elif ptype is GroupPlacementType.BALANCED:
                attr = getattr(group, "placement_attribute", None)
                minimum = getattr(group, "placement_minimum", 2) or 2
                if attr:
                    key = ("~balanced", job.group, attr)
                    m = eq_masks.get(key)
                    if m is None:
                        freqs: Dict[Optional[str], int] = {}
                        for hn in ctx.group_running_hosts.get(job.group, ()):
                            v = ctx.host_attrs(hn, offer_attrs).get(attr)
                            freqs[v] = freqs.get(v, 0) + 1
                        if freqs:
                            col = attr_col(attr)
                            ok = {v: _balanced_ok(freqs, v, minimum)
                                  for v in set(col.tolist())}
                            m = np.array([ok[v] for v in col.tolist()],
                                         dtype=bool)
                        else:
                            m = np.ones(H, dtype=bool)
                        eq_masks[key] = m
                    row &= m

    # gang topology-contiguity preference (docs/GANG.md): each gang with
    # a topology request is restricted to the topology domain (slice)
    # that can absorb the most members, so the match kernel packs
    # slice-local by construction — the gang reduction in ops/gang.py
    # then only enforces the invariant instead of fighting scattered
    # placements.  Domains are compared by member SLOT capacity, not
    # host count: the matcher packs several members onto a wide host,
    # so a 2-host slice of big machines may hold the whole gang while a
    # 3-host slice of small ones cannot — an argmax on hosts would
    # hard-pin the gang to the small slice every cycle and starve it.
    # Score = (holds the whole gang?, remaining slot capacity, feasible
    # host count); ties break on the lexicographically smallest value
    # (deterministic).
    # claimed[(attr, value)]: member slots earlier gangs in THIS batch
    # were already steered into a domain — without it, every gang
    # requesting the same attribute would pick the same argmax slice
    # (identical scores, identical tie-break) and deadlock on it while
    # other slices sit idle
    claimed: Dict[tuple, int] = {}
    if gang_rows:
        avail4 = np.array([[o.available.cpus, o.available.mem,
                            o.available.gpus, o.available.disk]
                           for o in offers], dtype=np.float32)
    for guuid, rows in gang_rows.items():
        group = ctx.groups[guuid]
        attr = group.gang_topology
        col = attr_col(attr)
        # ELASTIC gangs with members already RUNNING (the grow path,
        # docs/GANG.md elasticity) are pinned to the topology domain the
        # gang occupies — a grow member landing in a different slice
        # would violate the equality invariant the reduction no longer
        # checks for satisfied gangs.  Rigid gangs never grow, so this
        # is elastic-only and cannot perturb rigid decisions.
        from ..state.schema import gang_is_elastic
        if gang_is_elastic(group):
            run_vals = set()
            for hn in ctx.group_running_hosts.get(guuid, ()):
                h = host_index.get(hn)
                if h is not None:
                    run_vals.add(col[h])
                else:
                    v = ctx.host_attributes.get(hn, {}).get(attr)
                    if v is not None:
                        run_vals.add(v)
            run_vals.discard(None)
            if len(run_vals) == 1:
                mask[rows] &= (col == next(iter(run_vals)))[None, :]
                continue
        # size members by the elementwise-MAX demand across the gang and
        # gate hosts on EVERY member's constraint row: conservative for
        # heterogeneous gangs (may undercount capacity), but a domain
        # scored "holds the whole gang" really does — sizing by one
        # representative member would let a small member's demand pick a
        # domain its bigger sibling can never fit, pinning the gang
        # there every cycle
        need = np.max(np.array(
            [[jobs[j].resources.cpus, jobs[j].resources.mem,
              jobs[j].resources.gpus, jobs[j].resources.disk]
             for j in rows], dtype=np.float32), axis=0)
        slots = member_slots(avail4, need, cap=len(rows))
        feasible = np.logical_and.reduce(mask[rows], axis=0) & (slots > 0)
        values = sorted({v for v in col.tolist() if v is not None})
        best, best_score = None, None
        for v in values:
            dom = feasible & (col == v)
            cap = int(slots[dom].sum()) - claimed.get((attr, v), 0)
            score = (cap >= len(rows), cap, int(dom.sum()))
            if best_score is None or score > best_score:
                best, best_score = v, score
        if best is None:
            # no host advertises the requested attribute: the gang has
            # no topology domain to land in at all
            mask[rows] = False
        else:
            mask[rows] &= (col == best)[None, :]
            claimed[(attr, best)] = claimed.get((attr, best), 0) \
                + len(rows)
    return mask


def validate_group_placement(jobs: List[Job], assignments: np.ndarray,
                             offers: List[Offer],
                             ctx: ConstraintContext) -> np.ndarray:
    """Post-match within-batch group check: for UNIQUE groups, only the first
    (highest-ranked) cotask per host keeps its slot this cycle; for
    ATTRIBUTE_EQUALS with no running cotask yet, the first placed cotask
    fixes the attribute value for the rest of the batch.

    Returns the assignment vector with violators reset to -1 (they retry next
    cycle, like a Fenzo failure would).
    """
    out = assignments.copy()
    offer_attrs = {o.hostname: o.attributes for o in offers}
    group_hosts: Dict[str, Set[str]] = {
        g: set(hs) for g, hs in ctx.group_running_hosts.items()}
    group_attr: Dict[str, str] = dict(ctx.group_attr_values)
    # BALANCED: running attribute-value frequencies, updated as the batch
    # commits placements in rank order
    group_freqs: Dict[str, Dict[Optional[str], int]] = {}
    for g, hns in ctx.group_running_hosts.items():
        group = ctx.groups.get(g)
        if getattr(group, "placement_type", None) is GroupPlacementType.BALANCED:
            attr = getattr(group, "placement_attribute", None)
            if attr:
                freqs = group_freqs.setdefault(g, {})
                for hn in hns:
                    v = ctx.host_attrs(hn, offer_attrs).get(attr)
                    freqs[v] = freqs.get(v, 0) + 1
    for j, job in enumerate(jobs):
        h = int(out[j])
        if h < 0 or job.group is None:
            continue
        group = ctx.groups.get(job.group)
        ptype = getattr(group, "placement_type", None)
        hostname = offers[h].hostname
        if ptype is GroupPlacementType.UNIQUE:
            used = group_hosts.setdefault(job.group, set())
            if hostname in used:
                out[j] = -1
            else:
                used.add(hostname)
        elif ptype is GroupPlacementType.ATTRIBUTE_EQUALS:
            attr = getattr(group, "placement_attribute", None)
            if attr:
                val = offers[h].attributes.get(attr)
                fixed = group_attr.get(job.group)
                if fixed is None:
                    if val is not None:
                        group_attr[job.group] = val
                elif val != fixed:
                    out[j] = -1
        elif ptype is GroupPlacementType.BALANCED:
            attr = getattr(group, "placement_attribute", None)
            minimum = getattr(group, "placement_minimum", 2) or 2
            if attr:
                freqs = group_freqs.setdefault(job.group, {})
                val = offers[h].attributes.get(attr)
                if _balanced_ok(freqs, val, minimum):
                    freqs[val] = freqs.get(val, 0) + 1
                else:
                    out[j] = -1
    return out


# Constraint names follow the reference's Fenzo constraint class names so
# the unscheduled explainer's message table lines up
# (unscheduled.clj constraint-name->message).
def explain_placement_failure(job: Job, offers: List[Offer],
                              ctx: ConstraintContext,
                              avail: Optional[np.ndarray] = None) -> Dict:
    """Per-host failure census for ONE job: which resource dimensions and
    which constraints excluded how many hosts (reference:
    fenzo_utils.clj summarize-placement-failure — Fenzo reports
    AssignmentFailures per host; here each cause is recomputed as a
    vectorized mask over the offer axis).

    Returns {"resources": {dim: host_count}, "constraints": {name: count}}.
    Only called for under-investigation jobs, so host-side numpy is fine.
    """
    H = len(offers)
    out = {"resources": {}, "constraints": {}}
    if H == 0:
        return out
    if avail is None:
        avail = np.array([[o.available.cpus, o.available.mem,
                           o.available.gpus, o.available.disk]
                          for o in offers], dtype=np.float32)
    need = np.array([job.resources.cpus, job.resources.mem,
                     job.resources.gpus, job.resources.disk],
                    dtype=np.float32)
    for d, dim in enumerate(("cpus", "mem", "gpus", "disk")):
        n = int((avail[:, d] < need[d]).sum())
        if n:
            out["resources"][dim] = n

    def count(name: str, bad_mask: np.ndarray) -> None:
        n = int(np.asarray(bad_mask).sum())
        if n:
            out["constraints"][name] = n

    host_names = [o.hostname for o in offers]
    failed = ctx.failed_hosts.get(job.uuid) or set()
    count("novel_host_constraint",
          np.array([h in failed for h in host_names]))
    host_gpu = np.array([o.capacity.gpus > 0 for o in offers])
    if job.resources.gpus > 0:
        count("gpu_host_constraint", ~host_gpu)
        model = job.labels.get(GPU_MODEL_LABEL)
        if model:
            count("gpu_model_constraint",
                  host_gpu & np.array([o.gpu_model != model for o in offers]))
    else:
        count("non_gpu_host_constraint", host_gpu)
    disk = job.labels.get(DISK_TYPE_LABEL)
    if disk:
        count("disk_type_constraint",
              np.array([o.disk_type != disk for o in offers]))
    for c in job.constraints:
        if c.operator.upper() == "EQUALS":
            count(f"user_defined_constraint:{c.attribute}",
                  np.array([o.attributes.get(c.attribute) != c.pattern
                            for o in offers]))
    loc = ctx.checkpoint_locations.get(job.uuid)
    if loc:
        count("checkpoint_locality_constraint",
              np.array([o.attributes.get(LOCATION_ATTRIBUTE) != loc
                        for o in offers]))
    reserved_other = {h for u, h in ctx.reserved_hosts.items()
                      if u != job.uuid}
    count("rebalancer_reservation_constraint",
          np.array([h in reserved_other for h in host_names]))
    if ctx.max_tasks_per_host is not None:
        count("max_tasks_per_host_constraint",
              np.array([o.task_count >= ctx.max_tasks_per_host
                        for o in offers]))
    if job.group is not None:
        group = ctx.groups.get(job.group)
        ptype = getattr(group, "placement_type", None)
        running = ctx.group_running_hosts.get(job.group, ())
        if getattr(group, "gang", False) \
                and getattr(group, "gang_topology", None):
            # hosts outside every topology domain large enough for the
            # whole gang ("no slice of size K satisfies the request") —
            # sized in member SLOTS, matching the chooser: a slice of 2
            # wide hosts that each fit 2 members DOES hold a gang of 3
            attr = group.gang_topology
            size = int(getattr(group, "gang_size", 0) or 0)
            col = np.array([o.attributes.get(attr) for o in offers],
                           dtype=object)
            need4 = np.array([job.resources.cpus, job.resources.mem,
                              job.resources.gpus, job.resources.disk],
                             dtype=np.float32)
            slots = member_slots(avail, need4, cap=max(size, 1))
            ok_hosts = np.zeros(H, dtype=bool)
            for v in {x for x in col.tolist() if x is not None}:
                sel = col == v
                if int(slots[sel].sum()) >= size:
                    ok_hosts |= sel
            count("gang_topology_constraint", ~ok_hosts)
        if ptype is GroupPlacementType.UNIQUE:
            count("unique_host_constraint",
                  np.array([h in set(running) for h in host_names]))
        elif ptype is GroupPlacementType.ATTRIBUTE_EQUALS:
            attr = getattr(group, "placement_attribute", None)
            if attr:
                offer_attrs = {o.hostname: o.attributes for o in offers}
                want = ctx.group_attr_values.get(job.group)
                allowed = {want} if want is not None else {
                    ctx.host_attrs(hn, offer_attrs).get(attr)
                    for hn in running}
                allowed.discard(None)
                if allowed:
                    count("attribute-equals-host-placement-group-constraint",
                          np.array([o.attributes.get(attr) not in allowed
                                    for o in offers]))
        elif ptype is GroupPlacementType.BALANCED:
            attr = getattr(group, "placement_attribute", None)
            minimum = getattr(group, "placement_minimum", 2) or 2
            if attr:
                offer_attrs = {o.hostname: o.attributes for o in offers}
                freqs: Dict[Optional[str], int] = {}
                for hn in running:
                    v = ctx.host_attrs(hn, offer_attrs).get(attr)
                    freqs[v] = freqs.get(v, 0) + 1
                if freqs:
                    count("balanced-host-placement-group-constraint",
                          np.array([not _balanced_ok(
                              freqs, o.attributes.get(attr), minimum)
                              for o in offers]))
    return out
