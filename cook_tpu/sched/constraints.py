"""Constraint compiler: the reference's constraint zoo lowered to a bool[J, H]
mask consumed by the match kernels.

The reference evaluates constraints as host predicates one task at a time
inside Fenzo (reference: scheduler/src/cook/scheduler/constraints.clj —
JobConstraint protocol :51, registry :459, fenzoized :466).  Here the common
constraints are *vectorized* over the jobs x hosts plane up front, which is
what lets the matcher stay a single jitted kernel (SURVEY.md section 7
"constraint extensibility on device"); anything truly dynamic (within-batch
group placement) is validated host-side post-match.

Implemented (reference locations):
  novel-host            constraints.clj:68   — never retry on a host that failed this job
  gpu-host              constraints.clj:122  — gpu jobs only on matching-gpu hosts, and
                                               non-gpu jobs never on gpu hosts
  disk-host             constraints.clj:164  — disk-type affinity
  user attribute EQUALS constraints.clj:356
  max-tasks-per-host    constraints.clj:433
  rebalancer-reservation constraints.clj:242 — reserved hosts only for their job
  checkpoint-locality   constraints.clj:218  — restarted checkpointed jobs pinned
                                               to their previous location attribute
  group unique-host / attribute-equals (running cotasks)
                        constraints.clj:586-676
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

import numpy as np

from ..cluster.base import Offer
from ..state.schema import GroupPlacementType, Job

GPU_MODEL_LABEL = "gpu-model"
DISK_TYPE_LABEL = "disk-type"
LOCATION_ATTRIBUTE = "location"


@dataclass
class ConstraintContext:
    """Host-side facts the compiler needs beyond the job/offer lists."""

    # job uuid -> hostnames where a previous instance of this job failed
    failed_hosts: Dict[str, Set[str]] = field(default_factory=dict)
    # job uuid -> reserved hostname (rebalancer reservations,
    # rebalancer.clj:419-432, consumed at scheduler.clj:645-653)
    reserved_hosts: Dict[str, str] = field(default_factory=dict)
    # group uuid -> hostnames of *running* cotasks
    group_running_hosts: Dict[str, Set[str]] = field(default_factory=dict)
    # group uuid -> attribute value of running cotasks (attribute-equals)
    group_attr_values: Dict[str, str] = field(default_factory=dict)
    # group uuid -> Group entity (for placement type/attribute)
    groups: Dict[str, object] = field(default_factory=dict)
    # job uuid -> checkpoint location attribute value to pin to
    checkpoint_locations: Dict[str, str] = field(default_factory=dict)
    max_tasks_per_host: Optional[int] = None


def build_constraint_mask(jobs: List[Job], offers: List[Offer],
                          ctx: ConstraintContext) -> np.ndarray:
    """Compile all active constraints into one bool[J, H] feasibility mask."""
    J, H = len(jobs), len(offers)
    mask = np.ones((J, H), dtype=bool)
    if J == 0 or H == 0:
        return mask

    host_gpu = np.array([o.capacity.gpus > 0 for o in offers], dtype=bool)
    host_gpu_model = [o.gpu_model for o in offers]
    host_disk_type = [o.disk_type for o in offers]
    host_names = [o.hostname for o in offers]
    host_tasks = np.array([o.task_count for o in offers], dtype=np.int32)

    # hosts reserved for some job are off-limits to every other job
    reserved_by = {h: u for u, h in ctx.reserved_hosts.items()}

    if ctx.max_tasks_per_host is not None:
        mask &= (host_tasks < ctx.max_tasks_per_host)[None, :]

    for j, job in enumerate(jobs):
        row = mask[j]

        # novel-host
        failed = ctx.failed_hosts.get(job.uuid)
        if failed:
            for h, name in enumerate(host_names):
                if name in failed:
                    row[h] = False

        # gpu-host: bidirectional isolation
        if job.resources.gpus > 0:
            row &= host_gpu
            wanted_model = job.labels.get(GPU_MODEL_LABEL)
            if wanted_model:
                row &= np.array([m == wanted_model for m in host_gpu_model])
        else:
            row &= ~host_gpu

        # disk-type affinity
        wanted_disk = job.labels.get(DISK_TYPE_LABEL)
        if wanted_disk:
            row &= np.array([d == wanted_disk for d in host_disk_type])

        # user-specified attribute constraints (EQUALS)
        for c in job.constraints:
            if c.operator.upper() == "EQUALS":
                row &= np.array([o.attributes.get(c.attribute) == c.pattern
                                 for o in offers])

        # checkpoint locality: pin to prior location
        loc = ctx.checkpoint_locations.get(job.uuid)
        if loc:
            row &= np.array([o.attributes.get(LOCATION_ATTRIBUTE) == loc
                             for o in offers])

        # rebalancer reservations
        for h, name in enumerate(host_names):
            owner = reserved_by.get(name)
            if owner is not None and owner != job.uuid:
                row[h] = False

        # group placement vs RUNNING cotasks (within-batch handled post-match)
        if job.group is not None:
            group = ctx.groups.get(job.group)
            ptype = getattr(group, "placement_type", None)
            if ptype is GroupPlacementType.UNIQUE:
                running = ctx.group_running_hosts.get(job.group, set())
                for h, name in enumerate(host_names):
                    if name in running:
                        row[h] = False
            elif ptype is GroupPlacementType.ATTRIBUTE_EQUALS:
                attr = getattr(group, "placement_attribute", None)
                want = ctx.group_attr_values.get(job.group)
                if attr and want is not None:
                    row &= np.array([o.attributes.get(attr) == want
                                     for o in offers])
    return mask


def validate_group_placement(jobs: List[Job], assignments: np.ndarray,
                             offers: List[Offer],
                             ctx: ConstraintContext) -> np.ndarray:
    """Post-match within-batch group check: for UNIQUE groups, only the first
    (highest-ranked) cotask per host keeps its slot this cycle; for
    ATTRIBUTE_EQUALS with no running cotask yet, the first placed cotask
    fixes the attribute value for the rest of the batch.

    Returns the assignment vector with violators reset to -1 (they retry next
    cycle, like a Fenzo failure would).
    """
    out = assignments.copy()
    group_hosts: Dict[str, Set[str]] = {
        g: set(hs) for g, hs in ctx.group_running_hosts.items()}
    group_attr: Dict[str, str] = dict(ctx.group_attr_values)
    for j, job in enumerate(jobs):
        h = int(out[j])
        if h < 0 or job.group is None:
            continue
        group = ctx.groups.get(job.group)
        ptype = getattr(group, "placement_type", None)
        hostname = offers[h].hostname
        if ptype is GroupPlacementType.UNIQUE:
            used = group_hosts.setdefault(job.group, set())
            if hostname in used:
                out[j] = -1
            else:
                used.add(hostname)
        elif ptype is GroupPlacementType.ATTRIBUTE_EQUALS:
            attr = getattr(group, "placement_attribute", None)
            if attr:
                val = offers[h].attributes.get(attr)
                fixed = group_attr.get(job.group)
                if fixed is None:
                    if val is not None:
                        group_attr[job.group] = val
                elif val != fixed:
                    out[j] = -1
    return out
