"""Rank cycle: store entities -> DRU-ordered pending queue per pool.

The host half of the reference's rank path (reference: rank-jobs
scheduler.clj:2262, sort-jobs-by-dru-pool :2159, sort-jobs-by-dru-helper
:2073): gather running+pending per user in the user's task order, hand the
tensors to the rank kernel (or the CPU fallback), map the ranked order back
to Job entities, then apply the pool/quota-group global caps.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import Config, PoolQuota
from ..ops import host_prep, reference_impl
from ..state.schema import DruMode, Instance, Job, job_usage
from ..state.store import Store

F32 = np.float32
_PENDING_START = float(2**62)  # stands in for "no start time yet" (MAX)


def _job_feature_key(job: Job, inst: Optional[Instance]) -> Tuple:
    """Per-user task order (reference: tools.clj task->feature-vector
    :614-632): running-before-pending via start-time, then priority desc,
    then stable ids."""
    start = inst.start_time_ms if inst is not None else _PENDING_START
    return (-job.priority, start, job.submit_time_ms, job.uuid)


def build_user_tasks(pending: List[Job],
                     running: List[Tuple[Job, Instance]]
                     ) -> Tuple[List[reference_impl.UserTasks], Dict[int, Job]]:
    """Group tasks by user in comparator order; ids index into id2job."""
    per_user: Dict[str, List[Tuple[Tuple, Job, bool]]] = {}
    for job, inst in running:
        per_user.setdefault(job.user, []).append(
            (_job_feature_key(job, inst), job, False))
    for job in pending:
        per_user.setdefault(job.user, []).append(
            (_job_feature_key(job, None), job, True))
    uts: List[reference_impl.UserTasks] = []
    id2job: Dict[int, Job] = {}
    tid = 0
    for user, entries in per_user.items():
        entries.sort(key=lambda e: e[0])
        ids, rows, pend = [], [], []
        for _key, job, is_pending in entries:
            ids.append(tid)
            id2job[tid] = job
            rows.append([job.resources.cpus, job.resources.mem,
                         job.resources.gpus, 1.0])
            pend.append(is_pending)
            tid += 1
        uts.append(reference_impl.UserTasks(
            user, ids, np.array(rows, dtype=F32), pend))
    return uts, id2job


def _quota_vec(q: Dict[str, float]) -> np.ndarray:
    return np.array([q.get("cpus", np.inf), q.get("mem", np.inf),
                     q.get("gpus", np.inf), q.get("count", np.inf)], dtype=F32)


def _pool_quota_vec(q: PoolQuota) -> np.ndarray:
    return np.array([q.cpus, q.mem, q.gpus, q.count], dtype=F32)


def build_user_tables(store: Store, pool_name: str, users) -> tuple:
    """Per-user share/quota tables in segment order — the compact wire
    form's U-sized control arrays, gathered on device via user_rank.
    ONE builder shared by the fused pack and the columnar rank path so
    the two decision-identical paths cannot drift."""
    share_mat = np.stack([
        np.array([store.get_share(u, pool_name).get(d, np.inf)
                  for d in ("cpus", "mem", "gpus")], dtype=F32)
        for u in users]) if users else np.full((1, 3), np.inf, dtype=F32)
    quota_mat = np.stack([
        _quota_vec(store.get_quota(u, pool_name)) for u in users]) \
        if users else np.full((1, 4), np.inf, dtype=F32)
    return share_mat, quota_mat


class RankedQueue:
    """Lazy ranked queue: uuids + resource columns from the columnar index;
    Job entities are materialized only for the prefix a consumer actually
    touches (the matcher's considerable prefix, the REST /queue page, the
    rebalancer's top-N) — never the whole 1M-job queue (VERDICT r1 weak #4).

    Duck-types the List[Job] surface the cycle consumers use: len, bool,
    iteration, indexing and slicing (a slice returns materialized Jobs)."""

    def __init__(self, store: Store, uuids: np.ndarray,
                 resources: np.ndarray, users: Optional[np.ndarray] = None,
                 rows: Optional[np.ndarray] = None, rows_fn=None,
                 n: Optional[int] = None):
        """With ``rows`` given, ``uuids``/``resources``/``users`` are BASE
        columns and the queue is their ``rows`` selection, gathered lazily:
        the production cycle publishes a ~100k-row queue every cycle, and
        consumers that only touch a prefix (matcher, /queue page) should
        not pay three full-column gathers per cycle.

        ``rows_fn`` defers the row selection itself: the fused cycle keeps
        the rank-ordered queue rows DEVICE-resident and fetches them only
        when a consumer touches the queue (the device->host link is the
        production cycle's scarcest resource over a tunneled chip).  The
        callable returns the absolute base rows; ``n`` (required with
        ``rows_fn``) is the queue length, known without fetching."""
        self.store = store
        self._rows = rows
        self._rows_fn = rows_fn
        self._uuids = uuids
        self._resources = resources  # f32[n, 4] in ranked order
        self._users = users
        if rows_fn is not None:
            if n is None:
                raise ValueError("rows_fn requires an explicit n")
            self._n = int(n)
        else:
            self._n = len(uuids) if rows is None else len(rows)
        # materialization guard: the queue is read concurrently by the
        # rebalancer thread and REST handlers; an unguarded lazy gather
        # would let a reader observe half-swapped columns
        self._mat_lock = __import__("threading").Lock()

    def _resolve_rows(self) -> None:
        """Run the deferred device fetch (caller holds _mat_lock)."""
        if self._rows_fn is not None:
            self._rows = self._rows_fn()
            self._rows_fn = None

    @property
    def uuids(self) -> np.ndarray:
        with self._mat_lock:
            self._resolve_rows()
            if self._rows is not None:
                rows = self._rows
                uuids = self._uuids[rows]
                users = (np.zeros(self._n, dtype="<U64")
                         if self._users is None else self._users[rows])
                resources = self._resources[rows]
                # publish fully-formed columns, then drop rows last
                self._uuids, self._users, self._resources = \
                    uuids, users, resources
                self._rows = None
            return self._uuids

    @property
    def resources(self) -> np.ndarray:
        self.uuids  # materialize
        return self._resources

    @property
    def users(self) -> np.ndarray:
        self.uuids  # materialize
        return self._users if self._users is not None \
            else np.zeros(self._n, dtype="<U64")

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def _uuid_at(self, i):
        """uuid(s) at queue position(s) without materializing the whole
        selection (a prefix touch stays O(prefix))."""
        with self._mat_lock:
            self._resolve_rows()
            if self._rows is not None:
                return self._uuids[self._rows[i]]
            return self._uuids[i]

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [j for j in (self.store.job(u) for u in self._uuid_at(i))
                    if j is not None]
        return self.store.job(self._uuid_at(i))

    def __iter__(self):
        for u in self.uuids:
            job = self.store.job(u)
            if job is not None:  # completed/killed since the rank snapshot
                yield job

    def filtered(self, keep: np.ndarray) -> "RankedQueue":
        return RankedQueue(self.store, self.uuids[keep],
                           self.resources[keep], self.users[keep])


class Ranker:
    """Per-pool DRU ranking with kernel/fallback dispatch."""

    def __init__(self, store: Store, config: Config, backend: str = "tpu"):
        self.store = store
        self.config = config
        self.backend = backend
        # device-resident res/disk base mirror for the compact rank wire
        # form (ops/delta.DeviceBaseMirror), created on first columnar rank
        self._mirror = None

    def reset_device_state(self) -> None:
        """Drop the rank path's device base mirror (device failure /
        degraded cycle): its sync is keyed on the compaction epoch, so
        after a device restart it would keep handing out dead buffers
        until the next index compaction."""
        self._mirror = None

    def rank_pool(self, pool_name: str,
                  dru_mode: DruMode = DruMode.DEFAULT) -> List[Job]:
        if self.backend != "cpu" and self.config.columnar_index:
            return self._rank_pool_columnar(pool_name, dru_mode)
        pending = self.store.pending_jobs(pool_name)
        running = self.store.running_instances(pool_name)
        if not pending:
            return []
        uts, id2job = build_user_tasks(pending, running)
        shares = {ut.user: tuple(
            self.store.get_share(ut.user, pool_name).get(d, np.inf)
            for d in ("cpus", "mem", "gpus")) for ut in uts}
        quotas = {ut.user: _quota_vec(self.store.get_quota(ut.user, pool_name))
                  for ut in uts}
        gpu_mode = dru_mode is DruMode.GPU

        if self.backend == "cpu":
            ranked_ids = [tid for tid, _dru in reference_impl.rank_by_dru(
                uts, shares, quotas, gpu_mode=gpu_mode,
                max_over_quota_jobs=self.config.max_over_quota_jobs)]
        else:
            import jax.numpy as jnp
            from ..ops import rank_kernel
            from ..ops.dru import RankInputs
            arrays, task_ids = host_prep.pack_rank_inputs(uts, shares, quotas)
            res = rank_kernel(
                RankInputs(**{k: jnp.asarray(v) for k, v in arrays.items()}),
                gpu_mode=gpu_mode,
                max_over_quota_jobs=self.config.max_over_quota_jobs)
            n = int(res.num_ranked)
            ranked_ids = [task_ids[i] for i in np.asarray(res.order)[:n]]

        ranked = [id2job[t] for t in ranked_ids]
        return self._apply_pool_quota(pool_name, ranked, running)

    # -- columnar fast path (state/index.py; VERDICT r1 weak #4) -----------
    def _rank_pool_columnar(self, pool_name: str, dru_mode: DruMode):
        """Rank straight off the incrementally-maintained columnar index:
        no entity deep-copies, no per-task Python on the hot path — and
        since ISSUE 7, no [T]-sized host staging either: the per-task
        upload is the sorted row permutation + one flags byte
        (ops/dru.CompactRankInputs), usage is gathered on device from the
        resident base mirror, shares/quota ride per-USER tables, and the
        ranked queue is a lazy selection over the index's base snapshots
        (no full uuid/user unicode gathers)."""
        import jax.numpy as jnp
        from ..ops import CompactRankInputs, bucket, rank_kernel_compact
        from ..ops import telemetry
        from ..ops.delta import DeviceBaseMirror, pack_flags

        idx = self.store.ensure_index()
        snap = idx.fused_arrays(pool_name, compact=True)
        if snap is None:
            return RankedQueue(self.store, np.zeros(0, dtype="<U36"),
                               np.zeros((0, 4), dtype=F32))
        arrays, rows_s, users = snap.arrays, snap.rows_s, snap.users
        T = rows_s.size
        share_mat, quota_mat = build_user_tables(self.store, pool_name,
                                                 users)
        flags = pack_flags(arrays["pending"], arrays["valid"],
                           arrays["is_first"])
        TB = bucket(T)
        rows_p = np.zeros(TB, dtype=np.int32)
        rows_p[:T] = rows_s
        flags_p = np.zeros(TB, dtype=np.uint8)  # padding: valid=False
        flags_p[:T] = flags
        UB = bucket(max(len(users), 1), minimum=8)
        shares_u = np.full((UB, 3), np.inf, dtype=F32)
        shares_u[:share_mat.shape[0]] = share_mat
        quota_u = np.full((UB, 4), np.inf, dtype=F32)
        quota_u[:quota_mat.shape[0]] = quota_mat
        if self._mirror is None:
            self._mirror = DeviceBaseMirror()
        res_dev, _disk_dev = self._mirror.sync(
            snap.res_base, snap.disk_base, snap.compactions)
        telemetry.count_transfer(
            "h2d", rows_p.nbytes + flags_p.nbytes + shares_u.nbytes
            + quota_u.nbytes)
        res = rank_kernel_compact(
            CompactRankInputs(rows=jnp.asarray(rows_p),
                              flags=jnp.asarray(flags_p),
                              res_base=res_dev,
                              shares_u=jnp.asarray(shares_u),
                              quota_u=jnp.asarray(quota_u)),
            gpu_mode=dru_mode is DruMode.GPU,
            max_over_quota_jobs=self.config.max_over_quota_jobs)
        n = int(res.num_ranked)
        with telemetry.sync_wait("rank.order"):
            order = np.asarray(res.order[:n])
        telemetry.count_transfer("d2h", order.nbytes)
        queue = RankedQueue(self.store, snap.uuid_base, snap.res_base,
                            snap.user_base, rows=rows_s[order])
        return self._apply_pool_quota_columnar(pool_name, queue)

    def _apply_pool_quota_columnar(self, pool_name: str,
                                   queue: RankedQueue) -> RankedQueue:
        """Pool + quota-group caps over columns (scheduler.clj:2134-2157)."""
        cfg = self.config
        quota = cfg.pool_quota(pool_name)
        group_name = cfg.quota_groups.get(pool_name)
        group_quota = cfg.quota_group_quotas.get(group_name) \
            if group_name else None
        if quota is None and group_quota is None or not len(queue):
            return queue
        idx = self.store.ensure_index()
        keep = np.ones(len(queue), dtype=bool)
        if quota is not None:
            keep &= reference_impl.filter_pool_quota(
                queue.resources, idx.pool_usage_base(pool_name),
                _pool_quota_vec(quota))
        if group_quota is not None:
            group_base = np.zeros(4, dtype=F32)
            for member, g in cfg.quota_groups.items():
                if g == group_name:
                    group_base += idx.pool_usage_base(member)
            keep &= reference_impl.filter_pool_quota(
                queue.resources, group_base, _pool_quota_vec(group_quota))
        return queue.filtered(keep)

    # -- pool + quota-group caps (reference: filter-based-on-quota
    #    scheduler.clj:2134-2157) ------------------------------------------
    def _apply_pool_quota(self, pool_name: str, ranked: List[Job],
                          running: List[Tuple[Job, Instance]]) -> List[Job]:
        cfg = self.config
        quota = cfg.pool_quota(pool_name)
        group_name = cfg.quota_groups.get(pool_name)
        group_quota = cfg.quota_group_quotas.get(group_name) if group_name else None
        if quota is None and group_quota is None:
            return ranked

        job_use = np.array(
            [[j.resources.cpus, j.resources.mem, j.resources.gpus, 1.0]
             for j in ranked], dtype=F32)
        base = np.zeros(4, dtype=F32)
        for job, _inst in running:
            base += [job.resources.cpus, job.resources.mem,
                     job.resources.gpus, 1.0]
        keep = np.ones(len(ranked), dtype=bool)
        if quota is not None:
            keep &= reference_impl.filter_pool_quota(
                job_use, base, _pool_quota_vec(quota))
        if group_quota is not None:
            # aggregate usage across the group's member pools
            group_base = np.zeros(4, dtype=F32)
            for member, g in cfg.quota_groups.items():
                if g != group_name:
                    continue
                for job, _inst in self.store.running_instances(member):
                    group_base += [job.resources.cpus, job.resources.mem,
                                   job.resources.gpus, 1.0]
            keep &= reference_impl.filter_pool_quota(
                job_use, group_base, _pool_quota_vec(group_quota))
        return [j for j, k in zip(ranked, keep) if k]
