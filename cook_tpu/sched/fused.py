"""Fused production cycle driver: every eligible pool's rank + admission +
match in ONE device dispatch, host applies assignments transactionally.

This is the production form of the reference's per-pool match-cycle
architecture (reference: scheduler/src/cook/scheduler/scheduler.clj
:2398-2517 make-pool-handler round-robin; rank cycle :2286-2296) re-drawn
for a device mesh: instead of a host loop over pools with a device round
trip per pool, the host packs all pools' entities into stacked padded
tensors, dispatches the jitted pool-sharded cycle
(parallel/sharded.make_pool_cycle), and walks the returned assignment
vectors to run the transactional launch path (guard txn -> kill-lock ->
cluster launch, scheduler.clj:1028).

Host-side responsibilities that stay host-side (each feeds the kernel a
mask or cap instead of a Python loop over the hot path):
  - plugin launch verdicts (arbitrary host predicates) -> launch_ok
  - offensive-job stifling (scheduler.clj:2205-2257)   -> enqueue_ok
  - launch-rate token budgets                          -> tokens
  - head-of-queue backoff (scheduler.clj:1613-1651)    -> num_considerable
  - pool / quota-group caps (scheduler.clj:2125-2157)  -> pool_quota,
    group_quota + on-device all_gather of running usage
  - within-batch group placement + the launch transaction stay host-side
    post-kernel (they mutate store state).

Pools are grouped by DRU mode (default|gpu — a static of the kernel) and
stacked per group; task/host axes are padded to shared buckets so shapes
recur and XLA reuses the compiled cycle.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..cluster.base import Offer
from ..config import Config
from ..ops import host_prep
from ..ops import telemetry
from ..ops.delta import (
    FLAG_ENQUEUE_OK,
    FLAG_LAUNCH_OK,
    FLAG_PENDING,
    FLAG_USER_FIRST,
    FLAG_VALID,
)
from ..ops.padding import bucket, pad_to
from ..state.schema import DruMode, Job, Pool, SchedulerKind
from ..state.store import Store
from ..utils import audit as _audit
from ..utils import tracing
from ..utils.flight import recorder as _flight
from .constraints import build_constraint_mask, validate_group_placement
from .matcher import MatchCycleResult, Matcher, _BackoffState
from .ranker import build_user_tasks, _quota_vec, _pool_quota_vec

F32 = np.float32
INF = float("inf")


class _PackedPool:
    """Host-side staging for one pool's cycle inputs."""

    def __init__(self, pool: Pool):
        self.pool = pool
        self.task_ids: List[int] = []
        self.id2job: Dict[int, Job] = {}
        # columnar mode: kernel rows map to job uuids instead of entities.
        # uuid/user/res come as index BASE snapshots + the sorted absolute
        # rows (rows_s); sorted-position lookups go through
        # base[rows_s[pos]] so no full string column is ever gathered
        self.columnar = False
        self.rows_s: Optional[np.ndarray] = None        # i64[T] sorted rows
        self.uuid_base: Optional[np.ndarray] = None     # U36[n] by row
        self.user_base: Optional[np.ndarray] = None     # U64[n] by row
        self.res_base: Optional[np.ndarray] = None      # f32[n, 4] by row
        # structured-mask form (columnar mode; parallel/sharded
        # StructuredPoolCycleInputs): no dense [T, H] mask is ever built
        self.host_gpu: Optional[np.ndarray] = None      # bool[H]
        self.host_blocked: Optional[np.ndarray] = None  # bool[H]
        self.exc_id: Optional[np.ndarray] = None        # i32[T]
        self.exc_mask: Optional[np.ndarray] = None      # bool[E, H]
        self.offers: List[Offer] = []
        self.ctx = None
        self.arrays: Dict[str, np.ndarray] = {}
        self.job_res = None
        self.cmask = None
        self.avail = None
        # overdraft-adjusted availability (pipelined driver only): set by
        # the reconciler when an overlapped cycle consumed capacity this
        # pack's staged avail never saw; the gang rescue/refill places
        # against it instead of pp.avail
        self.avail_headroom: Optional[np.ndarray] = None  # f32[H, 4]
        self.capacity = None
        self.enqueue_ok = None
        self.launch_ok = None
        self.tokens = None
        # compact wire form (CompactPoolCycleInputs): per-user tables +
        # packed admission flags; the device expands them (expand_compact)
        self.compact = False
        self.shares_u: Optional[np.ndarray] = None      # f32[U, 3]
        self.quota_u: Optional[np.ndarray] = None       # f32[U, 4]
        self.tokens_u: Optional[np.ndarray] = None      # f32[U]
        self.flags: Optional[np.ndarray] = None         # u8[T]
        self.disk_base: Optional[np.ndarray] = None     # f32[n] by row
        self.base_compactions = -1   # index compaction epoch at pack
        self.exc_rows: Optional[np.ndarray] = None      # i32[n_exc]
        self.num_considerable = 0
        self.pool_quota = np.full(4, INF, dtype=F32)
        self.group_quota = np.full(4, INF, dtype=F32)
        self.group_id = -1
        self.offensive: List[Job] = []
        self.n_tasks = 0
        self.n_hosts = 0
        # megakernel gang wire (ops/gang.build_gang_wire): per-task gang
        # segments staged PRE-dispatch so the kernel's fused gang stage
        # reduces in-launch; plus the pack-time satisfied-elastic set the
        # apply path compares against before trusting the fused verdicts
        self.gang_wire = None
        self.gang_satisfied: frozenset = frozenset()


class _StagedCycle:
    """Phase-1 (stage) output: one cycle's packed pools, grouped by DRU
    mode and ready for dispatch."""

    __slots__ = ("pools", "groups")

    def __init__(self, pools: List[Pool]):
        self.pools = pools
        self.groups: List["_StagedGroup"] = []


class _StagedGroup:
    """One DRU-mode group's staged kernel inputs (host arrays already
    stacked/padded; uploaded by dispatch_group).  With ``resident`` the
    rows/flags fields are the device-resident buffers (already synced by
    the delta scatter — dispatch_group must not re-account them as
    upload bytes)."""

    __slots__ = ("gpu_mode", "group", "inp", "structured", "cap", "T", "H",
                 "stage_ms", "resident", "mega", "mega_fallback")

    def __init__(self, *, gpu_mode, group, inp, structured, cap, T, H,
                 stage_ms, resident=False, mega=None):
        self.gpu_mode = gpu_mode
        self.group = group
        self.inp = inp
        self.structured = structured
        self.cap = cap
        self.T = T
        self.H = H
        self.stage_ms = stage_ms
        self.resident = resident
        # megakernel dispatch payload (ops/pallas_cycle.MegaCycleWire +
        # negotiated codec tags + the wire-rebuild thunk the fused-XLA
        # fallback uses); None = XLA cycle.  ``mega_fallback`` marks a
        # group re-dispatched after a Pallas failure (its h2d was
        # already charged for the wire)
        self.mega = mega
        self.mega_fallback = False


class _GroupDispatch:
    """An in-flight device dispatch of one staged group: the kernel result
    refs plus the compact-output refs whose async device->host copies are
    already rolling.  ``fetched`` holds the host arrays after
    fetch_group."""

    __slots__ = ("sg", "res", "outs", "fetched")

    def __init__(self, sg: _StagedGroup, res, outs):
        self.sg = sg
        self.res = res
        self.outs = outs
        self.fetched = None


class _ResidentPack:
    """One DRU-mode group's device-resident wire arrays: the [P, T] rows
    permutation + flags bytes living on device across cycles, plus the
    host shadow the per-cycle diff runs against.  ``key`` pins the group
    composition and bucket shape; ``epoch`` the index compaction epoch
    the row ids are valid in."""

    __slots__ = ("key", "epoch", "rows_dev", "flags_dev", "rows_host",
                 "flags_host")

    def __init__(self, key, epoch, rows_dev, flags_dev, rows_host,
                 flags_host):
        self.key = key
        self.epoch = epoch
        self.rows_dev = rows_dev
        self.flags_dev = flags_dev
        self.rows_host = rows_host
        self.flags_host = flags_host


class FusedCycleDriver:
    def __init__(self, store: Store, config: Config, matcher: Matcher,
                 plugins, rate_limits, mesh=None, shard_id=None):
        self.store = store
        self.config = config
        self.matcher = matcher
        self.plugins = plugins
        self.rate_limits = rate_limits
        self._mesh = mesh
        # sharded-controller mode (ISSUE 19): this process owns ONE mesh
        # shard, so the [P, ...] pool-stacked arrays it builds cover only
        # its partition's pools and its resident buffers are committed
        # per-PROCESS — the mesh it runs on must be this shard's local
        # device slice, never a pool mesh spanning other shards' pools
        # (mesh() enforces this)
        self.shard_id = shard_id
        self._cycles: Dict[Tuple, object] = {}
        # device-resident mirror of the columnar index's immutable res/disk
        # base columns: rows append-only while the compaction epoch is
        # unchanged, so steady-state cycles upload only the NEW rows
        # (ops/delta.DeviceBaseMirror, shared with the columnar rank path)
        from ..ops.delta import DeviceBaseMirror, PackDeltaApplier
        self._mirror = DeviceBaseMirror()
        # device-RESIDENT pack (ISSUE 7 tentpole): the stacked [P, T]
        # rows/flags wire arrays live in device buffers across cycles,
        # keyed by DRU mode; each stage diffs the freshly built host
        # arrays against the shadow and scatter-applies just the delta
        self._resident: Dict[bool, _ResidentPack] = {}
        self._applier = PackDeltaApplier()
        # quiet-pool fast path: the index's tx-event delta feed
        # (state/index.py attach_pack_consumer) tells the pack when a
        # pool saw zero churn since its last pack, letting it reuse the
        # cached [T]-sized arrays wholesale instead of rebuilding them
        self._delta_cid: Optional[int] = None
        self._pack_cache: Dict[str, Dict] = {}
        # sticky quantized-wire scales (ops/quant.py): the negotiated
        # fixed-point scale tuples are STATIC jit keys of the
        # megakernel, so they persist across cycles while they still
        # round-trip (renegotiation only on an exactness miss)
        self._mega_scales: Dict[str, tuple] = {}

    # ------------------------------------------------------------------ mesh
    def mesh(self):
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh

            from ..parallel.mesh import POOL_AXIS
            self._mesh = Mesh(np.array(jax.devices()[:1]), (POOL_AXIS,))
        if self.shard_id is not None and self._mesh.size > 1:
            # one partition = one process = one mesh shard: a shard
            # worker driving a multi-device pool mesh would commit
            # resident buffers for pools OTHER processes own —
            # double-owned device state, the exact split-brain the boot
            # alignment check (parallel.mesh.validate_shard_alignment)
            # exists to refuse
            from ..parallel.mesh import ShardAlignmentError
            raise ShardAlignmentError(
                f"controller shard {self.shard_id} was given a "
                f"{self._mesh.size}-device pool mesh: a shard process "
                "commits resident buffers for ITS pools only; give each "
                "shard its local device slice")
        return self._mesh

    def _cycle_fn(self, gpu_mode: bool, considerable_cap: int,
                  structured: bool = False, compact: bool = False):
        key = (id(self.mesh()), gpu_mode, self.config.max_over_quota_jobs,
               considerable_cap, structured, compact)
        fn = self._cycles.get(key)
        if fn is None:
            from ..parallel.sharded import make_pool_cycle
            fn = telemetry.instrument_jit("fused.pool_cycle", make_pool_cycle(
                self.mesh(), gpu_mode=gpu_mode,
                max_over_quota_jobs=self.config.max_over_quota_jobs,
                considerable_cap=considerable_cap, structured=structured,
                compact=compact))
            self._cycles[key] = fn
        return fn

    # --------------------------------------------------------------- warmup
    def warmup(self, *, tasks: int, hosts: int, users: int = 8,
               sweep: bool = False, gpu: bool = False) -> int:
        """Boot-time cold-start killer (config.PipelineConfig): compile
        AND execute once, with zeroed inputs, the compact fused cycle at
        the bucket grid the configured design point implies, so the
        16.5 s first-call compile spikes (BENCH_r05) land at boot — inside
        the leader's takeover window — and never inside a live cycle.
        Executing (not just AOT-lowering) populates the jit call cache,
        so steady-state cycles at warmed shapes trace zero times; with
        the persistent compilation cache enabled the XLA compile itself
        is also disk-cached across restarts.

        ``sweep=True`` warms every (T, H) bucket up to the targets (ramp
        traffic hits warm executables at every scale), else just the
        target buckets.  Returns the number of warmup executions."""
        if tasks <= 0 or hosts <= 0:
            return 0
        if not self.config.columnar_index:
            # warmup covers the production compact/columnar wire form
            # only; silently "warming" the wrong kernel variant would
            # spend boot time and still compile inside the first live
            # cycle (docs/PERFORMANCE.md)
            import logging
            logging.getLogger(__name__).warning(
                "fused-cycle warmup skipped: columnar_index=False packs "
                "the dense PoolCycleInputs variant, which warmup does "
                "not cover")
            return 0
        import jax
        import jax.numpy as jnp

        from ..parallel.sharded import CompactPoolCycleInputs

        def grid(n: int, minimum: int = 64) -> List[int]:
            top = bucket(n, minimum=minimum)
            if not sweep:
                return [top]
            out, b = [], minimum
            while b <= top:
                out.append(b)
                b *= 2
            return out

        P = self.mesh().size
        U = bucket(max(users, 1), minimum=8)
        E = 8  # exception bucket floor: no complex jobs in the zero world
        # the dispatch cap is bucket(max matcher cap over the group's
        # pools); pool_matchers overrides can bucket differently from the
        # default, so warm every DISTINCT cap bucket
        caps = {bucket(self.config.default_matcher.max_jobs_considered)}
        caps.update(bucket(mc.max_jobs_considered)
                    for _rx, mc in self.config.pool_matchers)
        f32, i32 = jnp.float32, jnp.int32
        runs = 0
        for gm in ((False, True) if gpu else (False,)):
            for T in grid(tasks):
                # the device base mirror's capacity bucket tracks the
                # index row count (~T at one pool per index row)
                mir = bucket(T, minimum=1024)
                res_base = jnp.zeros((mir, 4), dtype=f32)
                disk_base = jnp.zeros(mir, dtype=f32)
                for H in grid(hosts):
                    inp = CompactPoolCycleInputs(
                        rows=jnp.zeros((P, T), dtype=i32),
                        flags=jnp.zeros((P, T), dtype=jnp.uint8),
                        res_base=res_base,
                        disk_base=disk_base,
                        tokens_u=jnp.full((P, U), jnp.inf, dtype=f32),
                        shares_u=jnp.full((P, U, 3), jnp.inf, dtype=f32),
                        quota_u=jnp.full((P, U, 4), jnp.inf, dtype=f32),
                        num_considerable=jnp.zeros((P,), dtype=i32),
                        pool_quota=jnp.full((P, 4), jnp.inf, dtype=f32),
                        group_quota=jnp.full((P, 4), jnp.inf, dtype=f32),
                        group_id=jnp.full((P,), -1, dtype=i32),
                        host_gpu=jnp.zeros((P, H), dtype=bool),
                        host_blocked=jnp.ones((P, H), dtype=bool),
                        exc_rows=jnp.full((P, E), -1, dtype=i32),
                        exc_mask=jnp.zeros((P, E, H), dtype=bool),
                        avail=jnp.zeros((P, H, 4), dtype=f32),
                        capacity=jnp.zeros((P, H, 4), dtype=f32))
                    for cap in sorted({min(c, T) for c in caps}):
                        fn = self._cycle_fn(gm, cap, True, compact=True)
                        jax.block_until_ready(fn(inp).n_queue)
                        runs += 1
                    mega_backends = {self.config.default_matcher.backend}
                    mega_backends.update(
                        mc.backend for _rx, mc in self.config.pool_matchers)
                    if self.mesh().size == 1 and (
                            "tpu-megakernel" in mega_backends
                            or ("auto" in mega_backends
                                and jax.default_backend() == "tpu")):
                        # warm the MEGAKERNEL executables too (the live
                        # path for this config): wide rows for the
                        # resident wire, i8-delta for the quantized
                        # rebuild norm.  Residual cold traces remain for
                        # the first negotiated fixed-point scale tuple
                        # and the first gang-bearing bucket — sticky
                        # scales make each a one-time cost.
                        from ..ops import pallas_cycle
                        from ..ops import quant as _quant
                        gang = pallas_cycle.empty_gang_wire(P, T, H)
                        host_bits = jnp.zeros((P, 2, (H + 7) // 8),
                                              dtype=jnp.uint8)
                        codecs = [(jnp.int32, _quant.ROWS_WIDE)]
                        if self.config.quantized_wire:
                            codecs.append((jnp.int8, _quant.ROWS_I8))
                        for rdt, rcodec in codecs:
                            wire = pallas_cycle.MegaCycleWire(
                                rows=jnp.zeros((P, T), dtype=rdt),
                                flags=inp.flags, res_base=inp.res_base,
                                disk_base=inp.disk_base,
                                tokens_u=inp.tokens_u,
                                shares_u=inp.shares_u,
                                quota_u=inp.quota_u,
                                num_considerable=inp.num_considerable,
                                pool_quota=inp.pool_quota,
                                group_quota=inp.group_quota,
                                group_id=inp.group_id,
                                host_bits=host_bits,
                                exc_rows=inp.exc_rows,
                                exc_mask=inp.exc_mask,
                                avail=inp.avail, capacity=inp.capacity,
                                gang_id=jnp.asarray(gang[0]),
                                gang_size=jnp.asarray(gang[1]),
                                gang_attr=jnp.asarray(gang[2]),
                                host_topo=jnp.asarray(gang[3]))
                            for cap in sorted({min(c, T) for c in caps}):
                                jax.block_until_ready(
                                    pallas_cycle.megacycle(
                                        wire, gpu_mode=gm,
                                        max_over_quota_jobs=self.config
                                        .max_over_quota_jobs,
                                        considerable_cap=cap,
                                        rows_codec=rcodec).n_queue)
                                runs += 1
                if self.config.resident_pack:
                    # the resident pack's delta scatter compiles once per
                    # (buffer shape+sharding, delta bucket): warm every
                    # bucket up to the buffer size so a steady-state
                    # delta never traces inside a live cycle (the
                    # zero-recompile guarantee the warmup assertion
                    # protects).  The warm buffers must carry the SAME
                    # placement as the live resident buffers — jit keys
                    # executables on input sharding, so an unsharded warm
                    # pass would leave the sharded variant cold
                    from ..ops.delta import _DELTA_MIN_BUCKET
                    n_flat = P * T
                    kbs, k = set(), _DELTA_MIN_BUCKET
                    while k < n_flat:
                        kbs.add(k)
                        k *= 2
                    kbs.add(n_flat)  # the clamped top bucket
                    if self.mesh().size > 1:
                        from ..parallel.mesh import pool_sharding
                        sh = pool_sharding(self.mesh())
                        rows_b = jax.device_put(
                            np.zeros((P, T), dtype=np.int32), sh)
                        flags_b = jax.device_put(
                            np.zeros((P, T), dtype=np.uint8), sh)
                    else:
                        rows_b = jnp.zeros((P, T), dtype=i32)
                        flags_b = jnp.zeros((P, T), dtype=jnp.uint8)
                    for k in sorted(kbs):
                        idx = np.full(k, n_flat, dtype=np.int32)  # no-op
                        rows_b, flags_b = self._applier.apply(
                            rows_b, flags_b, idx,
                            np.zeros(k, dtype=np.int32),
                            np.zeros(k, dtype=np.uint8))
                        if self.config.quantized_wire:
                            # warm the narrow value codecs too (i8 via
                            # zero deltas, i16 via an out-of-i8 delta):
                            # all-sentinel indices make them no-op
                            # scatters, so the buffers stay zeros
                            for vals in (np.zeros(k, dtype=np.int32),
                                         np.full(k, 1000,
                                                 dtype=np.int32)):
                                rows_b, flags_b = self._applier.apply(
                                    rows_b, flags_b, idx, vals,
                                    np.zeros(k, dtype=np.uint8),
                                    quantize=True)
                    jax.block_until_ready(rows_b)
        return runs

    # ---------------------------------------------------------- base mirror
    def _sync_base_mirror(self, res_base: np.ndarray, disk_base: np.ndarray,
                          compactions: int):
        """Bring the device base mirror up to the snapshot (see
        ops/delta.DeviceBaseMirror): full (re)upload on a compaction
        epoch change or capacity overflow, else one bucketed chunk append
        of the rows added since the last cycle."""
        return self._mirror.sync(res_base, disk_base, compactions)

    # ------------------------------------------------------- resident pack
    def reset_resident(self) -> None:
        """Drop ALL device-resident state — the rows/flags pack, the
        quiet-pool cache, and the res/disk base mirror — so the next
        stage rebuilds from scratch (leader handoff, degraded cycle,
        tests).  The mirror must go too: after a device failure its
        buffers live on the failed device state, and its compaction-epoch
        key would otherwise keep handing them out forever.  Safe at any
        time — residency is a pure mirror of what the next full pack
        would build."""
        from ..ops.delta import DeviceBaseMirror
        self._resident.clear()
        self._pack_cache.clear()
        self._mirror = DeviceBaseMirror()

    def _sync_resident(self, gpu_mode: bool, key: Tuple, rows_p: np.ndarray,
                       flags_p: np.ndarray, epoch: int):
        """Bring the resident [P, T] rows/flags device buffers up to the
        freshly staged host arrays: steady state diffs against the host
        shadow (delta EXTRACTION — native/pack.cpp when built) and
        dispatches the jitted scatter (ops/delta.PackDeltaApplier) of
        just the changed positions; a compaction-epoch fence, group/
        bucket reshape, or kernel fault forces a clean full upload
        (``cook_resident_repack_total{reason=}``).  Returns
        (rows_dev, flags_dev)."""
        from ..native import pack as native_pack
        from ..utils.faults import injector as _faults
        from ..utils.metrics import registry
        st = self._resident.get(gpu_mode)
        reason = None
        if st is None:
            reason = "cold"
        elif st.key != key:
            reason = "shape"
        elif st.epoch != epoch:
            reason = "compaction"
        if reason is None:
            try:
                _faults.fire("delta.extract")
                idx = native_pack.pack_diff(st.rows_host, rows_p,
                                            st.flags_host, flags_p)
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "resident-pack delta extraction failed; full repack")
                registry.counter_inc("cook_kernel_fallback",
                                     labels={"kernel": "delta.extract"})
                _flight.note_fault("kernel.dispatch-fallback")
                reason = "fault"
            else:
                k = int(idx.size)
                if k == 0:
                    _flight.note_delta(0)
                    return st.rows_dev, st.flags_dev
                # a scatter pair costs ~9 B/row vs ~5 B/row for the full
                # upload: past roughly half the table the repack is the
                # cheaper transfer AND skips the scatter dispatch
                if 2 * k > rows_p.size:
                    reason = "oversize"
                else:
                    try:
                        with tracing.span("delta.apply", rows=k,
                                          gpu=gpu_mode):
                            _faults.fire("delta.apply")
                            flat = rows_p.reshape(-1)
                            fflat = flags_p.reshape(-1)
                            # stage (h2d starts on fresh buffers) then
                            # commit (scatter dispatch): under the
                            # pipelined driver this whole block runs in
                            # cycle k+1's STAGE phase while cycle k's
                            # kernel is still in flight, so the delta
                            # bytes move during compute
                            staged = self._applier.stage(
                                tuple(st.rows_dev.shape), idx,
                                flat[idx], fflat[idx],
                                quantize=bool(
                                    self.config.quantized_wire))
                            rows_dev, flags_dev = self._applier.commit(
                                st.rows_dev, st.flags_dev, staged)
                    except Exception:
                        import logging
                        logging.getLogger(__name__).exception(
                            "resident-pack delta apply failed; full repack")
                        registry.counter_inc(
                            "cook_kernel_fallback",
                            labels={"kernel": "delta.apply"})
                        _flight.note_fault("kernel.dispatch-fallback")
                        reason = "fault"
                    else:
                        registry.counter_inc("cook_delta_rows", float(k))
                        _flight.note_delta(k)
                        st.rows_dev, st.flags_dev = rows_dev, flags_dev
                        st.rows_host, st.flags_host = rows_p, flags_p
                        return rows_dev, flags_dev
        import jax.numpy as jnp
        registry.counter_inc("cook_resident_repack",
                             labels={"reason": reason})
        _flight.note_repack(reason)
        telemetry.count_transfer("h2d", rows_p.nbytes + flags_p.nbytes)
        mesh = self.mesh()
        if mesh.size > 1:
            # each pool shard owns its own resident buffer slice: commit
            # the [P, T] arrays with the pool-axis sharding the cycle's
            # shard_map expects (parallel/mesh.pool_sharding)
            import jax
            from ..parallel.mesh import pool_sharding
            sh = pool_sharding(mesh)
            rows_dev = jax.device_put(rows_p, sh)
            flags_dev = jax.device_put(flags_p, sh)
        else:
            rows_dev = jnp.asarray(rows_p)
            flags_dev = jnp.asarray(flags_p)
        self._resident[gpu_mode] = _ResidentPack(
            key, epoch, rows_dev, flags_dev, rows_p, flags_p)
        return rows_dev, flags_dev

    # ------------------------------------------------------------------ pack
    def _pack_pool_columnar(self, scheduler, pool: Pool, exclude=None,
                            token_delta=None) -> Optional[_PackedPool]:
        """Pack one pool's cycle inputs straight off the columnar index
        (state/index.py): no entity materialization for the plain-job
        majority — entities are fetched only for rows the vectorized path
        can't decide (user constraints, groups, checkpoint, prior
        instances; see index._is_complex) and for the offensive minority.
        This closes the 'fused cycle packs from entities' gap tracked in
        docs/PARITY.md; decision parity with the entity pack is asserted by
        tests/test_fused_cycle.py."""
        store, cfg = self.store, self.config
        idx = store.ensure_index()
        # ONE snapshot of the reservations: the rebalancer thread mutates
        # reserved_hosts concurrently, and every later read in this pack
        # (owner rows, host blocks, local owners) must see the same set
        resv = dict(scheduler.reserved_hosts)
        # tx-event delta feed (state/index.py attach_pack_consumer): one
        # drain per pack.  A quiet pool — zero journaled rows, no fence —
        # reuses its cached [T]-sized pack products wholesale instead of
        # rebuilding them (the incremental-view-maintenance fast path;
        # ineligible shapes fall through to the full rebuild below)
        if self._delta_cid is None:
            self._delta_cid = idx.attach_pack_consumer()
        delta = idx.pack_delta(self._delta_cid, pool.name)
        cached = self._pack_cache.get(pool.name)
        if (cached is not None and not delta.fence
                and delta.rows.size == 0
                and delta.epoch == cached["epoch"]
                and delta.version == cached["version"]
                and not self.plugins.launch_filters
                and not self._resv_owner_in_pack(idx, resv, cached)):
            return self._pack_pool_cached(scheduler, pool, cached, resv,
                                          exclude=exclude,
                                          token_delta=token_delta)
        self._pack_cache.pop(pool.name, None)
        snap = idx.fused_arrays(pool.name, owner_uuids=list(resv),
                                compact=True)
        if snap is None:
            return None
        arrays, rows_s = snap.arrays, snap.rows_s
        uuid_base, complex_rows, owner_rows = \
            snap.uuid_base, snap.complex_s, snap.owner_rows
        users = snap.users
        pp = _PackedPool(pool)
        pp.columnar = True
        pp.rows_s = rows_s
        pp.uuid_base, pp.user_base, pp.res_base = \
            uuid_base, snap.user_base, snap.res_base
        # device-resident base mirror inputs: NO per-task resource columns
        # are gathered on the host at all (expand_compact gathers the
        # res/disk base by rows on device)
        pp.disk_base = snap.disk_base
        pp.base_compactions = snap.compactions
        # sorted-position -> uuid, via the base snapshot (no full gather)
        uuid_at = lambda sel: uuid_base[rows_s[sel]]
        T = rows_s.size
        pp.arrays, pp.n_tasks = arrays, T
        pend = arrays["pending"]
        pp.compact = True

        # per-user share/quota TABLES: the kernel gathers them on device via
        # user_rank (CompactPoolCycleInputs), so the host never broadcasts
        # ~32 B/task of user data into [T]-sized columns
        pp.shares_u, pp.quota_u = self._user_tables(pool, users)

        host_index = self._pack_offers(pp, scheduler, pool)
        offers = pp.offers
        if offers:
            H = len(offers)
            reserved_idx = [host_index[hn]
                            for hn in resv.values()
                            if hn in host_index]
            pp.host_blocked[reserved_idx] = True
            # exception rows = complex jobs + reservation owners (owners
            # must punch through the blanket reserved-host block; owners
            # whose reserved host serves another pool need no exception)
            is_exc = pend & complex_rows
            local_owners = [u for u, hn in resv.items()
                            if hn in host_index]
            if local_owners:
                # int row-membership test against rows resolved under the
                # SAME index lock hold as rows_s (a post-snapshot rows_for
                # could race a compaction's row remap); a string isin would
                # re-gather the full uuid column this pack is built to avoid
                local_rows = np.array(
                    [owner_rows[u] for u in local_owners
                     if u in owner_rows], dtype=np.int64)
                is_exc |= pend & np.isin(rows_s, local_rows)
            cjobs, keep = [], []
            for i in np.flatnonzero(is_exc):
                job = store.job(str(uuid_at(i)))
                if job is not None:
                    cjobs.append(job)
                    keep.append(i)
            crow = np.array(keep, dtype=np.int64)
            ctx = self.matcher._constraint_context(
                cjobs, resv)
            self.matcher._fill_cotask_host_attributes(
                ctx, pool.name, offers, scheduler.clusters)
            pp.ctx = ctx
            if cjobs:
                # the compiler emits COMPLETE rows (gpu isolation,
                # max-tasks, reservations included), so an exception row
                # fully replaces the base
                pp.exc_mask = build_constraint_mask(cjobs, offers, ctx)
                pp.exc_rows = crow.astype(np.int32)
            else:
                pp.exc_mask = np.zeros((1, H), dtype=bool)
                pp.exc_rows = np.zeros(0, dtype=np.int32)

        # offensive-job filter: vectorized over the BASE columns (the
        # compact pack gathers no per-task resource columns), then one
        # [T] bool gather by rows
        enqueue_ok = np.ones(T, dtype=bool)
        limits = cfg.offensive_job_limits
        if limits is not None:
            res_b = snap.res_base
            bad_base = ((res_b[:, 1] > limits.memory_gb * 1024.0)
                        | (res_b[:, 0] > limits.cpus))
            bad = pend & bad_base[rows_s]
            if bad.any():
                enqueue_ok[bad] = False
                pp.offensive = [j for j in (store.job(str(u))
                                            for u in uuid_at(bad))
                                if j is not None]
                # one gather over the existing wire arrays attributes the
                # aggregate to job uuids (utils/audit.py)
                _audit.note_skips(store.audit,
                                  {"offensive": list(uuid_at(bad))},
                                  pool=pool.name)
        pp.enqueue_ok = enqueue_ok

        # plugin launch verdicts: only when a filter is configured, and the
        # per-uuid verdict cache is consulted before materializing an
        # entity (plugins/launch.clj caches accept/defer the same way), so
        # steady state costs no deep copies even with filters on
        launch_ok = np.ones(T, dtype=bool)
        if self.plugins.launch_filters:
            for i in np.flatnonzero(pend):
                uuid = str(uuid_at(i))
                cached = self.plugins.launch_verdict_cached(uuid)
                if cached is None:
                    job = store.job(uuid)
                    if job is None:
                        # vanished-but-still-indexed uuid: cache a synthetic
                        # accept so the next cycle stays copy-free instead
                        # of re-missing and re-fetching forever.  Short TTL:
                        # if the uuid re-materializes (store swap race) the
                        # real filters re-run within seconds, not 60s
                        self.plugins.cache_launch_verdict(uuid, True,
                                                          ttl_s=5.0)
                        cached = True
                    else:
                        cached = self.plugins.launch_allowed(job)
                if not cached:
                    launch_ok[i] = False
            filtered = ~launch_ok
            if filtered.any():
                _audit.note_skips(
                    store.audit,
                    {"launch-filtered": list(uuid_at(filtered))},
                    pool=pool.name)
        # pipelined-driver speculation mask (sched/pipeline.py): rows the
        # in-flight overlapped cycle is about to launch are withheld from
        # THIS cycle's launch candidates (they'd conflict at reconcile).
        # Row ids are only valid within one index compaction epoch; on a
        # mismatch the mask is skipped and reconciliation catches the
        # conflicts instead (rare: compaction between two packs).
        spec_masked = None
        if exclude is not None:
            kind, epoch, rows = exclude
            if kind == "rows" and epoch == snap.compactions and len(rows):
                masked = pend & np.isin(rows_s, rows)
                if masked.any():
                    launch_ok = launch_ok & ~masked
                    spec_masked = masked
                    _audit.note_skips(
                        store.audit,
                        {"pipeline-speculative": list(uuid_at(masked))},
                        pool=pool.name)
        pp.launch_ok = launch_ok

        # launch-rate token budgets per USER (device gathers via user_rank)
        launch_rl = self.rate_limits.job_launch
        pp.tokens_u = self._tokens_u(pool, users, token_delta)

        # gang-cohort admission: every gang member is a complex row, so
        # the materialized exception jobs carry the full cohorts
        gang_members: Dict[str, List] = {}
        if pp.ctx is not None and len(pp.exc_rows):
            for i, job in zip(pp.exc_rows, cjobs):
                if pend[i] and job.group is not None and getattr(
                        pp.ctx.groups.get(job.group), "gang", False):
                    gang_members.setdefault(job.group, []).append(
                        (int(i), job))
        tok_by_user = dict(zip(users, pp.tokens_u.tolist()))
        satisfied = self._gang_cohort_admission(
            pool, pp.ctx.groups if pp.ctx is not None else {},
            gang_members, launch_ok,
            (lambda u: tok_by_user.get(u, 0.0))
            if launch_rl.enforce else None,
            spec_masked=spec_masked)
        if gang_members and self._pool_mega_candidate(pool.name):
            # megakernel gang wire: the same membership the host pass
            # would derive from the candidates, staged pre-dispatch so
            # the fused gang stage reduces in-launch (ops/pallas_cycle).
            # Built only when this pool can actually dispatch mega —
            # the O(T) wire would otherwise be allocated every cycle
            # just to be dropped
            from ..ops.gang import build_gang_wire
            pp.gang_wire = build_gang_wire(
                T, gang_members,
                pp.ctx.groups if pp.ctx is not None else {}, pp.offers,
                satisfied=satisfied)
            pp.gang_satisfied = frozenset(satisfied or ())

        # the admission bools + user-segment boundaries, packed into one
        # wire byte per task (user_rank/first_idx re-derive on device)
        from ..ops.delta import pack_flags
        pp.flags = pack_flags(pend, arrays["valid"], arrays["is_first"],
                              enqueue_ok=enqueue_ok, launch_ok=launch_ok)

        # quiet-pool cache (the delta-feed fast path above): only shapes
        # with no entity-coupled rows are reusable wholesale — no COMPLEX
        # pending rows (their constraint masks depend on entities the
        # event feed doesn't cover; checked against the snapshot, NOT
        # pp.exc_rows, which is only populated when offers exist — an
        # offer-less cycle must not cache a constrained job as maskless),
        # no offensive rows (their stifle kills are in flight), no launch
        # filters (verdict TTLs live outside the index).  Reservations
        # per se are fine: their blanket host blocks are re-applied per
        # cycle by the fast path, and an OWNER entering this pool's
        # pending set is re-checked against the live map on every reuse
        if (not self.plugins.launch_filters and not pp.offensive
                and not (pend & complex_rows).any()):
            flags0 = pp.flags
            if spec_masked is not None and spec_masked.any():
                # cache the PRE-speculation flags: the in-flight footprint
                # changes every cycle and is re-patched by the fast path
                flags0 = flags0.copy()
                flags0[spec_masked] |= np.uint8(FLAG_LAUNCH_OK)
            self._pack_cache[pool.name] = {
                "epoch": snap.compactions, "version": delta.version,
                "rows_s": rows_s, "pend": pend, "flags0": flags0,
                "users": users, "uuid_base": uuid_base,
                "user_base": snap.user_base, "res_base": snap.res_base,
                "disk_base": snap.disk_base}

        self._pack_caps(pp, pool)
        return pp

    def _pack_offers(self, pp: _PackedPool, scheduler, pool: Pool
                     ) -> Optional[Dict[str, int]]:
        """Per-cycle offer staging shared by the full pack and the
        quiet-pool fast path: breaker-filtered offers (a tripped cluster
        contributes none, so the kernel routes demand at healthy
        clusters) plus the STRUCTURED per-host base vectors — gpu
        isolation and max-tasks blocking (constraints.clj:122,433; see
        parallel/sharded.StructuredPoolCycleInputs) — and the
        avail/capacity stacks.  Returns hostname -> index for the full
        path's reservation/exception handling (None when no offers; the
        empty-offer fallback shapes are set here so the two paths can
        never diverge)."""
        cfg = self.config
        offers: List[Offer] = []
        for cluster in scheduler.launchable_clusters(pool.name):
            offers.extend(cluster.pending_offers(pool.name))
        pp.offers = offers
        pp.n_hosts = len(offers)
        if not offers:
            pp.host_gpu = np.zeros(1, dtype=bool)
            pp.host_blocked = np.ones(1, dtype=bool)
            pp.exc_rows = np.zeros(0, dtype=np.int32)
            pp.exc_mask = np.zeros((1, 1), dtype=bool)
            pp.avail = np.zeros((1, 4), dtype=F32)
            pp.capacity = np.zeros((1, 4), dtype=F32)
            return None
        H = len(offers)
        pp.host_gpu = np.array([o.capacity.gpus > 0 for o in offers],
                               dtype=bool)
        host_tasks = np.array([o.task_count for o in offers],
                              dtype=np.int32)
        host_blocked = np.zeros(H, dtype=bool)
        if cfg.max_tasks_per_host is not None:
            host_blocked |= host_tasks >= cfg.max_tasks_per_host
        pp.host_blocked = host_blocked
        pp.avail = np.array(
            [[o.available.cpus, o.available.mem, o.available.gpus,
              o.available.disk] for o in offers], dtype=F32)
        pp.capacity = np.array(
            [[o.capacity.cpus, o.capacity.mem, o.capacity.gpus,
              o.capacity.disk] for o in offers], dtype=F32)
        return {o.hostname: h for h, o in enumerate(offers)}

    def _user_tables(self, pool: Pool, users: List[str]
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """Per-user share/quota tables in segment order (shared by the
        full pack, the quiet-pool fast path, and — via the same module
        function — the columnar rank path)."""
        from .ranker import build_user_tables
        return build_user_tables(self.store, pool.name, users)

    def _tokens_u(self, pool: Pool, users: List[str],
                  token_delta) -> np.ndarray:
        """Per-user launch-rate token budgets, net of the pipelined
        driver's in-flight spends (shared by both pack paths)."""
        launch_rl = self.rate_limits.job_launch
        if not launch_rl.enforce:
            return np.full(max(len(users), 1), INF, dtype=F32)
        from ..policy import pool_user_key
        tokens = np.array(
            [launch_rl.get_token_count(pool_user_key(pool.name, u))
             for u in users], dtype=F32)
        if token_delta:
            # tokens an overlapped in-flight cycle will spend at its
            # apply (the limiter hasn't seen the spends yet)
            tokens = np.maximum(tokens - np.array(
                [token_delta.get(u, 0.0) for u in users], dtype=F32), 0.0)
        return tokens

    def _resv_owner_in_pack(self, idx, resv: Dict, c: Dict) -> bool:
        """True when a reservation OWNER is one of the cached pack's
        pending rows: owners need an exception-mask punch-through, which
        only the full pack builds.  Plain reservations (owner elsewhere)
        stay fast-path compatible — their blanket host blocks are
        per-cycle state applied by _pack_pool_cached."""
        if not resv:
            return False
        owner_rows = idx.rows_for(list(resv))
        if not owner_rows.size:
            return False
        return bool(np.isin(owner_rows, c["rows_s"][c["pend"]]).any())

    def _pack_pool_cached(self, scheduler, pool: Pool, c: Dict,
                          resv: Dict, exclude=None,
                          token_delta=None) -> _PackedPool:
        """Quiet-pool fast path: the index's delta feed reported zero
        churn since this pool's last pack, so the [T]-sized pack products
        (sorted rows, admission flags) are reused WHOLESALE — no index
        snapshot, no order repair, no flags rebuild, no O(T) host work.
        Only the per-user tables, offers, reserved-host blocks, caps,
        and the pipelined driver's speculative mask are rebuilt per
        cycle; the mask is a bit-patch over the cached flags, which the
        resident pack then ships as a device-side scatter delta — an
        in-flight footprint is never a repack (ISSUE 7 tentpole (d)).

        Eligibility was checked by the caller + at cache time: no
        reservation OWNERS pending in this pool, no launch filters, and
        the cached pack had no exception or offensive rows — so
        exceptions are empty and enqueue/launch verdicts are all-accept
        by construction."""
        pp = _PackedPool(pool)
        pp.columnar = True
        pp.compact = True
        rows_s = c["rows_s"]
        pp.rows_s = rows_s
        pp.uuid_base, pp.user_base = c["uuid_base"], c["user_base"]
        pp.res_base, pp.disk_base = c["res_base"], c["disk_base"]
        pp.base_compactions = c["epoch"]
        T = rows_s.size
        pp.n_tasks = T
        pend = c["pend"]
        users = c["users"]
        pp.shares_u, pp.quota_u = self._user_tables(pool, users)

        host_index = self._pack_offers(pp, scheduler, pool)
        if host_index is not None:
            # blanket reserved-host blocks are per-cycle state, applied
            # here exactly as the full path does (owners needing the
            # punch-through exception forced a full rebuild upstream)
            reserved_idx = [host_index[hn] for hn in resv.values()
                            if hn in host_index]
            pp.host_blocked[reserved_idx] = True
            # eligibility guarantees no exception rows; the empty ctx
            # still carries the co-task host attributes the gang/group
            # apply path reads
            pp.exc_mask = np.zeros((1, len(pp.offers)), dtype=bool)
            pp.exc_rows = np.zeros(0, dtype=np.int32)
            ctx = self.matcher._constraint_context([], resv)
            self.matcher._fill_cotask_host_attributes(
                ctx, pool.name, pp.offers, scheduler.clusters)
            pp.ctx = ctx

        pp.enqueue_ok = np.ones(T, dtype=bool)
        launch_ok = np.ones(T, dtype=bool)
        flags = c["flags0"]
        if exclude is not None:
            kind, epoch, rows = exclude
            if kind == "rows" and epoch == c["epoch"] and len(rows):
                masked = pend & np.isin(rows_s, rows)
                if masked.any():
                    launch_ok = launch_ok & ~masked
                    flags = flags.copy()
                    flags[masked] &= np.uint8(~np.uint8(FLAG_LAUNCH_OK))
                    _audit.note_skips(
                        self.store.audit,
                        {"pipeline-speculative":
                             list(pp.uuid_base[rows_s[masked]])},
                        pool=pool.name)
        pp.launch_ok = launch_ok
        pp.tokens_u = self._tokens_u(pool, users, token_delta)
        # no gang members by eligibility, but a gang that admitted last
        # cycle must still shed its stale deferral reason
        self.matcher.last_admission_deferred[pool.name] = {}
        pp.flags = flags
        self._pack_caps(pp, pool)
        return pp

    def _gang_cohort_admission(self, pool: Pool, groups_ctx: Dict,
                               members_by_gang: Dict,
                               launch_ok: np.ndarray,
                               net_tokens, spec_masked=None) -> set:
        """Host-side gang-cohort admission for the fused pack paths
        (mirrors Matcher.considerable_jobs, docs/GANG.md): a gang that
        cannot clear this cycle's throttles WHOLE is withheld whole by
        clearing its members' launch_ok bits.  The device admits rows
        in rank order until tokens/caps run out, so a straddling cohort
        would admit partial, match, and be reset by the reduction —
        burning capacity every cycle when the budget can never cover
        the gang, with a capacity-shaped explanation for what is a
        rate-limit condition.  (Token/cap contention with earlier
        singles can still split a cohort transiently on device; the
        reduction drops it that cycle and the refilled budget admits it
        whole later.)

        ``members_by_gang``: group uuid -> [(task_row, job)] for the
        pack's pending gang members; ``net_tokens``: user -> launch
        tokens net of the pipeline's token_delta, or None when the
        limiter is off.  Returns the pack-time SATISFIED elastic-gang
        set (the megakernel gang wire excludes those gangs exactly like
        the host reduction does)."""
        deferred_why: Dict[str, Dict] = {}
        skipped: List = []
        satisfied = set()
        if members_by_gang:
            from ..state.schema import gang_bounds, gang_is_elastic
            from .elastic import satisfied_gangs
            mc = self.config.matcher_for_pool(pool.name)
            backoff = self.matcher._backoff.setdefault(
                pool.name, _BackoffState(mc.max_jobs_considered))
            nc = min(backoff.num_considerable, mc.max_jobs_considered)
            mgr = self.matcher.elastic
            if mgr is not None:
                mgr.start_pool_cycle(pool.name)
            satisfied = satisfied_gangs(
                self.store, {guuid: groups_ctx.get(guuid)
                             for guuid in members_by_gang
                             if groups_ctx.get(guuid) is not None}) or set()
            for guuid, members in members_by_gang.items():
                g = groups_ctx.get(guuid)
                if not getattr(g, "gang", False):
                    continue
                if guuid in satisfied:
                    # GROW path (docs/GANG.md elasticity): the gang runs
                    # at >= min, so its waiting members admit like
                    # singles — capped at gang_max, then metered by the
                    # optimizer's grow budget
                    headroom = self.store.gang_growth_headroom(guuid)
                    grow_skipped: List[str] = []
                    max_skipped: List[str] = []
                    for row, j in members:
                        if not launch_ok[row]:
                            continue
                        if headroom < 1:
                            launch_ok[row] = False
                            max_skipped.append(j.uuid)
                            continue
                        if mgr is not None \
                                and not mgr.admit_grow(pool.name):
                            launch_ok[row] = False
                            grow_skipped.append(j.uuid)
                            continue
                        headroom -= 1
                    reasons = {}
                    if grow_skipped:
                        reasons["gang-grow-deferred"] = grow_skipped
                    if max_skipped:
                        reasons["gang-at-max"] = max_skipped
                    if reasons:
                        _audit.note_skips(self.store.audit, reasons,
                                          pool=pool.name)
                    continue
                # cohort size: gang_size for rigid gangs (bit-identical
                # to the pre-elastic admission), gang_min for elastic
                size = gang_bounds(g)[0] if gang_is_elastic(g) \
                    else int(getattr(g, "gang_size", 0) or 0)
                if not size:
                    continue
                if gang_is_elastic(g):
                    # surplus beyond the cohort is capped by the growth
                    # headroom: admit at most max(size, headroom)
                    # members so an unsatisfied elastic gang cannot
                    # overshoot gang_max through the min-threshold
                    # reduction's partial packing (the cohort itself
                    # always admits — it restores legality)
                    allowed = int(max(
                        size, self.store.gang_growth_headroom(guuid)))
                    over = [(row, j) for row, j in members[allowed:]
                            if launch_ok[row]]
                    if over:
                        for row, _j in over:
                            launch_ok[row] = False
                        _audit.note_skips(
                            self.store.audit,
                            {"gang-at-max": [j.uuid for _r, j in over]},
                            pool=pool.name)
                        members = members[:allowed]
                if len(members) < size:
                    reason = "members-missing"
                elif size > nc:
                    reason = "considerable-cap"
                elif sum(1 for row, _j in members
                         if launch_ok[row]) < size:
                    if spec_masked is not None and all(
                            launch_ok[row] or spec_masked[row]
                            for row, _j in members):
                        # every withheld member is the pipeline's
                        # speculative in-flight footprint: the gang is
                        # mid-launch in the overlapped cycle, not
                        # filter/quota-denied — withhold the rest whole
                        # with no deferral reason (reconcile re-surfaces
                        # the gang if the overlapped launch conflicts)
                        extra = []
                        for row, j in members:
                            if launch_ok[row]:
                                launch_ok[row] = False
                                extra.append(j.uuid)
                        if extra:
                            _audit.note_skips(
                                self.store.audit,
                                {"pipeline-speculative": extra},
                                pool=pool.name)
                        continue
                    reason = "member-denied"
                elif net_tokens is not None \
                        and net_tokens(members[0][1].user) < size:
                    reason = "rate-limited"
                else:
                    continue
                for row, job in members:
                    if launch_ok[row]:
                        launch_ok[row] = False
                        skipped.append((job.uuid, {"why": reason}))
                deferred_why[guuid] = {"size": size, "reason": reason}
        # set every cycle, like considerable_jobs on the split path, so
        # a gang that admitted this cycle sheds last cycle's reason
        self.matcher.last_admission_deferred[pool.name] = deferred_why
        if skipped:
            _audit.note_skips(self.store.audit,
                              {"gang-deferred": skipped}, pool=pool.name)
        return satisfied

    def _pack_caps(self, pp: _PackedPool, pool: Pool) -> None:
        """Backoff cap + pool/quota-group caps (shared by both pack paths)."""
        cfg = self.config
        mc = cfg.matcher_for_pool(pool.name)
        backoff = self.matcher._backoff.setdefault(
            pool.name, _BackoffState(mc.max_jobs_considered))
        pp.num_considerable = min(backoff.num_considerable,
                                  mc.max_jobs_considered)
        q = cfg.pool_quota(pool.name)
        if q is not None:
            pp.pool_quota = _pool_quota_vec(q)
        gname = cfg.quota_groups.get(pool.name)
        gq = cfg.quota_group_quotas.get(gname) if gname else None
        if gq is not None:
            pp.group_quota = _pool_quota_vec(gq)

    def _pack_pool(self, scheduler, pool: Pool, exclude=None,
                   token_delta=None) -> Optional[_PackedPool]:
        store, cfg = self.store, self.config
        if cfg.columnar_index:
            return self._pack_pool_columnar(scheduler, pool,
                                            exclude=exclude,
                                            token_delta=token_delta)
        pending = store.pending_jobs(pool.name)
        pp = _PackedPool(pool)
        if not pending:
            return None
        running = store.running_instances(pool.name)
        uts, id2job = build_user_tasks(pending, running)
        shares = {ut.user: tuple(
            store.get_share(ut.user, pool.name).get(d, INF)
            for d in ("cpus", "mem", "gpus")) for ut in uts}
        quotas = {ut.user: _quota_vec(store.get_quota(ut.user, pool.name))
                  for ut in uts}
        arrays, task_ids = host_prep.pack_rank_inputs(
            uts, shares, quotas, pad=False)
        T = arrays["usage"].shape[0]
        pp.task_ids, pp.id2job, pp.arrays, pp.n_tasks = \
            task_ids, id2job, arrays, T

        # offers from every cluster serving this pool
        offers: List[Offer] = []
        # breaker-filtered: a tripped cluster contributes no offers, so
        # the kernel routes demand at healthy clusters
        for cluster in scheduler.launchable_clusters(pool.name):
            offers.extend(cluster.pending_offers(pool.name))
        pp.offers = offers
        pp.n_hosts = len(offers)

        jobs_in_rows = [pp.id2job[t] for t in task_ids]
        pend_rows = arrays["pending"]

        # per-row match resources (running rows never matched, zeroed)
        pp.job_res = np.stack(
            [[j.resources.cpus, j.resources.mem, j.resources.gpus,
              j.resources.disk] for j in jobs_in_rows]).astype(F32) \
            * pend_rows[:, None]

        # constraint mask for pending rows (running rows all-False)
        if offers:
            pend_idx = np.flatnonzero(pend_rows)
            pend_jobs = [jobs_in_rows[i] for i in pend_idx]
            ctx = self.matcher._constraint_context(
                pend_jobs, scheduler.reserved_hosts)
            self.matcher._fill_cotask_host_attributes(
                ctx, pool.name, offers, scheduler.clusters)
            pp.ctx = ctx
            sub = build_constraint_mask(pend_jobs, offers, ctx)
            cmask = np.zeros((T, len(offers)), dtype=bool)
            cmask[pend_idx] = sub
            pp.cmask = cmask
            pp.avail = np.array(
                [[o.available.cpus, o.available.mem, o.available.gpus,
                  o.available.disk] for o in offers], dtype=F32)
            pp.capacity = np.array(
                [[o.capacity.cpus, o.capacity.mem, o.capacity.gpus,
                  o.capacity.disk] for o in offers], dtype=F32)
        else:
            pp.cmask = np.zeros((T, 1), dtype=bool)
            pp.avail = np.zeros((1, 4), dtype=F32)
            pp.capacity = np.zeros((1, 4), dtype=F32)
            pp.n_hosts = 0

        # offensive-job filter -> enqueue_ok (scheduler.clj:2205-2257)
        enqueue_ok = np.ones(T, dtype=bool)
        limits = cfg.offensive_job_limits
        if limits is not None:
            max_mem_mb = limits.memory_gb * 1024.0
            for i, j in enumerate(jobs_in_rows):
                if pend_rows[i] and (j.resources.mem > max_mem_mb
                                     or j.resources.cpus > limits.cpus):
                    enqueue_ok[i] = False
                    pp.offensive.append(j)
        pp.enqueue_ok = enqueue_ok

        # plugin launch verdicts -> launch_ok (cached accept/defer)
        launch_ok = np.ones(T, dtype=bool)
        for i, j in enumerate(jobs_in_rows):
            if pend_rows[i] and not self.plugins.launch_allowed(j):
                launch_ok[i] = False
        # pipelined-driver speculation mask (entity-pack form: by uuid)
        spec_masked = None
        if exclude is not None:
            kind, _epoch, uuids = exclude
            if kind == "uuids" and uuids:
                spec_masked = np.zeros(T, dtype=bool)
                masked_uuids = []
                for i, j in enumerate(jobs_in_rows):
                    if pend_rows[i] and launch_ok[i] and j.uuid in uuids:
                        launch_ok[i] = False
                        spec_masked[i] = True
                        masked_uuids.append(j.uuid)
                if masked_uuids:
                    _audit.note_skips(
                        store.audit,
                        {"pipeline-speculative": masked_uuids},
                        pool=pool.name)
        pp.launch_ok = launch_ok

        # launch-rate token budgets, per user broadcast to tasks
        launch_rl = self.rate_limits.job_launch
        if launch_rl.enforce:
            from ..policy import pool_user_key
            user_tokens = {
                ut.user: launch_rl.get_token_count(
                    pool_user_key(pool.name, ut.user)) for ut in uts}
            if token_delta:
                # overlapped in-flight spends not yet on the limiter
                user_tokens = {
                    u: max(t - token_delta.get(u, 0.0), 0.0)
                    for u, t in user_tokens.items()}
            tok = np.array([user_tokens[pp.id2job[t].user]
                            for t in task_ids], dtype=F32)
        else:
            tok = np.full(T, INF, dtype=F32)
        pp.tokens = tok

        # gang-cohort admission (see the columnar pack / helper doc)
        gang_members: Dict[str, List] = {}
        if offers and pp.ctx is not None:
            for i, job in zip(pend_idx, pend_jobs):
                if job.group is not None and getattr(
                        pp.ctx.groups.get(job.group), "gang", False):
                    gang_members.setdefault(job.group, []).append(
                        (int(i), job))
        self._gang_cohort_admission(
            pool, pp.ctx.groups if pp.ctx is not None else {},
            gang_members, launch_ok,
            (lambda u: user_tokens.get(u, 0.0))
            if launch_rl.enforce else None,
            spec_masked=spec_masked)

        self._pack_caps(pp, pool)
        return pp

    # ------------------------------------------------------------------ step
    def stage(self, scheduler, exclude=None, avail_delta=None,
              token_delta=None) -> "_StagedCycle":
        """Phase 1 of a cycle: host-side staging.  Packs every active
        non-direct pool off the store and builds the per-DRU-mode dispatch
        groups (padded + stacked, ready for :meth:`dispatch_group`).

        The two optional arguments are the pipelined driver's optimistic-
        concurrency hooks (sched/pipeline.py, Omega-style):

        - ``exclude``: pool name -> ("rows"|"uuids", epoch, ids) — launch
          candidates a fetched-but-not-yet-applied overlapped cycle is
          about to consume; they are withheld from this cycle's
          launch_ok so back-to-back cycles don't fight over the head of
          the queue.
        - ``avail_delta``: (cluster, hostname) -> f32[4] — the resources
          those candidates will consume, subtracted from the staged offer
          availability so this cycle's speculative placements stay
          feasible even though the store doesn't show the launches yet.
        - ``token_delta``: pool name -> user -> launch-rate tokens those
          candidates will spend, subtracted from the staged per-user
          token budgets (the rate limiter's spend() lands only at apply,
          after this cycle staged — without the delta a user would get
          depth-x the configured per-cycle launch rate).

        All are None on the sync path, which stays bit-for-bit today's
        behavior."""
        from ..utils.faults import injector as _faults
        _faults.fire("fused.dispatch")

        pools = [p for p in self.store.pools()
                 if p.state == "active" and p.scheduler is not SchedulerKind.DIRECT]
        packed: List[_PackedPool] = []
        excl = exclude or {}
        tokd = token_delta or {}
        # "cycle.rank" is the canonical rank-phase span on the cycle trace
        # (flight.PHASE_BY_SPAN): host-side rank staging — the columnar
        # pack that feeds the device the rank+match problem
        pack_t0 = time.perf_counter()
        with tracing.span("cycle.rank"), tracing.span("fused.pack"):
            for pool in pools:
                pp = self._pack_pool(scheduler, pool,
                                     exclude=excl.get(pool.name),
                                     token_delta=tokd.get(pool.name))
                if pp is not None:
                    packed.append(pp)
            # compact packs must share ONE index compaction epoch: the
            # device base mirror holds one buffer generation, and a pool
            # packed before a mid-cycle compaction carries remapped row
            # ids.  Re-pack stragglers (rare: the dead-row threshold means
            # compaction fires at most once between two packs).
            epochs = {pp.base_compactions for pp in packed if pp.compact}
            if len(epochs) > 1:
                latest = max(epochs)
                refreshed = []
                for pp in packed:
                    if pp.compact and pp.base_compactions != latest:
                        # a stale pack must NEVER be dispatched: its rows_s
                        # are pre-compaction row ids.  A re-pack returning
                        # None (pool's pending drained by the same churn)
                        # just drops the pool from this cycle.
                        pp = self._pack_pool(
                            scheduler, pp.pool,
                            exclude=excl.get(pp.pool.name),
                            token_delta=tokd.get(pp.pool.name))
                        if pp is None or (pp.compact and
                                          pp.base_compactions != latest):
                            continue
                    refreshed.append(pp)
                packed = refreshed
        _flight.note_phase_detail(
            "pack", (time.perf_counter() - pack_t0) * 1000.0)
        if avail_delta:
            for pp in packed:
                for h, o in enumerate(pp.offers):
                    d = avail_delta.get((o.cluster, o.hostname))
                    if d is not None:
                        pp.avail[h] = np.maximum(pp.avail[h] - d, 0.0)
        staged = _StagedCycle(pools)
        if not packed:
            return staged

        # group pools by DRU mode (kernel static)
        by_mode: Dict[bool, List[_PackedPool]] = {}
        for pp in packed:
            by_mode.setdefault(pp.pool.dru_mode is DruMode.GPU, []).append(pp)
        for gpu_mode, group in by_mode.items():
            staged.groups.append(self._stage_group(gpu_mode, group))
        return staged

    def _stage_group(self, gpu_mode: bool,
                     group: List[_PackedPool]) -> "_StagedGroup":
        """Fold quota-group caps and build one DRU-mode group's padded,
        stacked kernel inputs (the wire form :meth:`dispatch_group`
        uploads)."""
        import jax.numpy as jnp

        # Quota-group ids are per dispatch; member pools NOT in this
        # dispatch (no pending jobs, different dru-mode, or direct) still
        # consume the group's cap, so their running usage is folded into
        # the cap host-side (the on-device all_gather covers in-dispatch
        # members; reference semantics: scheduler.clj:2125-2157 counts
        # every member pool's running usage).
        gids: Dict[str, int] = {}
        in_dispatch = {pp.pool.name for pp in group}
        missing_by_group: Dict[str, np.ndarray] = {}

        def missing_usage(gname: str) -> np.ndarray:
            m = missing_by_group.get(gname)
            if m is None:
                m = np.zeros(4, dtype=F32)
                idx = (self.store.ensure_index()
                       if self.config.columnar_index else None)
                for member, g in self.config.quota_groups.items():
                    if g != gname or member in in_dispatch:
                        continue
                    if idx is not None:
                        m += idx.pool_usage_base(member)
                        continue
                    for job, _i in self.store.running_instances(member):
                        m += [job.resources.cpus, job.resources.mem,
                              job.resources.gpus, 1.0]
                missing_by_group[gname] = m
            return m

        for pp in group:
            gname = self.config.quota_groups.get(pp.pool.name)
            if not gname:
                continue
            pp.group_id = gids.setdefault(gname, len(gids))
            pp.group_quota = (pp.group_quota
                              - missing_usage(gname)).astype(F32)
        n_dev = self.mesh().size
        T = bucket(max(pp.n_tasks for pp in group))
        H = bucket(max(max(pp.n_hosts, 1) for pp in group))
        P = max(n_dev, ((len(group) + n_dev - 1) // n_dev) * n_dev)

        def stack(fn, fill=0, dtype=None):
            rows = [fn(pp) for pp in group]
            rows += [np.full_like(rows[0], fill)] * (P - len(group))
            out = np.stack(rows)
            return out if dtype is None else out.astype(dtype)

        def padT(a, fill=0):
            return pad_to(a, T, fill=fill)

        from ..parallel.sharded import (
            CompactPoolCycleInputs,
            PoolCycleInputs,
        )
        arr = lambda k, fill: stack(lambda pp: padT(pp.arrays[k], fill))
        structured = group[0].columnar
        stage_t0 = time.perf_counter()
        avail_p = np.zeros((P, H, 4), dtype=F32)
        cap_p = np.zeros((P, H, 4), dtype=F32)
        for i, pp in enumerate(group):
            avail_p[i, :pp.avail.shape[0]] = pp.avail
            cap_p[i, :pp.capacity.shape[0]] = pp.capacity
        scalars = dict(
            num_considerable=jnp.asarray(np.array(
                [pp.num_considerable for pp in group]
                + [0] * (P - len(group)), dtype=np.int32)),
            pool_quota=jnp.asarray(np.stack(
                [pp.pool_quota for pp in group]
                + [np.full(4, INF, dtype=F32)] * (P - len(group)))),
            group_quota=jnp.asarray(np.stack(
                [pp.group_quota for pp in group]
                + [np.full(4, INF, dtype=F32)] * (P - len(group)))),
            group_id=jnp.asarray(np.array(
                [pp.group_id for pp in group]
                + [-1] * (P - len(group)), dtype=np.int32)))
        if structured:
            # COMPACT wire form: the per-task upload is the sorted row
            # permutation + one flags byte (~5 B/task); resource
            # columns live in the device-resident base mirror and
            # everything else is derived on device (expand_compact).
            # every pp in the group shares one compaction epoch (step
            # re-packs or drops stale pools right after the pack loop),
            # so the mirror's row indices are valid for all of them —
            # assert rather than silently uploading mixed-epoch content
            # under one mirror key
            epoch = max(pp.base_compactions for pp in group)
            assert all(pp.base_compactions == epoch for pp in group), \
                [pp.base_compactions for pp in group]
            base_pp = max(group, key=lambda pp: pp.res_base.shape[0])
            mir_res, mir_disk = self._sync_base_mirror(
                base_pp.res_base, base_pp.disk_base, epoch)
            E = bucket(max(max(len(pp.exc_rows), pp.exc_mask.shape[0])
                           for pp in group), minimum=8)
            U = bucket(max(pp.shares_u.shape[0] for pp in group),
                       minimum=8)
            rows_p = np.zeros((P, T), dtype=np.int32)
            flags_p = np.zeros((P, T), dtype=np.uint8)
            exc_rows_p = np.full((P, E), -1, dtype=np.int32)
            exc_mask_p = np.zeros((P, E, H), dtype=bool)
            host_gpu_p = np.zeros((P, H), dtype=bool)
            # padding hosts stay blocked so zero-resource jobs can
            # never land on them (the dense path's zero rows did this)
            host_blocked_p = np.ones((P, H), dtype=bool)
            shares_u_p = np.full((P, U, 3), INF, dtype=F32)
            quota_u_p = np.full((P, U, 4), INF, dtype=F32)
            tokens_u_p = np.full((P, U), INF, dtype=F32)
            for i, pp in enumerate(group):
                rows_p[i, :pp.n_tasks] = pp.rows_s
                flags_p[i, :pp.n_tasks] = pp.flags
                exc_rows_p[i, :len(pp.exc_rows)] = pp.exc_rows
                e, h = pp.exc_mask.shape
                exc_mask_p[i, :e, :h] = pp.exc_mask
                host_gpu_p[i, :pp.host_gpu.shape[0]] = pp.host_gpu
                host_blocked_p[i, :pp.host_blocked.shape[0]] = \
                    pp.host_blocked
                shares_u_p[i, :pp.shares_u.shape[0]] = pp.shares_u
                quota_u_p[i, :pp.quota_u.shape[0]] = pp.quota_u
                tokens_u_p[i, :pp.tokens_u.shape[0]] = pp.tokens_u
            mega = None
            use_mega = self._megakernel_selected(group)
            if self.config.resident_pack:
                # DEVICE-RESIDENT wire arrays: steady state ships only
                # the scatter delta, not the [P, T] world (ISSUE 7)
                key = (tuple(pp.pool.name for pp in group), P, T)
                rows_dev, flags_dev = self._sync_resident(
                    gpu_mode, key, rows_p, flags_p, epoch)
                resident = True
            elif use_mega and self.config.quantized_wire:
                # the quantized wire carries rows/flags narrow; no wide
                # upload happens at all on this path
                rows_dev = flags_dev = None
                resident = False
            else:  # rebuild mode: dispatch_group accounts the upload
                rows_dev = jnp.asarray(rows_p)
                flags_dev = jnp.asarray(flags_p)
                resident = False
            if use_mega:
                inp = None
                mega = self._stage_mega(
                    group, rows_p=rows_p, flags_p=flags_p,
                    rows_dev=rows_dev, flags_dev=flags_dev,
                    mir_res=mir_res, mir_disk=mir_disk,
                    tokens_u_p=tokens_u_p, shares_u_p=shares_u_p,
                    quota_u_p=quota_u_p, scalars=scalars,
                    host_gpu_p=host_gpu_p, host_blocked_p=host_blocked_p,
                    exc_rows_p=exc_rows_p, exc_mask_p=exc_mask_p,
                    avail_p=avail_p, cap_p=cap_p, T=T, H=H, P=P,
                    resident=resident)
            else:
                inp = CompactPoolCycleInputs(
                    rows=rows_dev,
                    flags=flags_dev,
                    res_base=mir_res,
                    disk_base=mir_disk,
                    tokens_u=jnp.asarray(tokens_u_p),
                    shares_u=jnp.asarray(shares_u_p),
                    quota_u=jnp.asarray(quota_u_p),
                    **scalars,
                    host_gpu=jnp.asarray(host_gpu_p),
                    host_blocked=jnp.asarray(host_blocked_p),
                    exc_rows=jnp.asarray(exc_rows_p),
                    exc_mask=jnp.asarray(exc_mask_p),
                    avail=jnp.asarray(avail_p),
                    capacity=jnp.asarray(cap_p))
        else:
            mega = None
            cmask_p = np.zeros((P, T, H), dtype=bool)
            for i, pp in enumerate(group):
                cmask_p[i, :pp.n_tasks, :pp.cmask.shape[1]] = pp.cmask
            inp = PoolCycleInputs(
                usage=jnp.asarray(arr("usage", 0)),
                quota=jnp.asarray(arr("quota", INF)),
                shares=jnp.asarray(arr("shares", INF)),
                first_idx=jnp.asarray(arr("first_idx", 0)),
                user_rank=jnp.asarray(arr("user_rank", 2**31 - 1)),
                pending=jnp.asarray(arr("pending", False)),
                valid=jnp.asarray(arr("valid", False)),
                enqueue_ok=jnp.asarray(
                    stack(lambda pp: padT(pp.enqueue_ok, False))),
                launch_ok=jnp.asarray(
                    stack(lambda pp: padT(pp.launch_ok, False))),
                tokens=jnp.asarray(
                    stack(lambda pp: padT(pp.tokens, 0.0))),
                **scalars,
                job_res=jnp.asarray(
                    stack(lambda pp: padT(pp.job_res, 0.0))),
                cmask=jnp.asarray(cmask_p),
                avail=jnp.asarray(avail_p),
                capacity=jnp.asarray(cap_p))

        # static match-problem cap: the configured max_jobs_considered
        # (>= every pool's dynamic num_considerable), bucketed so the
        # compiled cycle is reused across config tweaks
        cap = bucket(max(
            self.config.matcher_for_pool(pp.pool.name).max_jobs_considered
            for pp in group))
        stage_ms = round((time.perf_counter() - stage_t0) * 1000.0, 1)
        _flight.note_phase_detail("stage", stage_ms)
        return _StagedGroup(gpu_mode=gpu_mode, group=group, inp=inp,
                            structured=structured, cap=cap, T=T, H=H,
                            stage_ms=stage_ms,
                            resident=structured and bool(
                                self.config.resident_pack),
                            mega=mega)

    # ------------------------------------------------------------ megakernel
    def _megakernel_selected(self, group: List[_PackedPool]) -> bool:
        """Route this dispatch group through the single-launch Pallas
        megakernel (ops/pallas_cycle.py)?  An explicit ``tpu-megakernel``
        pin on ANY pool takes the whole group there (interpret-mode on
        CPU — the tier-1 parity surface; co-grouped ``auto`` pools ride
        along, decisions are parity-identical); pure-``auto`` groups
        prefer it only on a real TPU backend.  The kernel serves the
        compact structured wire on a single-device mesh; everything
        else keeps the fused XLA cycle."""
        if not self.config.columnar_index or self.mesh().size != 1:
            return False
        backends = {self.config.matcher_for_pool(pp.pool.name).backend
                    for pp in group}
        if not backends <= {"auto", "tpu-megakernel"}:
            return False
        if "tpu-megakernel" in backends:
            return True  # an explicit pin wins for the group
        import jax
        return jax.default_backend() == "tpu"

    def _pool_mega_candidate(self, pool_name: str) -> bool:
        """Pack-time gate for the gang-wire build: could this pool's
        dispatch group take the megakernel path?  A cheap per-pool
        approximation of :meth:`_megakernel_selected` — pools whose
        group dispatches mega WITHOUT their own wire (possible only for
        an ``auto`` pool riding a pinned group on CPU) simply keep the
        host gang reduction (the apply path requires ``pp.gang_wire``
        before trusting fused verdicts).  The converse imprecision is
        accepted too: a pinned pool co-grouped with a non-mega pool
        (mixed explicit backends, exotic) stages a wire its group never
        dispatches — wasted staging, never a wrong decision; group
        composition is a DRU-mode fact this pack-time gate cannot
        see."""
        if not self.config.columnar_index or self.mesh().size != 1:
            return False
        b = self.config.matcher_for_pool(pool_name).backend
        if b == "tpu-megakernel":
            return True
        if b == "auto":
            import jax
            return jax.default_backend() == "tpu"
        return False

    def _stage_mega(self, group, *, rows_p, flags_p, rows_dev, flags_dev,
                    mir_res, mir_disk, tokens_u_p, shares_u_p, quota_u_p,
                    scalars, host_gpu_p, host_blocked_p, exc_rows_p,
                    exc_mask_p, avail_p, cap_p, T, H, P, resident):
        """Build the megakernel dispatch payload for one staged group:
        the negotiated (quantized or wide) wire, the padded gang arrays,
        the h2d byte account, and a thunk that rebuilds the fused-XLA
        CompactPoolCycleInputs if the Pallas dispatch fails."""
        import jax.numpy as jnp
        from ..ops import pallas_cycle, quant
        from ..ops.padding import bucket as _bucket
        quantize = bool(self.config.quantized_wire)
        h2d = 0
        # rows/flags: device-resident buffers cost nothing this cycle;
        # rebuild mode ships them — delta-coded narrow when they fit
        rows_codec = quant.ROWS_WIDE
        if resident:
            w_rows, w_flags = rows_dev, flags_dev
        elif quantize:
            # negotiate over an IDENTITY-padded copy: rows_p zero-pads
            # its bucket tail, and a zero at position t would read as
            # delta -t — blowing the narrow range for any pool not
            # exactly filling its bucket.  Padding rows are fully
            # masked downstream (flags 0, and every consumer multiplies
            # by valid/pending), so the decoded identity values are
            # inert and their deltas are 0: the REAL rows decide the
            # width.
            rows_q = rows_p.copy()
            iota = np.arange(T, dtype=rows_q.dtype)
            for i in range(P):
                n = group[i].n_tasks if i < len(group) else 0
                rows_q[i, n:] = iota[n:]
            qr = quant.quantize_rows(rows_q)
            rows_codec = qr.codec
            w_rows = jnp.asarray(qr.data)
            w_flags = jnp.asarray(flags_p)
            h2d += qr.nbytes + flags_p.nbytes
        else:
            w_rows = (jnp.asarray(rows_p) if rows_dev is None else rows_dev)
            w_flags = (jnp.asarray(flags_p) if flags_dev is None
                       else flags_dev)
            h2d += rows_p.nbytes + flags_p.nbytes
        avail_scale = cap_scale = 0.0
        if quantize:
            # STICKY scales: the tuple is a static jit key of the
            # megakernel, so reuse the last negotiated scale while it
            # still round-trips — renegotiating to the finest exact
            # scale every cycle would retrace on every domain shift
            qa = quant.quantize_fixed(
                avail_p, "avail", prefer=self._mega_scales.get("avail"))
            qc = quant.quantize_fixed(
                cap_p, "capacity",
                prefer=self._mega_scales.get("capacity"))
            avail_scale, cap_scale = qa.scale, qc.scale
            if qa.scale != 0.0:
                self._mega_scales["avail"] = qa.scale
            if qc.scale != 0.0:
                self._mega_scales["capacity"] = qc.scale
            w_avail, w_cap = jnp.asarray(qa.data), jnp.asarray(qc.data)
            h2d += qa.nbytes + qc.nbytes
        else:
            w_avail, w_cap = jnp.asarray(avail_p), jnp.asarray(cap_p)
            h2d += avail_p.nbytes + cap_p.nbytes
        host_bits = np.stack([quant.pack_bits(host_gpu_p),
                              quant.pack_bits(host_blocked_p)], axis=1)
        h2d += (host_bits.nbytes + exc_rows_p.nbytes + exc_mask_p.nbytes
                + tokens_u_p.nbytes + shares_u_p.nbytes + quota_u_p.nbytes)
        # gang wire, padded across the group (structural no-op rows for
        # gang-free pools: id -1 everywhere, unreachable padding sizes)
        wires = [pp.gang_wire for pp in group]
        if any(w is not None for w in wires):
            G = _bucket(max(len(w.gang_size) for w in wires
                            if w is not None), minimum=8)
            A = _bucket(max(w.host_topo.shape[0] for w in wires
                            if w is not None), minimum=1)
            gang_id_p = np.full((P, T), -1, dtype=np.int32)
            gang_size_p = np.full((P, G), 2 ** 30, dtype=np.int32)
            gang_attr_p = np.zeros((P, G), dtype=np.int32)
            host_topo_p = np.full((P, A, H), -1, dtype=np.int32)
            host_topo_p[:, 0, :] = 0
            for i, w in enumerate(wires):
                if w is None:
                    continue
                gang_id_p[i, :w.gang_id.shape[0]] = w.gang_id
                gang_size_p[i, :w.gang_size.shape[0]] = w.gang_size
                gang_attr_p[i, :w.gang_attr.shape[0]] = w.gang_attr
                a, hh = w.host_topo.shape
                host_topo_p[i, :a, :hh] = w.host_topo
        else:
            gang_id_p, gang_size_p, gang_attr_p, host_topo_p = \
                pallas_cycle.empty_gang_wire(P, T, H)
        h2d += (gang_id_p.nbytes + gang_size_p.nbytes
                + gang_attr_p.nbytes + host_topo_p.nbytes)
        wire = pallas_cycle.MegaCycleWire(
            rows=w_rows, flags=w_flags, res_base=mir_res,
            disk_base=mir_disk, tokens_u=jnp.asarray(tokens_u_p),
            shares_u=jnp.asarray(shares_u_p),
            quota_u=jnp.asarray(quota_u_p),
            num_considerable=scalars["num_considerable"],
            pool_quota=scalars["pool_quota"],
            group_quota=scalars["group_quota"],
            group_id=scalars["group_id"],
            host_bits=jnp.asarray(host_bits),
            exc_rows=jnp.asarray(exc_rows_p),
            exc_mask=jnp.asarray(exc_mask_p),
            avail=w_avail, capacity=w_cap,
            gang_id=jnp.asarray(gang_id_p),
            gang_size=jnp.asarray(gang_size_p),
            gang_attr=jnp.asarray(gang_attr_p),
            host_topo=jnp.asarray(host_topo_p))

        def build_fused_inp():
            # reconstruct the fused-XLA inputs FROM THE WIRE (every
            # codec is lossless by contract), not from captured host
            # staging arrays: the closure would otherwise pin tens of
            # MB of [P,T]/[P,E,H] host memory for the staged group's
            # whole lifetime to serve a fallback that runs only on a
            # Pallas dispatch failure.  Rows decode identity-padded
            # (vs the original zero padding) — padding rows are
            # flag-masked in expand_compact exactly as in the kernel,
            # so decisions are unchanged.
            from ..parallel.sharded import CompactPoolCycleInputs
            if resident:
                rd, fd = rows_dev, flags_dev
            else:
                rd = jnp.asarray(quant.expand_rows(quant.QuantizedRows(
                    rows_codec, np.asarray(wire.rows))))
                fd = wire.flags
            bits = np.asarray(wire.host_bits)
            avail_f = (jnp.asarray(quant.expand_fixed(quant.QuantizedFixed(
                avail_scale, np.asarray(wire.avail))))
                if avail_scale != 0.0 else wire.avail)
            cap_f = (jnp.asarray(quant.expand_fixed(quant.QuantizedFixed(
                cap_scale, np.asarray(wire.capacity))))
                if cap_scale != 0.0 else wire.capacity)
            return CompactPoolCycleInputs(
                rows=rd, flags=fd, res_base=mir_res, disk_base=mir_disk,
                tokens_u=wire.tokens_u, shares_u=wire.shares_u,
                quota_u=wire.quota_u, **scalars,
                host_gpu=jnp.asarray(quant.unpack_bits(bits[:, 0], H)),
                host_blocked=jnp.asarray(
                    quant.unpack_bits(bits[:, 1], H)),
                exc_rows=wire.exc_rows, exc_mask=wire.exc_mask,
                avail=avail_f, capacity=cap_f)

        return {"wire": wire, "rows_codec": rows_codec,
                "avail_scale": avail_scale, "cap_scale": cap_scale,
                "h2d_bytes": int(h2d), "build_fused_inp": build_fused_inp}

    def _dispatch_mega(self, sg: "_StagedGroup") -> "_GroupDispatch":
        """Single-launch dispatch of a megakernel-staged group; a Pallas
        failure (Mosaic lowering, device loss, injected fault) degrades
        to the fused XLA cycle rebuilt from the same staged arrays —
        the cycle never dies (docs/ROBUSTNESS.md)."""
        from ..ops import pallas_cycle
        from ..utils.metrics import registry
        m = sg.mega
        telemetry.profile_upload(sg.stage_ms, m["wire"])
        telemetry.count_transfer("h2d", m["h2d_bytes"])
        try:
            with tracing.span("fused.dispatch", pools=len(sg.group),
                              tasks=sg.T, hosts=sg.H, gpu=sg.gpu_mode,
                              stage_ms=sg.stage_ms, megakernel=True):
                res = pallas_cycle.megacycle(
                    m["wire"], gpu_mode=sg.gpu_mode,
                    max_over_quota_jobs=self.config.max_over_quota_jobs,
                    considerable_cap=min(sg.cap, sg.T),
                    rows_codec=m["rows_codec"],
                    avail_scale=m["avail_scale"],
                    cap_scale=m["cap_scale"])
        except Exception:
            import logging
            logging.getLogger(__name__).exception(
                "megakernel dispatch failed; fused XLA cycle fallback")
            registry.counter_inc("cook_kernel_fallback",
                                 labels={"kernel": "pallas.megacycle"})
            _flight.note_fault("kernel.dispatch-fallback")
            sg.inp = m["build_fused_inp"]()
            sg.mega = None
            # the wire's h2d was already charged above and the rebuilt
            # inputs reuse its device arrays — the re-dispatch must not
            # re-count the whole input as a second upload
            sg.mega_fallback = True
            return self.dispatch_group(sg)
        _flight.note_path("megakernel")
        outs = (res.cand_row, res.cand_assign, res.cand_qpos,
                res.n_queue, res.cand_gang, res.cand_dropped)
        for out_arr in outs:
            copy_async = getattr(out_arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        return _GroupDispatch(sg, res, outs)

    def dispatch_group(self, sg: "_StagedGroup") -> "_GroupDispatch":
        """Phase 2: upload one staged group's inputs and dispatch the
        jitted cycle; starts the async device->host copies of the compact
        outputs so a later :meth:`fetch_group` overlaps the transfer with
        whatever the host does in between (the pipelined driver's whole
        point)."""
        if sg.mega is not None:
            return self._dispatch_mega(sg)
        telemetry.profile_upload(sg.stage_ms, sg.inp)
        # staged wire bytes this dispatch: the device-resident base
        # mirror fields are never re-uploaded per cycle (the mirror sync
        # accounts its own transfers), and in resident-pack mode the
        # rows/flags buffers are device-resident too — only their delta
        # scatter moved bytes, accounted by _sync_resident
        skip = {"res_base", "disk_base"}
        if sg.resident:
            skip |= {"rows", "flags"}
        if sg.mega_fallback:
            # rebuilt from the already-uploaded wire: the bytes crossed
            # the bus once, charged by _dispatch_mega (the few decoded
            # arrays are a slight undercount, never a double count)
            skip = set(type(sg.inp)._fields)
        telemetry.count_transfer("h2d", sum(
            getattr(a, "nbytes", 0)
            for name, a in zip(type(sg.inp)._fields, sg.inp)
            if name not in skip))
        with tracing.span("fused.dispatch", pools=len(sg.group),
                          tasks=sg.T, hosts=sg.H, gpu=sg.gpu_mode,
                          stage_ms=sg.stage_ms):
            res = self._cycle_fn(sg.gpu_mode, min(sg.cap, sg.T),
                                 sg.structured,
                                 compact=sg.structured)(sg.inp)
        _flight.note_path("fused")
        # fetch ONLY the compact outputs: [C]-sized candidate
        # triples + the queue count.  The full [T] arrays
        # (order/queue_ok/assign) and the rank-ordered queue_rows
        # stay device-resident; the published RankedQueue fetches
        # queue_rows lazily when a consumer actually touches the
        # queue.  Device->host bandwidth is the cycle's scarcest
        # resource on a tunneled chip (~10 MB/s observed): the old
        # four-[T]-array fetch cost 2.1 MB / 210-250 ms per cycle
        # at T=131k; this fetches ~50 KB.
        outs = (res.cand_row, res.cand_assign, res.cand_qpos,
                res.n_queue)
        for out_arr in outs:
            copy_async = getattr(out_arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        return _GroupDispatch(sg, res, outs)

    def fetch_group(self, gd: "_GroupDispatch"):
        """Phase 3: one batched device->host fetch of a dispatch's compact
        outputs (each separate np.asarray would pay a full round trip,
        expensive on a tunneled chip).  Idempotent."""
        if gd.fetched is None:
            import jax
            with tracing.span("fused.fetch"), \
                    telemetry.sync_wait("fused.fetch"):
                gd.fetched = jax.device_get(gd.outs)
            telemetry.count_transfer("d2h", sum(
                getattr(a, "nbytes", 0) for a in gd.fetched))
        return gd.fetched

    def apply_group(self, scheduler, gd: "_GroupDispatch", queues, results,
                    reconciler=None) -> None:
        """Phase 4: map one fetched group's outputs back to entities and
        run the transactional launch path per pool.  ``reconciler`` is the
        pipelined driver's pre-launch re-validation hook (see
        :meth:`_apply_pool`)."""
        cand_row, cand_assign, cand_qpos, n_queue = gd.fetched[:4]
        # megakernel dispatches also fetched the fused gang stage's
        # verdicts (post-reduction assignment + drop mask per slot)
        gang_fetched = gd.fetched[4:6] if len(gd.fetched) >= 6 else None
        apply_t0 = time.perf_counter()
        with tracing.span("cycle.launch", pools=len(gd.sg.group)):
            for i, pp in enumerate(gd.sg.group):
                gang_pre = (None if gang_fetched is None else
                            (gang_fetched[0][i], gang_fetched[1][i]))
                self._apply_pool(scheduler, pp, cand_row[i],
                                 cand_assign[i], cand_qpos[i],
                                 int(n_queue[i]), gd.res.queue_rows, i,
                                 queues, results, reconciler=reconciler,
                                 gang_pre=gang_pre)
        _flight.note_phase_detail(
            "apply", (time.perf_counter() - apply_t0) * 1000.0)

    def step(self, scheduler) -> Tuple[Dict[str, List[Job]],
                                       Dict[str, MatchCycleResult]]:
        """One SYNCHRONOUS fused cycle over all active non-direct pools:
        stage -> dispatch -> fetch -> apply, group by group, exactly the
        pre-pipeline behavior (pipeline_depth=0 routes here).  Returns
        (pending queues, match results); direct pools are handled by the
        scheduler separately."""
        staged = self.stage(scheduler)
        queues: Dict[str, List[Job]] = {p.name: [] for p in staged.pools}
        results: Dict[str, MatchCycleResult] = {}
        for sg in staged.groups:
            with tracing.span("cycle.match", pools=len(sg.group),
                              tasks=sg.T, hosts=sg.H, gpu=sg.gpu_mode):
                gd = self.dispatch_group(sg)
                self.fetch_group(gd)
            self.apply_group(scheduler, gd, queues, results)
        return queues, results

    # ----------------------------------------------------------------- apply
    def _apply_pool(self, scheduler, pp: _PackedPool, cand_row, cand_assign,
                    cand_qpos, n_queue: int, queue_rows_dev, pool_slot: int,
                    queues, results, reconciler=None,
                    gang_pre=None) -> None:
        """Map one pool's COMPACT kernel outputs back to entities: queue
        refresh, within-batch group validation, backoff bookkeeping,
        transactional launch.

        ``cand_row``/``cand_assign``/``cand_qpos`` are the [C] admitted-slot
        arrays (-1 = empty slot); the rank-ordered queue rows stay on device
        in ``queue_rows_dev[pool_slot]`` and are fetched only when a queue
        consumer materializes them.

        ``reconciler`` is the pipelined driver's Omega-style pre-launch
        re-validation (sched/pipeline.py): called with (pp, cand_jobs,
        cand_host), returns (state_drop, resource_drop) bool masks over
        the candidates.  State conflicts (no longer WAITING — launched by
        an overlapped cycle, or killed since the pack) are removed
        outright and pruned from the published queue; resource conflicts
        (the host's availability was consumed by an overlapped launch the
        staged snapshot didn't see) fall back to unmatched and retry next
        cycle.  Never passed on the sync path."""
        pool_name = pp.pool.name
        # slice this pool's row off the [P, T] output eagerly (an async
        # device op): the published queue's closure must NOT keep the whole
        # P-wide buffer — or the rest of pp — alive for its lifetime
        dev_rows = queue_rows_dev[pool_slot]
        rows_s = pp.rows_s
        fetched_rows: List[Optional[np.ndarray]] = [None]

        def fetch_local_rows() -> np.ndarray:
            # one device->host transfer of exactly n_queue i32 rows, paid
            # only when some consumer (rebalancer, /queue page, direct-pool
            # logic) actually touches the published queue
            if fetched_rows[0] is None:
                import jax
                with telemetry.sync_wait("queue.rows"):
                    fetched_rows[0] = np.asarray(jax.device_get(
                        dev_rows[:n_queue]))
                telemetry.count_transfer("d2h", fetched_rows[0].nbytes)
            return fetched_rows[0]

        def local_rows_with_drops(drop_qpos) -> np.ndarray:
            rows = fetch_local_rows()
            if drop_qpos is not None and len(drop_qpos):
                # post-match queue prune (native/pack.cpp when built)
                from ..native.pack import prune_rows
                rows = prune_rows(rows, np.unique(drop_qpos))
            return rows

        def publish_queue(drop_qpos=None):
            if pp.columnar:
                # lazy queue straight over the index BASE snapshots; the
                # row selection itself is DEFERRED (device fetch + drop
                # filter run on first touch), and full-column gathers
                # happen only if someone reads .uuids/.resources/.users
                from .ranker import RankedQueue
                n = n_queue - (len(drop_qpos) if drop_qpos is not None
                               else 0)
                queues[pool_name] = RankedQueue(
                    self.store, pp.uuid_base, pp.res_base, pp.user_base,
                    rows_fn=lambda drop=drop_qpos:
                        rows_s[local_rows_with_drops(drop)],
                    n=n)
            else:
                queues[pool_name] = [
                    pp.id2job[pp.task_ids[r]]
                    for r in local_rows_with_drops(drop_qpos)]

        scheduler._stifle_offensive(pp.offensive)

        result = MatchCycleResult()
        slots = np.flatnonzero(cand_row >= 0)
        result.considered = len(slots)
        # fused gang verdicts (megakernel dispatch): usable only while
        # the candidate view the kernel reduced over stays INTACT — any
        # vanished job, reconcile drop, clip, or group-placement reset
        # below invalidates them and the host reduction recomputes
        # (identical math, ops/gang.py; parity-asserted).  The pool
        # must also have STAGED its gang wire: an auto pool riding a
        # pinned group on CPU dispatches mega without one, and its
        # all -1 gang ids would read as "nothing dropped"
        gang_ok = gang_pre is not None and pp.gang_wire is not None
        if pp.columnar:
            uuid_prefix = pp.uuid_base[pp.rows_s[cand_row[slots]]]
            fetched = self.store.jobs_bulk([str(u) for u in uuid_prefix])
            cand_jobs, cand_keep = [], []
            for s, job in zip(slots, fetched):
                if job is not None:
                    cand_jobs.append(job)
                    cand_keep.append(s)
            if len(cand_keep) != len(slots):
                gang_ok = False
            slots = np.array(cand_keep, dtype=np.int64)
        else:
            cand_jobs = [pp.id2job[pp.task_ids[r]] for r in cand_row[slots]]
        # per-job rank attribution for the fetched candidate slots
        # (bounded by the considerable cap, never [T]-sized): the
        # device-computed queue position, straight off the compact
        # outputs already on host (utils/audit.py)
        if len(slots):
            self.store.audit.ranked(
                [j.uuid for j in cand_jobs],
                [int(q) for q in cand_qpos[slots]], pool_name,
                users=[j.user for j in cand_jobs])
        if len(slots) == 0 or not pp.offers:
            # mirror Matcher.match_pool: an empty cycle returns the
            # considerable set unmatched and leaves backoff untouched
            result.unmatched = cand_jobs
            publish_queue()
            results[pool_name] = result
            return

        cand_host = cand_assign[slots].astype(np.int64)
        # clip padding-host assignments (can't happen: padding hosts have
        # zero capacity and all-False masks, but stay defensive)
        clipped = cand_host >= len(pp.offers)
        if clipped.any():
            cand_host[clipped] = -1
            gang_ok = False
        conflict_qpos = None
        res_conflict = None
        dropped_head_matched = False
        if reconciler is not None:
            with tracing.span("fused.reconcile", pool=pool_name,
                              candidates=len(slots)):
                state_drop, res_drop = reconciler(pp, cand_jobs, cand_host)
            # a dropped HEAD that held an assignment DID match (it
            # launched one cycle earlier, or the overlap consumed its
            # host): backoff must not shrink for a transient conflict
            dropped_head_matched = bool(
                (state_drop[0] or res_drop[0]) and cand_host[0] >= 0) \
                if len(slots) else False
            if state_drop.any() or res_drop.any():
                gang_ok = False
            if res_drop.any():
                cand_host[res_drop] = -1
            if state_drop.any():
                qp = cand_qpos[slots[state_drop]]
                conflict_qpos = qp[qp >= 0]
                keep = ~state_drop
                slots = slots[keep]
                cand_jobs = [j for j, k in zip(cand_jobs, keep) if k]
                cand_host = cand_host[keep]
                res_drop = res_drop[keep]
            res_conflict = res_drop if res_drop.any() else None
            if len(slots) == 0:
                # every candidate conflicted away: like the empty cycle,
                # leave backoff untouched (the head DID match — it just
                # launched one cycle earlier than this stale snapshot saw)
                publish_queue(conflict_qpos)
                result.queue_pruned = conflict_qpos is not None \
                    and len(conflict_qpos) > 0
                results[pool_name] = result
                return
        pre_validate = cand_host.copy()
        cand_host = validate_group_placement(
            cand_jobs, cand_host, pp.offers, pp.ctx)
        if gang_ok and (cand_host != pre_validate).any():
            # a within-batch placement rule reset an assignment after
            # the kernel's gang stage saw it: the fused verdict is stale
            gang_ok = False
        # gang all-or-nothing over the fetched candidates (ops/gang.py,
        # docs/GANG.md): partial gangs reset to unmatched with their
        # capacity refilled to group-less candidates in the SAME cycle.
        # Under the pipelined driver a reconcile-dropped member already
        # left its gang incomplete, so a conflicted gang drops atomically
        # here.  Structural no-op when no candidate is a gang member.
        groups_ctx = pp.ctx.groups if pp.ctx is not None else {}
        if any(j.group is not None
               and getattr(groups_ctx.get(j.group), "gang", False)
               for j in cand_jobs):
            from ..ops.gang import apply_gang_cycle
            from .elastic import satisfied_gangs
            H = len(pp.offers)
            cand_res = np.array(
                [[j.resources.cpus, j.resources.mem, j.resources.gpus,
                  j.resources.disk] for j in cand_jobs], dtype=F32)
            satisfied = satisfied_gangs(self.store, groups_ctx)
            if gang_ok:
                # the fused gang stage's membership is pack-time state:
                # a satisfied-set flip since staging (member failure,
                # grace shrink landing mid-cycle) changes who the
                # reduction even counts — recompute on host then
                wire_gangs = (frozenset(pp.gang_wire.uuids)
                              if pp.gang_wire is not None else frozenset())
                now_satisfied = frozenset(
                    u for u in (satisfied or ())
                    if u in wire_gangs or u in pp.gang_satisfied)
                if now_satisfied != pp.gang_satisfied:
                    gang_ok = False
            precomputed = None
            if gang_ok:
                precomputed = (np.asarray(gang_pre[0])[slots],
                               np.asarray(gang_pre[1])[slots].astype(bool))
            cand_host, gstats = apply_gang_cycle(
                cand_jobs, cand_host, pp.offers, groups_ctx,
                job_res=cand_res,
                cmask_fn=lambda: build_constraint_mask(
                    cand_jobs, pp.offers, pp.ctx),
                # reconcile-adjusted availability when an overlapped
                # cycle overdrafted the staged snapshot: the rescue and
                # refill passes must not re-place onto a host the
                # reconciler just protected
                avail=(pp.avail_headroom if pp.avail_headroom is not None
                       else pp.avail[:H]),
                capacity=pp.capacity[:H],
                device=False,
                refill_ok=(~res_conflict if res_conflict is not None
                           else None),
                audit_trail=self.store.audit, audit_pool=pool_name,
                satisfied=satisfied,
                precomputed=precomputed)
            if gstats is not None:
                result.gang_partial = gstats.partial
        if res_conflict is not None:
            # resource-conflicted candidates are a pipeline transient,
            # not a placement failure: keep them out of the unscheduled
            # explainer's persisted per-host summaries
            rp_keep = ~res_conflict
            self.matcher.record_placement_failures(
                [j for j, k in zip(cand_jobs, rp_keep) if k],
                cand_host[rp_keep], pp.offers, pp.ctx)
        else:
            self.matcher.record_placement_failures(
                cand_jobs, cand_host, pp.offers, pp.ctx)

        result.head_matched = bool(cand_host[0] >= 0) or dropped_head_matched
        mc = self.config.matcher_for_pool(pool_name)
        self.matcher._backoff[pool_name].update(mc, result.head_matched)

        for j, job in enumerate(cand_jobs):
            h = int(cand_host[j])
            if h < 0:
                result.unmatched.append(job)
            else:
                result.matched.append((job, pp.offers[h]))
        with tracing.span("fused.launch", pool=pool_name,
                          matched=len(result.matched)):
            self.matcher._launch(pool_name, result, scheduler.clusters)
        # drop this cycle's launches — and any reconcile-conflicted
        # candidates — from the queue by exact position (launched
        # candidates are always queue members — match_valid implies
        # queue_ok, so cand_qpos is valid for every launched slot)
        drops = ([conflict_qpos] if conflict_qpos is not None
                 and len(conflict_qpos) else [])
        if result.launched_job_uuids:
            cand_uuids = np.array([j.uuid for j in cand_jobs])
            launched_c = np.isin(cand_uuids,
                                 np.array(result.launched_job_uuids))
            drops.append(cand_qpos[slots[launched_c]])
        if drops:
            publish_queue(np.concatenate(drops))
            result.queue_pruned = True
        else:
            publish_queue()
        _audit.note_skips(self.store.audit, {
            "unmatched": [j.uuid for j in result.unmatched],
            "launch-failed": [(u, {"why": why})
                              for u, why in result.launch_failures],
        }, pool=pool_name)
        results[pool_name] = result
