"""Generic long-running service farm over the jobclient.

The reference integrates Dask and Spark by running their worker processes
as Cook jobs (reference: dask/docs/design.md architecture — "deploy the
scheduler node and worker nodes on Cook as jobs"; spark patches submit
coarse-grained executors the same way).  ServiceFarm is that pattern made
first-class: declare a command template, call :meth:`scale`, and the farm
submits or kills jobs to converge on the target, tracking them by a farm
label so a restarted client can re-adopt its fleet.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ..client import TERMINAL_STATES

FARM_LABEL = "cook-service-farm"


class ServiceFarm:
    """Manage N copies of a long-running service job.

    ``command_fn(index)`` produces the command line for worker *index*;
    ``spec`` carries the common job fields (cpus/mem/gpus/pool/labels...).
    """

    def __init__(self, client, name: str,
                 command_fn: Callable[[int], str],
                 spec: Optional[Dict] = None,
                 pool: Optional[str] = None):
        self.client = client
        self.name = name
        self.command_fn = command_fn
        self.spec = dict(spec or {})
        self.pool = pool
        self._next_index = 0
        # uuid -> worker index, live fleet as this farm believes it
        self._workers: Dict[str, int] = {}
        self._adopt()

    # ------------------------------------------------------------- adoption
    def _adopt(self) -> None:
        """Re-adopt jobs labeled for this farm that are still alive (a
        client restart must not leak a running fleet).

        A transient listing failure here would silently skip adoption and
        make the restarted client double-submit over a leaked fleet — the
        exact bug adoption exists to prevent — so the listing is retried
        and a persistent failure raises instead of returning quietly.
        """
        last_err = None
        for attempt in range(5):
            try:
                # filter by the submitting user: two users may run
                # same-named farms, and one must never adopt (then kill)
                # the other's fleet
                jobs = self.client.jobs(
                    user=getattr(self.client, "user", None),
                    states=["waiting", "running"])
                break
            except Exception as e:
                last_err = e
                time.sleep(min(0.25 * (2 ** attempt), 2.0))
        else:
            raise RuntimeError(
                f"ServiceFarm {self.name!r}: could not list jobs to "
                f"re-adopt the fleet ({last_err}); refusing to start "
                "blind (would double-submit over a leaked fleet)")
        for j in jobs:
            labels = j.get("labels") or {}
            if labels.get(FARM_LABEL) == self.name:
                idx = int(labels.get("cook-farm-index", -1))
                self._workers[j["uuid"]] = idx
                self._next_index = max(self._next_index, idx + 1)

    # --------------------------------------------------------------- fleet
    def _make_spec(self, idx: int) -> Dict:
        spec = dict(self.spec)
        labels = dict(spec.get("labels") or {})
        labels[FARM_LABEL] = self.name
        labels["cook-farm-index"] = str(idx)
        spec["labels"] = labels
        spec["command"] = self.command_fn(idx)
        spec.setdefault("max_retries", 1)
        return spec

    def _refresh(self) -> None:
        """Drop fleet members that completed (failed/killed workers)."""
        if not self._workers:
            return
        for j in self.client.query(list(self._workers)):
            if j.get("state") in TERMINAL_STATES:
                self._workers.pop(j["uuid"], None)

    def scale(self, n: int) -> List[str]:
        """Converge on ``n`` live workers; returns the fleet's uuids.
        Scale-down kills the newest workers first (the dask design doc's
        recommendation: disturb the oldest, warmest workers last)."""
        self._refresh()
        if len(self._workers) < n:
            # one batched POST, not a round trip per worker
            idxs = [self._next_index + k
                    for k in range(n - len(self._workers))]
            self._next_index = idxs[-1] + 1
            uuids = self.client.submit([self._make_spec(i) for i in idxs],
                                       pool=self.pool)
            self._workers.update(zip(uuids, idxs))
        if len(self._workers) > n:
            doomed = sorted(self._workers, key=self._workers.get,
                            reverse=True)[:len(self._workers) - n]
            self.client.kill(doomed)
            for u in doomed:
                self._workers.pop(u, None)
        return list(self._workers)

    def size(self) -> int:
        """Current believed fleet size (no HTTP round trip)."""
        return len(self._workers)

    def fleet(self) -> List[str]:
        """Current fleet uuids."""
        return list(self._workers)

    def kill_members(self, uuids: List[str]) -> None:
        """Kill specific fleet members and forget them."""
        doomed = [u for u in uuids if u in self._workers]
        if doomed:
            self.client.kill(doomed)
            for u in doomed:
                self._workers.pop(u, None)

    def status(self) -> Dict[str, str]:
        """uuid -> state for the current fleet."""
        if not self._workers:
            return {}
        return {j["uuid"]: j["state"]
                for j in self.client.query(list(self._workers))}

    def running(self) -> List[str]:
        return [u for u, s in self.status().items() if s == "running"]

    def wait_running(self, n: int, timeout_s: float = 60.0,
                     poll_s: float = 0.2) -> List[str]:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            r = self.running()
            if len(r) >= n:
                return r
            time.sleep(poll_s)
        raise TimeoutError(
            f"{self.name}: {n} running workers not reached in {timeout_s}s")

    def start_singleton(self, timeout_s: float = 60.0,
                        poll_s: float = 0.2):
        """Scale to ONE member and resolve its placement once running:
        returns ``(uuid, hostname, ports)``.  The shared head-node
        bring-up for the dask scheduler and the spark master — one
        definition of the poll/resolve/terminal-check loop."""
        [uuid] = self.scale(1)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            [job] = self.client.query([uuid])
            if job["state"] == "running" and job.get("instances"):
                inst = job["instances"][-1]
                return (uuid, inst.get("hostname", ""),
                        inst.get("ports") or [])
            if job["state"] in TERMINAL_STATES:
                raise RuntimeError(
                    f"{self.name}: singleton job completed early")
            time.sleep(poll_s)
        raise TimeoutError(
            f"{self.name}: singleton not running within {timeout_s}s")

    def close(self) -> None:
        """Kill the whole fleet."""
        if self._workers:
            self.client.kill(list(self._workers))
            self._workers.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
