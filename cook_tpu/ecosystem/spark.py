"""Spark-on-Cook: the reference's Spark integration as working code.

The reference ships two applied patches adding a Cook scheduler backend
INSIDE Spark (reference: ``spark/0001-Add-cook-support-for-spark-v1.5.0
.patch``, ``spark/README.md``) — Spark asks Cook for executors.  That
approach patches a specific Spark version; this module implements the
same capability the way every other cook_tpu ecosystem integration works
(and the way ``docs/ECOSYSTEM.md`` prescribes): run SPARK ITSELF as Cook
jobs — the standalone master and its workers are fleet members managed
by :class:`~cook_tpu.ecosystem.service_farm.ServiceFarm`, and
applications are ``spark-submit`` Cook jobs pointed at the resolved
``spark://host:port`` master URL.  No Spark fork, version-agnostic, and
the scheduler's fair-share/quota/preemption machinery governs Spark's
resources exactly as the reference patch intended.

``spark`` itself is only needed on the nodes that run the jobs; this
module stays importable without it::

    cluster = SparkOnCook(client)
    url = cluster.start_master()          # spark://host:port
    cluster.scale(8)                      # 8 standalone workers
    cluster.submit("wordcount.py", app_args="hdfs://in hdfs://out")
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .service_farm import ServiceFarm

DEFAULT_MASTER_PORT = 7077


class SparkOnCook:
    """Deploy a standalone Spark cluster as Cook jobs.

    ``client`` is a :class:`cook_tpu.client.JobClient` (or the native
    jobclient wrapper — anything with submit/query/kill/jobs).
    """

    def __init__(self, client, name: str = "spark",
                 pool: Optional[str] = None,
                 master_spec: Optional[Dict] = None,
                 worker_spec: Optional[Dict] = None,
                 master_port: int = DEFAULT_MASTER_PORT,
                 spark_class_cmd: str = "spark-class",
                 spark_submit_cmd: str = "spark-submit"):
        self.client = client
        self.name = name
        self.pool = pool
        self.master_port = master_port
        self._spark_submit_cmd = spark_submit_cmd
        mspec = dict(master_spec or {"cpus": 1.0, "mem": 2048.0})
        mspec.setdefault("name", f"{name}-master")
        # two host ports: the RPC endpoint workers/apps dial (PORT0) and
        # the web UI (PORT1); the launch path assigns them and exports
        # PORTn into the task env, so the master must bind THOSE
        mspec.setdefault("ports", 2)
        self._master_farm = ServiceFarm(
            client, f"{name}-master",
            lambda i: (f"{spark_class_cmd} "
                       "org.apache.spark.deploy.master.Master "
                       f"--host $(hostname) --port ${{PORT0:-{master_port}}} "
                       "--webui-port ${PORT1:-0}"),
            spec=mspec, pool=pool)
        self._master_uuid: Optional[str] = None
        self._master_url: Optional[str] = None
        wspec = dict(worker_spec or {"cpus": 2.0, "mem": 4096.0})
        wspec.setdefault("name", f"{name}-worker")
        wspec.setdefault("ports", 1)
        # the worker advertises exactly the cpus/mem Cook allotted it, so
        # Spark's view of the fleet equals the scheduler's accounting —
        # which is only possible for whole cores (--cores is an int), so
        # fractional worker cpus are refused instead of silently
        # over-advertising a rounded-up core
        cpus = float(wspec.get("cpus", 1))
        if cpus < 1 or cpus != int(cpus):
            raise ValueError(
                f"spark worker cpus must be a whole number >= 1 "
                f"(got {cpus}): Spark's --cores cannot advertise a "
                "fractional allotment")
        w_cores = int(cpus)
        w_mem = max(256, int(wspec.get("mem", 1024)))
        self._workers = ServiceFarm(
            client, f"{name}-workers",
            lambda i: (f"{spark_class_cmd} "
                       "org.apache.spark.deploy.worker.Worker "
                       f"--cores {w_cores} --memory {w_mem}M "
                       "--port ${PORT0:-0} "
                       f"{self._master_placeholder()}"),
            spec=wspec, pool=pool)

    def _master_placeholder(self) -> str:
        return self._master_url or "$COOK_SPARK_MASTER"

    # -------------------------------------------------------------- master
    def start_master(self, timeout_s: float = 60.0) -> str:
        """Submit the master job (if needed) and resolve its
        ``spark://host:port`` URL from the running instance."""
        self._master_uuid, host, ports = \
            self._master_farm.start_singleton(timeout_s=timeout_s)
        port = ports[0] if ports else self.master_port
        self._master_url = f"spark://{host}:{port}"
        return self._master_url

    @property
    def master_url(self) -> str:
        if self._master_url is None:
            return self.start_master()
        return self._master_url

    # ------------------------------------------------------------- workers
    def scale(self, n: int) -> List[str]:
        """Converge on n standalone workers; the master is started on
        first use so worker commands carry its resolved URL."""
        if n > 0 and self._master_url is None:
            self.start_master()
        return self._workers.scale(n)

    def wait_workers(self, n: int, timeout_s: float = 60.0) -> None:
        self._workers.wait_running(n, timeout_s=timeout_s)

    # -------------------------------------------------------- applications
    def submit(self, application: str, app_args: str = "",
               spec: Optional[Dict] = None,
               submit_args: str = "") -> str:
        """Run ``spark-submit`` against this cluster as a Cook job and
        return its job uuid: the driver's lifecycle (retries, kill, wait,
        quota) is Cook's, exactly like every other job."""
        job_spec = dict(spec or {"cpus": 1.0, "mem": 2048.0})
        job_spec.setdefault("name", f"{self.name}-app")
        job_spec["command"] = (
            f"{self._spark_submit_cmd} --master {self.master_url} "
            + (f"{submit_args} " if submit_args else "")
            + application + (f" {app_args}" if app_args else ""))
        if self.pool and "pool" not in job_spec:
            job_spec["pool"] = self.pool
        [uuid] = self.client.submit([job_spec])
        return uuid

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        """Tear the fleet down: workers first, then the master."""
        self._workers.close()
        self._master_farm.close()
        self._master_url = None

    def __enter__(self) -> "SparkOnCook":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
