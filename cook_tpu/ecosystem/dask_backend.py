"""Dask cluster backend: the reference's ``dask_cook.CookCluster`` design
(reference: dask/docs/design.md — a docs-only proposal there) implemented.

Architecture per the design doc: the Dask *scheduler node* and all *worker
nodes* run as Cook jobs; the client connects to the scheduler's address.
API shape matches the doc's examples::

    with CookCluster(client) as cluster:
        cluster.scale(20)            # add/remove workers
        from dask.distributed import Client
        client = Client(cluster.scheduler_address)

``dask`` itself is only needed on the nodes running the jobs (and by
:meth:`adapt`); this module stays importable without it.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..client import TERMINAL_STATES
from .service_farm import ServiceFarm

DEFAULT_SCHEDULER_PORT = 8786


class CookCluster:
    """Deploy a Dask cluster as Cook jobs.

    ``client`` is a :class:`cook_tpu.client.JobClient` (or the native
    jobclient wrapper — anything with submit/query/kill/jobs).
    """

    def __init__(self, client, name: str = "dask",
                 pool: Optional[str] = None,
                 scheduler_spec: Optional[Dict] = None,
                 worker_spec: Optional[Dict] = None,
                 scheduler_port: int = DEFAULT_SCHEDULER_PORT,
                 scheduler_cmd: str = "dask-scheduler",
                 worker_cmd: str = "dask-worker"):
        self.client = client
        self.name = name
        self.scheduler_port = scheduler_port
        sspec = dict(scheduler_spec or {"cpus": 1.0, "mem": 2048.0})
        sspec.setdefault("name", f"{name}-scheduler")
        # one host port for the scheduler endpoint (compiled into the task
        # env as PORT0 by the launch path)
        sspec.setdefault("ports", 1)
        # the launch path assigns the host port and exports it as PORT0;
        # the scheduler must listen on THAT port or workers would connect
        # to a port nothing listens on — fall back to scheduler_port when
        # the backend assigns none
        self._sched_farm = ServiceFarm(
            client, f"{name}-scheduler",
            lambda i: (f"{scheduler_cmd} "
                       f"--port ${{PORT0:-{scheduler_port}}}"),
            spec=sspec, pool=pool)
        self._scheduler_uuid: Optional[str] = None
        self._scheduler_address: Optional[str] = None
        wspec = dict(worker_spec or {"cpus": 1.0, "mem": 2048.0})
        wspec.setdefault("name", f"{name}-worker")
        # one host port per worker, bound as its dask listening port: the
        # instance's recorded ports then equal the port in the address dask
        # hands back to scale_down, so co-located workers are
        # distinguishable (a hostname-only match would kill the wrong one)
        wspec.setdefault("ports", 1)
        self._worker_cmd = worker_cmd
        self._workers = ServiceFarm(
            client, f"{name}-workers",
            lambda i: (f"{worker_cmd} {self._address_placeholder()}"
                       " --worker-port ${PORT0:-0}"),
            spec=wspec, pool=pool)
        self._adaptive = None

    def _address_placeholder(self) -> str:
        return self._scheduler_address or "$COOK_DASK_SCHEDULER"

    # ------------------------------------------------------------ scheduler
    def start_scheduler(self, timeout_s: float = 60.0) -> str:
        """Submit the scheduler job (if needed) and resolve its address from
        the running instance's hostname."""
        self._scheduler_uuid, host, ports = \
            self._sched_farm.start_singleton(timeout_s=timeout_s)
        port = ports[0] if ports else self.scheduler_port
        self._scheduler_address = f"tcp://{host}:{port}"
        return self._scheduler_address

    @property
    def scheduler_address(self) -> str:
        if self._scheduler_address is None:
            return self.start_scheduler()
        return self._scheduler_address

    # -------------------------------------------------------------- workers
    def scale(self, n: int):
        """Converge on n workers (design.md: ``cluster.scale(20)``).  The
        scheduler is started on first use so worker commands carry its
        resolved address."""
        if n > 0 and self._scheduler_address is None:
            self.start_scheduler()
        return self._workers.scale(n)

    def adapt(self, minimum: int = 0, maximum: int = 16):
        """Dynamic sizing (design.md: ``cluster.adapt()``).  With
        ``dask.distributed`` importable this returns dask's own ``Adaptive``
        wired to this cluster; otherwise it applies the minimum bound and
        records the range for an external autoscaler."""
        self._adaptive = (minimum, maximum)
        try:
            from distributed.deploy.adaptive import Adaptive  # type: ignore
        except Exception:
            # only enforce the LOWER bound — never shrink a healthy fleet
            # that is already within [minimum, maximum]
            target = max(minimum, self._workers.size())
            if len(self._workers.scale(target)) < minimum:
                raise RuntimeError("could not reach adapt minimum")
            return self._adaptive
        return Adaptive(self, minimum=minimum, maximum=maximum)

    # dask's Adaptive calls these on its cluster handle
    def scale_up(self, n: int):  # pragma: no cover - requires dask
        self.scale(n)

    def scale_down(self, workers):  # pragma: no cover - requires dask
        """Adaptive hands back dask worker ADDRESSES (tcp://host:port);
        map them to farm job uuids via each job's latest instance before
        killing.  Two workers can share one host, so a plain hostname
        match would kill the whole host's fleet when one worker is
        retired: prefer an exact (host, port) match against the
        instance's assigned ports, and otherwise kill at most as many
        co-located members as addresses were requested for that host
        (newest first)."""
        want = {}  # host -> list of requested ports (None = unknown)
        for w in workers:
            addr = str(w)
            if "://" in addr:
                addr = addr.split("://", 1)[1]
            host, _, port = addr.rpartition(":")
            if not host:
                host, port = addr, ""
            want.setdefault(host, []).append(
                int(port) if port.isdigit() else None)
        by_host = {}  # host -> [(farm_index, uuid, instance_ports)]
        idx_of = dict(zip(self._workers.fleet(),
                          range(len(self._workers.fleet()))))
        for j in self.client.query(self._workers.fleet()):
            insts = j.get("instances") or []
            if not insts or j.get("state") in TERMINAL_STATES:
                continue
            inst = insts[-1]
            host = inst.get("hostname")
            if host in want:
                by_host.setdefault(host, []).append(
                    (idx_of.get(j["uuid"], 0), j["uuid"],
                     set(inst.get("ports") or [])))
        doomed = []
        for host, ports in want.items():
            cands = sorted(by_host.get(host, []), reverse=True)  # newest 1st
            # two passes: every exact port match claims its worker FIRST, so
            # an unknown-port address's fallback can never steal (then
            # cascade onto) a worker another address names exactly
            unmatched = []
            for port in ports:
                hit = next((c for c in cands
                            if port is not None and port in c[2]), None)
                if hit is not None:
                    cands.remove(hit)
                    doomed.append(hit[1])
                else:
                    unmatched.append(port)
            for _ in unmatched:
                if cands:
                    doomed.append(cands.pop(0)[1])  # newest co-resident
        self._workers.kill_members(doomed)

    def workers_status(self) -> Dict[str, str]:
        return self._workers.status()

    def close(self) -> None:
        self._workers.close()
        self._sched_farm.close()
        self._scheduler_address = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
