"""Ecosystem integrations: run third-party distributed frameworks on
cook_tpu (reference: dask/docs/design.md — CookCluster API for Dask;
spark/ — patches running Spark executors as Cook jobs).

The building block is :class:`ServiceFarm` — a manager for N long-running
service jobs (scale up/down, status, teardown) over the REST client —
which the Dask backend (:mod:`cook_tpu.ecosystem.dask_backend`) and the
Spark standalone deployment (:mod:`cook_tpu.ecosystem.spark`) drive.
"""

from .service_farm import ServiceFarm  # noqa: F401
from .dask_backend import CookCluster  # noqa: F401
from .spark import SparkOnCook  # noqa: F401
