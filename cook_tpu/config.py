"""Framework configuration.

Mirrors the behavior-bearing knobs of the reference's EDN config system
(reference: scheduler/src/cook/config.clj:231-798), as nested dataclasses.
Per-pool scheduler selection follows the reference's pool-regex scheme
(config.clj:121,798): the matcher backend is chosen per pool, with ``cpu``
as the no-accelerator fallback (BASELINE.json north star).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Pattern


@dataclass
class MatcherConfig:
    """Per-pool matcher knobs (reference: default-fenzo-scheduler-config
    config.clj:110-117)."""

    # "auto" = greedy scan up to ``auto_large_j_threshold`` considerable
    # jobs, then waterfill or auction per ``auto_packing`` (VERDICT r1 #9:
    # large-J backend selection is automatic per pool size);
    # "tpu-greedy" = bit-exact greedy scan kernel; "tpu-auction" = top-K
    # adaptive auction + waterfill tail; "tpu-waterfill" = prefix-packing
    # kernel with no J x H work at all; "cpu" = numpy fallback;
    # "tpu-megakernel" = single-launch Pallas fused cycle (rank +
    # admission + match + gang reduce in one kernel, ops/pallas_cycle.py;
    # interpret-mode on CPU — bit-identical to the fused XLA driver, and
    # what "auto" prefers at the CYCLE level on a real TPU backend).
    backend: str = "auto"
    auto_large_j_threshold: int = 2000
    # what "auto" optimizes for ABOVE the threshold
    # (docs/PLACEMENT_QUALITY.md policy table):
    #   "throughput" -> waterfill: lowest latency, full placement,
    #                   looser packing (mean binding-dim util 0.82);
    #   "tight"      -> adaptive auction + waterfill tail: full placement
    #                   at near-greedy tightness (0.92+) for ~2.5x the
    #                   kernel latency — the reference's own default
    #                   fitness is bin-packing (cpuMemBinPacker,
    #                   config.clj:108), so pick this when consolidation
    #                   matters more than cycle latency.
    auto_packing: str = "throughput"
    # cmask rows below this density are "constrained" jobs: the auto
    # backend's waterfill path routes them to the exact greedy scan
    sparse_cmask_density: float = 0.5
    max_jobs_considered: int = 1000
    # head-of-queue fairness backoff (scheduler.clj:1613-1651)
    scaleback: float = 0.95
    floor_iterations_before_warn: int = 10
    floor_iterations_before_reset: int = 1000
    # auction-kernel shape knobs.  num_refresh is an UPPER BOUND: the
    # refresh loop is adaptive — it exits once a full pass admits fewer
    # than auction_min_refresh_gain new jobs (NOT zero: the waterfill
    # tail places the residue without J x H work), so a generous bound
    # costs nothing on easy workloads and lets contended ones converge
    # (docs/PLACEMENT_QUALITY.md)
    auction_num_prefs: int = 16
    auction_num_rounds: int = 8
    auction_num_refresh: int = 64
    # refresh-pass exit: stop once a full pass admits fewer than this
    # many new jobs (the waterfill tail places the residue without J x H
    # work; crawling passes for tail gains would burn the whole budget)
    auction_min_refresh_gain: int = 16
    waterfill_num_rounds: int = 32
    # tightness-improving migration rounds after waterfill converges
    # (upper bound; exits when no move lands)
    waterfill_num_compaction: int = 16

    def __post_init__(self):
        # validate/migrate at CONFIG time, not per match cycle: a typo'd
        # backend raising inside the cycle would silently zero out the
        # pool's scheduling instead of failing the daemon's config load
        if self.backend == "tpu-auction-pallas":
            # LOGGED deprecation with a metric increment (not a silent
            # rewrite): operators grep /metrics for
            # cook_config_deprecated_total to find stale configs before
            # the alias is dropped for good
            import logging
            logging.getLogger(__name__).warning(
                "DEPRECATED matcher backend tpu-auction-pallas was "
                "removed (docs/PLACEMENT_QUALITY.md); rewriting to "
                "tpu-auction — update the config, this alias will stop "
                "working in a future release")
            from .utils.metrics import registry as _registry
            _registry.counter_inc(
                "cook_config_deprecated",
                labels={"knob": "matcher.backend",
                        "value": "tpu-auction-pallas"})
            self.backend = "tpu-auction"
        if self.backend not in ("auto", "tpu-greedy", "tpu-auction",
                                "tpu-waterfill", "tpu-megakernel", "cpu"):
            raise ValueError(f"unknown matcher backend {self.backend!r}")
        if self.auto_packing not in ("throughput", "tight"):
            raise ValueError(f"unknown auto_packing "
                             f"{self.auto_packing!r} (throughput|tight)")


@dataclass
class RebalancerConfig:
    """Preemption-cycle parameters (reference: rebalancer.clj:535-557
    dynamic Datomic params)."""

    enabled: bool = True
    interval_seconds: float = 120.0
    safe_dru_threshold: float = 1.0
    min_dru_diff: float = 0.5
    max_preemption: int = 64


@dataclass
class OffensiveJobLimits:
    """A job is offensive iff its required mem or cpus exceeds these limits;
    offensive jobs are stifled out of the rank queue and aborted
    (reference: filter-offensive-jobs scheduler.clj:2205-2229)."""

    memory_gb: float = float("inf")
    cpus: float = float("inf")


@dataclass
class PoolQuota:
    """Pool-level global caps (reference: tools.clj global-pool-quota)."""

    cpus: float = float("inf")
    mem: float = float("inf")
    gpus: float = float("inf")
    count: float = float("inf")


@dataclass
class TaskConstraints:
    """Submission-time per-task limits (reference: config.clj:398-407
    :task-constraints defaults + validate-and-munge-job rest/api.clj:1070-1096).
    ``None`` disables a check; the reference's conservative defaults for the
    resource caps are commented — operators opt in because the right cap is
    deployment-specific."""

    retry_limit: Optional[int] = 20          # config.clj:403
    max_ports: Optional[int] = 5             # config.clj:405
    cpus: Optional[float] = None             # reference default: 4
    memory_gb: Optional[float] = None        # reference default: 12
    command_length_limit: Optional[int] = None
    # docker parameter allow-list; None = the conservative built-in
    # default (rest/api.py DEFAULT_DOCKER_PARAMETERS_ALLOWED — benign
    # task-shape keys only, privilege-bearing flags denied)
    docker_parameters_allowed: Optional[List[str]] = None


@dataclass
class SloConfig:
    """Service-level objectives published by the monitor sweep
    (sched/monitor.py) as burn-rate gauges on /metrics.

    Burn rate = breach fraction / error budget: 1.0 means errors arrive
    exactly at the rate that exhausts the budget over the SLO window,
    >1 burns faster (page), <1 is healthy.  Objectives are deployment
    policy, so both knobs are plain config."""

    # a pending job older than this breaches the queue-latency SLO
    queue_latency_objective_s: float = 300.0
    # a scheduler cycle slower than this breaches the cycle-duration SLO
    cycle_duration_objective_s: float = 1.0
    # allowed breach fraction (0.01 = 99% of cycles/jobs within objective)
    error_budget: float = 0.01
    # how many recent flight-recorder cycles the cycle-duration burn
    # rate is computed over
    cycle_window: int = 100
    # per-user metric families are capped at this many distinct user
    # label values per pool (top-K by usage; the tail folds into an
    # "other" series) so fairness gauges can't blow up the Prometheus
    # registry at millions-of-users scale (utils/metrics.py label caps,
    # cook_metrics_dropped_labels_total)
    max_user_series: int = 1000
    # a REST request slower than this breaches its endpoint-latency SLO
    # (per-endpoint burn rates off the serving-plane RED metrics,
    # rest/instrument.py; docs/OBSERVABILITY.md)
    endpoint_latency_objective_s: float = 0.5


@dataclass
class FaultInjectionConfig:
    """Deterministic fault injection (utils/faults.py).  Off by default;
    arming is an operator/chaos decision.  ``points`` maps fault-point
    name -> {"probability": p, "schedule": [call indices],
    "max_fires": n} (see utils/faults.py for the point registry)."""

    enabled: bool = False
    seed: int = 0
    points: Dict[str, Dict] = field(default_factory=dict)


@dataclass
class ReplicationConfig:
    """Socket journal replication + coordinated failover knobs (the
    daemon's ``"replication"`` conf section; state/replication.py,
    docs/DEPLOY.md).  Parsed through :meth:`from_conf` so a typo'd knob
    fails the BOOT instead of silently running with defaults while the
    operator believes durability/failover policy is set."""

    listen_port: int = 0               # 0 = pick a free port, publish it
    sync: bool = True                  # commit = fsynced on every synced
    #                                    follower (False = async mirror)
    ack_timeout_seconds: float = 5.0
    min_sync_followers: int = 0        # > 0 = CP mode (refuse lone commits)
    advertise_host: str = ""           # "" = the daemon's bind host
    # coordinated promotion (quorum-aware failover): how long the
    # election winner waits collecting candidate positions before
    # deciding whether it must first pull a delta from a better-synced
    # peer (Raft's vote comparison expressed over the election medium)
    candidacy_window_seconds: float = 1.0
    # how often standbys publish their replication position
    position_interval_seconds: float = 0.5
    # a candidate position older than this is a dead node's ghost and is
    # ignored by the ranking (and by catch-up failure handling)
    position_stale_seconds: float = 10.0
    # how long the winner tries to pull the delta from a live
    # better-synced peer before failing the takeover (exit nonzero so
    # that peer can win instead)
    catchup_timeout_seconds: float = 30.0

    @classmethod
    def from_conf(cls, conf: Dict) -> "ReplicationConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown replication key {k!r}")
            default = getattr(cfg, k)
            if isinstance(default, bool):
                # bool("false") is True — a templated string here would
                # silently invert the operator's durability policy
                if not isinstance(v, bool):
                    raise ValueError(
                        f"replication key {k!r} must be a JSON boolean, "
                        f"got {v!r}")
                setattr(cfg, k, v)
            else:
                setattr(cfg, k, type(default)(v))
        return cfg


@dataclass
class ServingConfig:
    """Serving-plane scale-out knobs (the daemon's ``"serving"`` conf
    section inside ``"scheduler"``; boot-validated like PipelineConfig):
    the follower read fleet (state/read_replica.py — standbys serve
    bounded-staleness GETs from a live journal-applied store) and the
    leader's group-commit admission batching (state/store.py — concurrent
    write transactions share ONE journal fsync + ONE replication ack
    round).  docs/DEPLOY.md "read fleet", docs/PERFORMANCE.md
    "group commit"."""

    #: standbys answer job/group/instance/queue/unscheduled/timeline GETs
    #: from their live-applied mirror (staleness surfaced per response via
    #: X-Cook-Replication-Offset / -Age-Ms) instead of 307-redirecting.
    #: Writes always redirect to the leader.
    follower_reads: bool = True
    #: how long the follower's apply loop sleeps between journal polls —
    #: the steady-state staleness floor (the mirror itself is pushed by
    #: the leader; this only bounds the local apply cadence)
    apply_interval_seconds: float = 0.02
    #: read-your-writes: a follower behind a client's X-Cook-Min-Offset
    #: token waits up to this long for its mirror to catch up before
    #: 307-redirecting the read to the leader
    min_offset_wait_seconds: float = 1.0
    #: leader write path: amortize journal fsync + replication ack across
    #: concurrent committers (one durability round per batch, outcomes
    #: demultiplexed per transaction — incl. the PR 3 indeterminate
    #: contract).  Engages only on stores with a journal attached.
    group_commit: bool = True
    #: coalescing window: after the first waiter arrives the committer
    #: waits this long for stragglers before draining the batch.  0 =
    #: drain immediately (whatever accumulated during the previous
    #: round's fsync/ack still batches).
    group_commit_window_ms: float = 0.5
    #: hard per-batch cap (a full batch drains without waiting)
    group_commit_max_batch: int = 256

    def __post_init__(self):
        if not isinstance(self.group_commit_max_batch, int) \
                or self.group_commit_max_batch < 1:
            raise ValueError("serving group_commit_max_batch must be an "
                             f"int >= 1, got {self.group_commit_max_batch!r}")
        for k in ("apply_interval_seconds", "min_offset_wait_seconds",
                  "group_commit_window_ms"):
            if float(getattr(self, k)) < 0:
                raise ValueError(f"serving {k} must be >= 0")

    @classmethod
    def from_conf(cls, conf: Dict) -> "ServingConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown serving key {k!r}")
            default = getattr(cfg, k)
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"serving key {k!r} must be a JSON "
                                     f"boolean, got {v!r}")
                setattr(cfg, k, v)
            else:
                setattr(cfg, k, type(default)(v))
        cfg.__post_init__()
        return cfg


@dataclass
class FleetConfig:
    """Fleet observability plane (sched/fleet.py; the daemon's
    ``"fleet"`` conf section, boot-validated like the sections around
    it): metrics federation over the election candidate registry,
    cross-process trace stitching, and the saturation-signal layer —
    docs/OBSERVABILITY.md "Debugging the fleet", docs/DEPLOY.md
    scrape topology."""

    #: run the FleetScraper at all (the monitor sweep drives it); off =
    #: /metrics/fleet serves only this process and /debug/fleet reports
    #: federation disabled
    enabled: bool = True
    #: minimum seconds between federation sweeps — the monitor sweep
    #: fires more often than this; the scraper self-gates
    scrape_interval_seconds: float = 10.0
    #: per-member /metrics fetch timeout; an unreachable member costs at
    #: most this per sweep and surfaces as ``up=0`` data, never a gap
    scrape_timeout_seconds: float = 2.0
    #: per-member /debug/trace/spans fetch timeout for the stitched
    #: fleet trace export
    trace_fanout_timeout_seconds: float = 2.0
    #: federated series kept per member per sweep; the excess is folded
    #: into ``cook_fleet_dropped_series{instance=}`` (the PR 7
    #: cardinality discipline applied at fleet scale)
    max_series_per_member: int = 4096
    #: hard cap on members per sweep (registry entries past it are
    #: skipped and counted) — a corrupt candidate registry must not turn
    #: one sweep into an unbounded fan-out
    max_members: int = 64
    #: static extra members ``[{"instance":, "url":, "role":}]`` merged
    #: over the candidate registry — agents or off-registry processes
    #: that expose /metrics but never campaign
    members: List[Dict] = field(default_factory=list)
    #: saturation gauges at/above this are "hot" on /debug/health +
    #: /debug/fleet — the red line the adaptive-admission consumer
    #: (ROADMAP item 3) will shed against
    saturation_red_line: float = 0.8
    #: follower-staleness normalization: saturation 1.0 == the read
    #: view's apply age reaching this (also flips a follower's
    #: /debug/health to unhealthy)
    staleness_red_line_seconds: float = 5.0
    #: audit-queue normalization: saturation 1.0 == this many durable
    #: audit events still buffered for the journal
    audit_queue_red_line: int = 4096
    #: journal-head normalization: saturation 1.0 == the live journal
    #: growing to this many bytes since the last checkpoint compaction
    journal_head_red_line_bytes: int = 256 * 1024 * 1024

    def __post_init__(self):
        for k in ("scrape_interval_seconds", "scrape_timeout_seconds",
                  "trace_fanout_timeout_seconds",
                  "staleness_red_line_seconds"):
            if float(getattr(self, k)) <= 0:
                raise ValueError(f"fleet {k} must be > 0")
        for k in ("max_series_per_member", "max_members",
                  "audit_queue_red_line", "journal_head_red_line_bytes"):
            v = getattr(self, k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(f"fleet {k} must be an int >= 1, "
                                 f"got {v!r}")
        if not 0.0 < float(self.saturation_red_line) <= 1.0:
            raise ValueError("fleet saturation_red_line must be in "
                             f"(0, 1], got {self.saturation_red_line!r}")
        for m in self.members:
            if not isinstance(m, dict) or not m.get("url"):
                raise ValueError("fleet members entries must be objects "
                                 f"with a \"url\", got {m!r}")

    @classmethod
    def from_conf(cls, conf: Dict) -> "FleetConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown fleet key {k!r}")
            default = getattr(cfg, k)
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"fleet key {k!r} must be a JSON "
                                     f"boolean, got {v!r}")
                setattr(cfg, k, v)
            elif k == "members":
                if not isinstance(v, list):
                    raise ValueError("fleet members must be a list of "
                                     "{instance, url, role} objects")
                cfg.members = [dict(m) for m in v]
            else:
                setattr(cfg, k, type(default)(v))
        cfg.__post_init__()
        return cfg


@dataclass
class PartitionConfig:
    """Partitioned write plane (state/partition.py; the daemon's
    ``"partitions"`` conf section inside ``"scheduler"``, boot-validated
    like the sections around it).  ``count=1`` is the compatibility
    default: the daemon keeps the classic single Store and nothing on
    the wire changes.  ``count>1`` shards the store + journal into
    per-pool-group partitions, each with its own fsync stream,
    group-commit stage, and lease claim (docs/DEPLOY.md "partitioned
    write plane")."""

    #: number of write-plane partitions (journals, fsync streams,
    #: group-commit stages, leases)
    count: int = 1
    #: explicit pool → partition routing (the config-declared pool
    #: groups); pools not listed hash deterministically.  Validated at
    #: boot: every index must be in [0, count).
    pools: Dict[str, int] = field(default_factory=dict)
    #: staleness bound of the cross-partition per-user summary exchange
    #: (quota enforcement / global DRU view read through it)
    summary_max_age_seconds: float = 1.0
    #: controller shard processes (ISSUE 19: one partition block = one
    #: process = one mesh shard).  0 = unsharded (this daemon owns every
    #: partition in-process, the classic plane); N > 0 declares an
    #: N-process topology and must divide ``count`` evenly.  Validated
    #: against the mesh pool layout at boot
    #: (parallel.mesh.validate_shard_alignment).
    shards: int = 0
    #: operator-declared pool -> mesh shard table, cross-checked at boot
    #: against the PartitionMap routing — a pool declared on a shard
    #: other than the one its write-plane partition belongs to is a
    #: config error (double-owned / orphaned resident buffers), refused
    #: at daemon start.
    shard_pools: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        if not isinstance(self.count, int) or isinstance(self.count, bool) \
                or self.count < 1:
            raise ValueError(
                f"partitions count must be an int >= 1, got {self.count!r}")
        for pool, idx in (self.pools or {}).items():
            if not isinstance(idx, int) or isinstance(idx, bool) \
                    or not 0 <= idx < self.count:
                raise ValueError(
                    f"partitions.pools[{pool!r}] must be an int in "
                    f"[0, {self.count}), got {idx!r}")
        if float(self.summary_max_age_seconds) < 0:
            raise ValueError(
                "partitions summary_max_age_seconds must be >= 0")
        if not isinstance(self.shards, int) or isinstance(self.shards, bool) \
                or self.shards < 0:
            raise ValueError(
                f"partitions shards must be an int >= 0, got {self.shards!r}")
        if self.shards:
            if self.count % self.shards != 0:
                raise ValueError(
                    f"partitions.count ({self.count}) must divide evenly "
                    f"over partitions.shards ({self.shards}): every "
                    "controller shard owns an equal contiguous partition "
                    "block")
        for pool, idx in (self.shard_pools or {}).items():
            if not isinstance(idx, int) or isinstance(idx, bool) \
                    or idx < 0 or (self.shards and idx >= self.shards):
                raise ValueError(
                    f"partitions.shard_pools[{pool!r}] must be an int in "
                    f"[0, {self.shards or '#shards'}), got {idx!r}")
        if self.shard_pools and not self.shards:
            raise ValueError(
                "partitions.shard_pools declared without partitions.shards")

    @classmethod
    def from_conf(cls, conf: Dict) -> "PartitionConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown partitions key {k!r}")
            if k == "pools":
                if not isinstance(v, dict):
                    raise ValueError("partitions.pools must be a map of "
                                     "pool name to partition index")
                cfg.pools = {str(p): i for p, i in v.items()}
            elif k == "shard_pools":
                if not isinstance(v, dict):
                    raise ValueError("partitions.shard_pools must be a map "
                                     "of pool name to mesh shard index")
                cfg.shard_pools = {str(p): i for p, i in v.items()}
            else:
                default = getattr(cfg, k)
                setattr(cfg, k, type(default)(v))
        cfg.__post_init__()
        return cfg


@dataclass
class PipelineConfig:
    """Pipelined fused-cycle driver + compile-warmup knobs (the daemon's
    ``"pipeline"`` conf section; sched/pipeline.py, docs/PERFORMANCE.md).
    Parsed through :meth:`from_conf` so a typo'd knob fails the BOOT like
    ReplicationConfig — a silently-defaulted depth would let an operator
    believe the sync path is pinned when it isn't (or vice versa)."""

    #: cycles in flight concurrently.  0 = strictly synchronous
    #: FusedCycleDriver (today's pre-pipeline behavior, bit-for-bit);
    #: 2 = the production default: while cycle k's launches are applied
    #: on host, cycle k+1's fused kernel is already computing on device
    #: against the pre-apply snapshot (Omega-style optimistic cycles,
    #: reconciled host-side before launch).  >2 is allowed but adds
    #: speculation: intermediate unfetched cycles' candidates can't be
    #: masked out of later stages, so the conflict-drop rate rises.
    depth: int = 2
    #: JAX persistent compilation cache directory ("" = disabled): fused
    #: cycle executables survive process restarts, so a failover or
    #: rolling restart re-traces but never re-COMPILES (the 16.5 s
    #: first-call spikes in BENCH_r05 land at boot, inside warmup, or
    #: not at all — never inside a live cycle).
    compilation_cache_dir: str = ""
    #: boot-time warmup sweep: pre-compile (and execute once, with
    #: zeroed inputs) the compact fused cycle at the bucket grid implied
    #: by these design points.  0 disables warmup.  ``warmup_tasks`` /
    #: ``warmup_hosts`` are the expected steady-state maxima (padded up
    #: to their power-of-two buckets, ops/padding.py); ``warmup_users``
    #: sizes the per-user table bucket (minimum 8).
    warmup_tasks: int = 0
    warmup_hosts: int = 0
    warmup_users: int = 8
    #: True = warm EVERY bucket up to the targets (cold-start ramp
    #: traffic hits warm executables at every scale); False = only the
    #: target buckets.
    warmup_sweep: bool = False
    #: also warm the gpu DRU-mode variant of the cycle (pools with
    #: dru_mode=gpu compile a separate kernel)
    warmup_gpu: bool = False

    def __post_init__(self):
        if not isinstance(self.depth, int) or self.depth < 0:
            raise ValueError(
                f"pipeline depth must be an int >= 0, got {self.depth!r}")
        for k in ("warmup_tasks", "warmup_hosts", "warmup_users"):
            v = getattr(self, k)
            if not isinstance(v, int) or v < 0:
                raise ValueError(f"pipeline {k} must be an int >= 0, "
                                 f"got {v!r}")

    @classmethod
    def from_conf(cls, conf: Dict) -> "PipelineConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown pipeline key {k!r}")
            default = getattr(cfg, k)
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"pipeline key {k!r} must be a JSON "
                                     f"boolean, got {v!r}")
                setattr(cfg, k, v)
            else:
                setattr(cfg, k, type(default)(v))
        cfg.__post_init__()
        return cfg


@dataclass
class AuditConfig:
    """Per-job scheduling audit trail knobs (utils/audit.py; the daemon's
    ``"audit"`` conf section, validated like PipelineConfig so a typo'd
    knob fails the boot).  docs/OBSERVABILITY.md."""

    #: record per-job decision events at all.  Off = the trail records
    #: nothing and `cs why` falls back to the stateless explainer.
    enabled: bool = True
    #: cap on jobs with a live event lane; the oldest-CREATED lane is
    #: evicted past this (insertion order, not LRU — the hot path skips
    #: per-event touch bookkeeping; the earliest submissions are the
    #: likeliest terminal)
    max_jobs: int = 100_000
    #: per-job event cap; repeated advisory events (ranked position,
    #: same-reason skips) coalesce into one counted event, and lifecycle
    #: events are evicted last
    per_job_events: int = 64
    #: journal durable events (lifecycle atomically with their txn,
    #: advisory once per cycle) so timelines survive leader failover;
    #: a store without an attached journal ignores this
    journal: bool = True

    def __post_init__(self):
        for k in ("max_jobs", "per_job_events"):
            v = getattr(self, k)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"audit {k} must be an int >= 1, "
                                 f"got {v!r}")

    @classmethod
    def from_conf(cls, conf: Dict) -> "AuditConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown audit key {k!r}")
            default = getattr(cfg, k)
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"audit key {k!r} must be a JSON "
                                     f"boolean, got {v!r}")
                setattr(cfg, k, v)
            else:
                setattr(cfg, k, type(default)(v))
        cfg.__post_init__()
        return cfg


@dataclass
class HttpConfig:
    """Serving-plane request observability knobs (rest/instrument.py;
    the daemon's ``"http"`` conf section, boot-validated like
    PipelineConfig so a typo'd knob fails the boot).
    docs/OBSERVABILITY.md."""

    #: request instrumentation master switch: ``http.request`` spans, the
    #: per-endpoint RED metrics, and the /debug/requests capture rings.
    #: Request ids (X-Cook-Request-Id) are always minted/echoed — they
    #: are part of the error contract, not observability overhead.
    observe: bool = True
    #: recent-request ring size (every request, newest evicts oldest)
    request_log: int = 256
    #: a request at least this slow is captured in the slow ring with its
    #: per-phase breakdown ("why was this POST slow")
    slow_request_ms: float = 500.0
    #: slow-ring size
    slow_log: int = 64

    def __post_init__(self):
        for k in ("request_log", "slow_log"):
            v = getattr(self, k)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"http {k} must be an int >= 1, "
                                 f"got {v!r}")

    @classmethod
    def from_conf(cls, conf: Dict) -> "HttpConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown http key {k!r}")
            default = getattr(cfg, k)
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"http key {k!r} must be a JSON "
                                     f"boolean, got {v!r}")
                setattr(cfg, k, v)
            else:
                setattr(cfg, k, type(default)(v))
        cfg.__post_init__()
        return cfg


@dataclass
class ElasticConfig:
    """Elastic-gang resize knobs (sched/elastic.py; the daemon's
    ``"elastic"`` conf section, boot-validated like the sections around
    it).  docs/GANG.md elasticity."""

    #: master switch: off = elastic bounds are still validated/stored
    #: but the resize plane (grow metering, grace shrinks, rebalancer
    #: shrink-instead-of-kill) never engages
    enabled: bool = True
    #: checkpoint grace between the shrink notification (SIGUSR1 +
    #: COOK_GANG_RESIZE_FILE event) and the member's kill.  0 = shed
    #: immediately (tests/sim).
    shrink_grace_seconds: float = 5.0
    #: resize-pass cadence when driven by wall-clock threads (the fused
    #: cycle also sweeps every cycle)
    resize_interval_seconds: float = 5.0

    def __post_init__(self):
        for k in ("shrink_grace_seconds", "resize_interval_seconds"):
            if float(getattr(self, k)) < 0:
                raise ValueError(f"elastic {k} must be >= 0")

    @classmethod
    def from_conf(cls, conf: Dict) -> "ElasticConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown elastic key {k!r}")
            default = getattr(cfg, k)
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"elastic key {k!r} must be a JSON "
                                     f"boolean, got {v!r}")
                setattr(cfg, k, v)
            else:
                setattr(cfg, k, type(default)(v))
        cfg.__post_init__()
        return cfg


@dataclass
class AdmissionConfig:
    """Layered admission control + saturation-driven brownout (the
    daemon's ``"admission"`` conf section inside ``"scheduler"``,
    boot-validated like the sections around it).  The front door
    (rest/api.py) token-buckets submissions per user and requests per
    IP; the monitor-driven ``sched.admission.AdmissionController`` maps
    the six ``cook_saturation`` gauges to a 0-1 admission level with
    hysteresis (DAGOR-style feedback admission) and walks the brownout
    ladder — observability detail sheds first, then reads degrade to
    bounded-stale follower serves, then low-priority writes shed, and
    committed writes + scheduling decisions never shed.  docs/DEPLOY.md
    "overload runbook", docs/ROBUSTNESS.md "brownout ladder"."""

    #: master switch: off = no submission buckets, no adaptive level,
    #: no brownout (the pre-existing launch-rate tokens still apply)
    enabled: bool = False
    #: per-user submission token refill (jobs/minute); 0 = unlimited.
    #: The ADAPTIVE level scales this down under pressure.
    submissions_per_minute: float = 0.0
    #: per-user bucket size (burst); 0 = same as submissions_per_minute
    submission_burst: float = 0.0
    #: per-IP request refill for the serving plane; 0 = fall back to the
    #: daemon's top-level ``ip_requests_per_minute`` knob (both feed the
    #: same exemption list: /metrics, /debug/*, health probes never
    #: rate-limit so observability survives the incident)
    ip_requests_per_minute: float = 0.0
    #: GLOBAL per-user pending-job cap enforced at submission across
    #: partitions by riding the bounded UserSummaryExchange per-user
    #: summaries (never job state); 0 = off
    max_user_pending: int = 0
    #: adaptive level floor: even fully saturated, this fraction of the
    #: configured refill survives (never starve to a hard zero — the
    #: metastable-failure guard: some traffic must drain to recover)
    level_floor: float = 0.1
    #: worst-gauge saturation above which the level starts declining
    engage_saturation: float = 0.8
    #: saturation below which the level recovers; the [release, engage)
    #: band is the hysteresis dead zone (no flapping at the threshold)
    release_saturation: float = 0.6
    #: per-sweep level decrement at full pressure (scaled by how far the
    #: worst gauge sits past the engage threshold)
    decrease_step: float = 0.2
    #: per-sweep level increment while below the release threshold
    #: (recovery is gradual so admitted load ramps, not steps)
    recover_step: float = 0.05
    #: brownout ladder thresholds on the admission level, strictly
    #: descending: stage 1 (advisory observability detail sheds: audit
    #: advisory-flush folds, slow-ring capture off) ...
    observability_shed_level: float = 0.75
    #: ... stage 2 (follower reads serve bounded-stale: relaxed
    #: min-offset gate, honest X-Cook-Replication-Age-Ms) ...
    stale_reads_level: float = 0.5
    #: ... stage 3 (low-priority writes shed with 429).  Committed
    #: writes and scheduling decisions degrade last or never.
    shed_writes_level: float = 0.25
    #: recovery dwell: the level must hold ABOVE a stage's threshold
    #: this long before the stage steps back down (escalation is
    #: immediate; de-escalation is damped)
    stage_hold_seconds: float = 10.0
    #: stage 3 sheds submissions whose every job has priority below this
    shed_priority_below: int = 50
    #: stage >= 2: the follower's min-offset wait gate shrinks to this
    #: fraction of serving.min_offset_wait_seconds (bounded-stale serves
    #: stop queueing reads behind replication under overload)
    relaxed_offset_wait_factor: float = 0.1

    def __post_init__(self):
        for k in ("submissions_per_minute", "submission_burst",
                  "ip_requests_per_minute", "stage_hold_seconds"):
            if float(getattr(self, k)) < 0:
                raise ValueError(f"admission {k} must be >= 0")
        if not isinstance(self.max_user_pending, int) \
                or self.max_user_pending < 0:
            raise ValueError("admission max_user_pending must be an "
                             f"int >= 0, got {self.max_user_pending!r}")
        if not isinstance(self.shed_priority_below, int):
            raise ValueError("admission shed_priority_below must be an "
                             f"int, got {self.shed_priority_below!r}")
        if not (0.0 <= self.level_floor < 1.0):
            raise ValueError("admission level_floor must be in [0, 1)")
        if not (0.0 < self.release_saturation < self.engage_saturation
                <= 1.0):
            raise ValueError(
                "admission thresholds must satisfy 0 < "
                "release_saturation < engage_saturation <= 1, got "
                f"{self.release_saturation!r} / {self.engage_saturation!r}")
        for k in ("decrease_step", "recover_step"):
            if not (0.0 < float(getattr(self, k)) <= 1.0):
                raise ValueError(f"admission {k} must be in (0, 1]")
        if not (0.0 < self.shed_writes_level < self.stale_reads_level
                < self.observability_shed_level < 1.0):
            raise ValueError(
                "admission brownout levels must be strictly descending "
                "in (0, 1): observability_shed_level > stale_reads_level "
                "> shed_writes_level")
        if not (0.0 <= self.relaxed_offset_wait_factor <= 1.0):
            raise ValueError(
                "admission relaxed_offset_wait_factor must be in [0, 1]")

    @classmethod
    def from_conf(cls, conf: Dict) -> "AdmissionConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown admission key {k!r}")
            default = getattr(cfg, k)
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"admission key {k!r} must be a JSON "
                                     f"boolean, got {v!r}")
                setattr(cfg, k, v)
            else:
                setattr(cfg, k, type(default)(v))
        cfg.__post_init__()
        return cfg


@dataclass
class StorageConfig:
    """Storage-integrity plane (the daemon's ``"storage"`` conf section;
    docs/ROBUSTNESS.md "WAL v2"): the monitor's background scrub
    incrementally re-verifies journal CRC32C frames
    (:meth:`~cook_tpu.state.store.Store.scrub`), a leader self-heals
    scrub-detected corruption by checkpointing (its memory is
    authoritative), and the boot hygiene sweep's minimum orphan age is
    tunable for shared-dir topologies."""

    #: master switch for the monitor-driven background scrub sweep
    scrub_enabled: bool = True
    #: seconds between scrub steps (each step verifies one chunk; the
    #: monitor sweep itself runs on monitor_interval_seconds, so the
    #: effective cadence is the max of the two)
    scrub_interval_seconds: float = 30.0
    #: journal bytes verified per scrub step — bounds the read burst a
    #: step may impose on the journal disk
    scrub_chunk_bytes: int = 1 << 20
    #: leader self-heal: checkpoint (fresh verified snapshot, damaged
    #: journal rotated aside) when the scrub finds corruption.  Off =
    #: detect-and-report only (the operator repairs per docs/DEPLOY.md).
    checkpoint_on_corruption: bool = True
    #: minimum age before the boot hygiene sweep unlinks an orphaned
    #: ``.tmp.`` atomic-write leftover or stale poison marker — a LIVE
    #: writer's in-flight temp in a shared dir must survive
    hygiene_min_age_seconds: float = 60.0
    #: per-peer timeout for the quarantine-and-pull repair path
    #: (state/repair.py)
    repair_timeout_seconds: float = 30.0

    def __post_init__(self):
        for k in ("scrub_interval_seconds", "hygiene_min_age_seconds"):
            if float(getattr(self, k)) < 0:
                raise ValueError(f"storage {k} must be >= 0")
        if not isinstance(self.scrub_chunk_bytes, int) \
                or self.scrub_chunk_bytes <= 0:
            raise ValueError("storage scrub_chunk_bytes must be an "
                             f"int > 0, got {self.scrub_chunk_bytes!r}")
        if float(self.repair_timeout_seconds) <= 0:
            raise ValueError("storage repair_timeout_seconds must be > 0")

    @classmethod
    def from_conf(cls, conf: Dict) -> "StorageConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown storage key {k!r}")
            default = getattr(cfg, k)
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"storage key {k!r} must be a JSON "
                                     f"boolean, got {v!r}")
                setattr(cfg, k, v)
            else:
                setattr(cfg, k, type(default)(v))
        cfg.__post_init__()
        return cfg


@dataclass
class FederationConfig:
    """Multi-cell front-door tier (the daemon's top-level
    ``"federation"`` conf section; presence of the section makes the
    process a stateless ROUTER node — no store, no journal, no
    election, no scheduler).  Boot-validated like every other section:
    a typo'd knob or malformed cell entry fails the boot, never routes
    half-configured.  docs/DEPLOY.md "multi-cell federation"."""

    #: the cells this router fronts: objects with ``id`` + ``url``
    #: (required) and optional ``tier`` (``standard``/``spot``),
    #: ``attributes`` (data-locality string pairs) and ``weight``
    #: (relative capacity for load scoring).  At least one.
    cells: List[Dict] = field(default_factory=list)
    #: job label key carrying a data-locality demand: a job labeled
    #: ``{"cell-attribute/region": "us-east"}`` (for the default
    #: ``"cell-attribute/"`` prefix) routes only to cells whose
    #: attributes match every such pair; a label naming the reserved
    #: key ``cell-attribute/cell`` pins the batch to that cell id
    locality_label_prefix: str = "cell-attribute/"
    #: staleness bound on the federated per-user summary merge — the
    #: window every global-enforcement refusal quotes (asserted: an
    #: unmeetable bound raises, never silently serves)
    summary_max_age_seconds: float = 5.0
    #: GLOBAL per-user pending-job cap across every cell (0 = off);
    #: enforced at the front door off the federated summaries
    max_user_pending: int = 0
    #: GLOBAL per-user dominant-share ceiling in [0, 1] (0 = off): a
    #: user whose dominant resource share of the federation's running
    #: total exceeds this sheds NEW submissions with 429 until usage
    #: drains — the DRU fair-share floor, lifted to the federation
    max_user_dominant_share: float = 0.0
    #: routing mode: ``"load"`` scores cells by weight, in-flight
    #: demand and saturation; ``"goodput"`` additionally replays each
    #: candidate cell's recent routed traffic through ``sim/`` and
    #: routes to argmax predicted goodput (costlier per decision)
    route_mode: str = "load"
    #: consecutive transport failures that open a cell's breaker (the
    #: whole cell's traffic then reroutes until a half-open probe heals)
    breaker_failures: int = 3
    #: seconds an open cell breaker waits before the half-open probe
    breaker_reset_seconds: float = 5.0
    #: per-proxied-request timeout against a cell
    request_timeout_seconds: float = 5.0
    #: score multiplier applied to ``spot``-tier cells so standard
    #: capacity absorbs steady demand first, in (0, 1]
    spot_penalty: float = 0.5
    #: bounded commit ledger: most recent ACCEPTED submission batches
    #: remembered per router for outage re-route and uuid->cell read
    #: routing (oldest evicted first; eviction is counted, never silent)
    ledger_max_batches: int = 10000
    #: recent routed batches replayed per candidate cell in goodput
    #: route mode
    goodput_window: int = 32

    def __post_init__(self):
        if not isinstance(self.cells, list):
            raise ValueError("federation cells must be a list of "
                             "{id, url, ...} objects")
        seen = set()
        for entry in self.cells:
            if not isinstance(entry, dict):
                raise ValueError(
                    f"federation cell entry must be an object, got "
                    f"{entry!r}")
            unknown = set(entry) - {"id", "url", "tier", "attributes",
                                    "weight"}
            if unknown:
                raise ValueError(
                    f"unknown federation cell key(s) "
                    f"{sorted(unknown)!r}")
            if not entry.get("id") or not entry.get("url"):
                raise ValueError(
                    "federation cell entries require id and url, got "
                    f"{entry!r}")
            cid = str(entry["id"])
            if "/" in cid or "," in cid:
                # "/" qualifies token entries and "," joins the vector:
                # either in a cell id would make session tokens
                # ambiguous (federation/tokens.py)
                raise ValueError(f"federation cell id {cid!r} must not "
                                 "contain '/' or ','")
            if not str(entry["url"]).startswith(("http://", "https://")):
                raise ValueError(f"federation cell {cid!r} url must be "
                                 f"http(s), got {entry['url']!r}")
            if entry.get("tier", "standard") not in ("standard", "spot"):
                raise ValueError(
                    f"federation cell {cid!r} tier must be 'standard' "
                    f"or 'spot', got {entry['tier']!r}")
            if not isinstance(entry.get("attributes", {}), dict):
                raise ValueError(f"federation cell {cid!r} attributes "
                                 "must be an object")
            if float(entry.get("weight", 1.0)) <= 0:
                raise ValueError(
                    f"federation cell {cid!r} weight must be > 0")
            if cid in seen:
                raise ValueError(
                    f"duplicate federation cell id {cid!r}")
            seen.add(cid)
        if self.route_mode not in ("load", "goodput"):
            raise ValueError("federation route_mode must be 'load' or "
                             f"'goodput', got {self.route_mode!r}")
        if not self.locality_label_prefix:
            raise ValueError(
                "federation locality_label_prefix must be non-empty")
        for k in ("summary_max_age_seconds", "breaker_reset_seconds"):
            if float(getattr(self, k)) < 0:
                raise ValueError(f"federation {k} must be >= 0")
        if float(self.request_timeout_seconds) <= 0:
            raise ValueError(
                "federation request_timeout_seconds must be > 0")
        if not isinstance(self.max_user_pending, int) \
                or self.max_user_pending < 0:
            raise ValueError("federation max_user_pending must be an "
                             f"int >= 0, got {self.max_user_pending!r}")
        if not (0.0 <= float(self.max_user_dominant_share) <= 1.0):
            raise ValueError("federation max_user_dominant_share must "
                             "be in [0, 1]")
        if not (0.0 < float(self.spot_penalty) <= 1.0):
            raise ValueError("federation spot_penalty must be in (0, 1]")
        for k in ("breaker_failures", "ledger_max_batches",
                  "goodput_window"):
            if not isinstance(getattr(self, k), int) \
                    or getattr(self, k) < 1:
                raise ValueError(f"federation {k} must be an int >= 1, "
                                 f"got {getattr(self, k)!r}")

    @classmethod
    def from_conf(cls, conf: Dict) -> "FederationConfig":
        cfg = cls()
        for k, v in conf.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown federation key {k!r}")
            default = getattr(cfg, k)
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"federation key {k!r} must be a "
                                     f"JSON boolean, got {v!r}")
                setattr(cfg, k, v)
            elif isinstance(default, list):
                if not isinstance(v, list):
                    raise ValueError(f"federation key {k!r} must be a "
                                     f"JSON array, got {v!r}")
                setattr(cfg, k, list(v))
            else:
                setattr(cfg, k, type(default)(v))
        if not cfg.cells:
            # a router fronting zero cells would accept nothing and
            # route nowhere — a config mistake, not a deployment
            raise ValueError("federation requires at least one cell "
                             "({id, url} entries under federation.cells)")
        cfg.__post_init__()
        return cfg


@dataclass
class CircuitBreakerConfig:
    """Per-compute-cluster launch circuit breaker (utils/retry.py):
    ``failure_threshold`` consecutive backend failures open the breaker
    (the matcher routes launches to healthy clusters); a half-open probe
    after ``reset_timeout_s`` discovers recovery."""

    failure_threshold: int = 5
    reset_timeout_s: float = 30.0


@dataclass
class EstimatedCompletionConfig:
    """estimated-completion constraint knobs (reference:
    config/estimated-completion-config, constraints.clj:408-432). Disabled
    unless both multiplier and host_lifetime_mins are set."""
    expected_runtime_multiplier: Optional[float] = None
    host_lifetime_mins: Optional[int] = None
    agent_start_grace_period_mins: int = 10


@dataclass
class Config:
    rank_interval_seconds: float = 5.0         # mesos.clj:108
    match_interval_seconds: float = 1.0        # target-per-pool-match-interval
    max_over_quota_jobs: int = 100             # config.clj:413-416
    # "fused": production path — one device dispatch runs rank+admission+
    # match for all pools (sched/fused.py); "split": host-driven per-pool
    # step_rank/step_match (CPU fallback, deterministic tests)
    cycle_mode: str = "fused"
    # rank straight off the incrementally-maintained columnar projection of
    # the store (state/index.py) instead of materializing entities per
    # cycle; the entity path remains the CPU-fallback/parity mode
    columnar_index: bool = True
    # keep the fused cycle's stacked [P, T] wire arrays (row permutation +
    # admission flags) RESIDENT on device across cycles, scatter-applying
    # per-cycle deltas extracted off the index's tx-event feed instead of
    # re-uploading the world (ops/delta.py; docs/PERFORMANCE.md).  Full
    # repacks happen only on compaction fences, bucket regrows, or kernel
    # faults.  Decision-identical to the rebuild path; only engages with
    # columnar_index=True (the compact wire form).
    resident_pack: bool = True
    # quantized compact wire (ops/quant.py; docs/PERFORMANCE.md wire
    # negotiation table): narrow each per-cycle h2d field to the
    # smallest dtype its domain admits THIS cycle — delta-coded i8/i16
    # rows, u16 fixed-point host stacks, bitpacked host flags — but only
    # where the round trip is bit-exact; overflowing domains ship wide
    # automatically.  Engages on the megakernel dispatch path and the
    # delta feed's scatter values; never changes a decision.
    quantized_wire: bool = True
    default_pool: str = "default"
    # pool-regex -> matcher config, first match wins (config.clj:798)
    pool_matchers: List[tuple] = field(default_factory=list)
    default_matcher: MatcherConfig = field(default_factory=MatcherConfig)
    rebalancer: RebalancerConfig = field(default_factory=RebalancerConfig)
    # pool name -> global quota; pool -> quota-group name for cross-pool caps
    pool_quotas: Dict[str, PoolQuota] = field(default_factory=dict)
    quota_groups: Dict[str, str] = field(default_factory=dict)
    quota_group_quotas: Dict[str, PoolQuota] = field(default_factory=dict)
    max_tasks_per_host: Optional[int] = None
    estimated_completion: EstimatedCompletionConfig = field(
        default_factory=EstimatedCompletionConfig)
    task_constraints: TaskConstraints = field(default_factory=TaskConstraints)
    # synthetic-pod autoscaling after each match cycle (scheduler.clj:1178)
    autoscaling_enabled: bool = False
    # reapers (scheduler.clj:1888-2016)
    lingering_task_interval_seconds: float = 30.0
    # dotted factory paths POST /compute-clusters/{name} may instantiate
    # (the daemon seeds this with its static cluster specs' factories);
    # empty = dynamic cluster CREATION disabled
    cluster_factory_allowlist: List[str] = field(default_factory=list)
    # a running instance whose compute cluster is GONE (the previous
    # leader's in-process backend, a deleted dynamic cluster) is failed
    # NODE_LOST (mea-culpa) after this grace window — long enough for a
    # dynamic re-add, short enough that failover retries promptly
    orphaned_cluster_grace_seconds: float = 30.0
    straggler_interval_seconds: float = 30.0
    # user/pool gauge sweeper (monitor.clj:209)
    monitor_interval_seconds: float = 30.0
    # queue-latency / cycle-duration SLOs exposed on /metrics
    slo: SloConfig = field(default_factory=SloConfig)
    # deterministic fault injection + launch circuit breakers
    # (docs/ROBUSTNESS.md); the scheduler applies both at construction
    faults: FaultInjectionConfig = field(
        default_factory=FaultInjectionConfig)
    circuit_breaker: CircuitBreakerConfig = field(
        default_factory=CircuitBreakerConfig)
    # pipelined fused-cycle driver + compile-cache warmup
    # (sched/pipeline.py, docs/PERFORMANCE.md); depth=0 pins the
    # strictly-synchronous driver
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    # per-job scheduling audit trail (utils/audit.py; the "why isn't my
    # job running" lane, docs/OBSERVABILITY.md)
    audit: AuditConfig = field(default_factory=AuditConfig)
    # serving-plane request observability: http.request spans, RED
    # metrics, /debug/requests capture rings (rest/instrument.py)
    http: HttpConfig = field(default_factory=HttpConfig)
    # serving-plane scale-out: follower read fleet + leader group-commit
    # admission batching (state/read_replica.py, state/store.py)
    serving: ServingConfig = field(default_factory=ServingConfig)
    # fleet observability plane: metrics federation + stitched traces +
    # saturation signals (sched/fleet.py, docs/OBSERVABILITY.md)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    # partitioned write plane: per-pool-group store/journal shards with
    # independent fsync streams + leases (state/partition.py); count=1 =
    # the classic single-store plane
    partitions: PartitionConfig = field(default_factory=PartitionConfig)
    # elastic-gang resize plane (sched/elastic.py, docs/GANG.md
    # elasticity): grace-shrink protocol + optimizer-set budgets
    elastic: ElasticConfig = field(default_factory=ElasticConfig)
    # layered admission control + saturation-driven brownout
    # (sched/admission.py, policy/rate_limit.py; docs/DEPLOY.md
    # "overload runbook")
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    # storage-integrity plane: background CRC scrub + corruption
    # self-heal + hygiene-sweep tuning (state/integrity.py,
    # state/repair.py; docs/ROBUSTNESS.md "WAL v2")
    storage: StorageConfig = field(default_factory=StorageConfig)
    # the real optimizer loop (sched/optimizer.py): a
    # ``sched.optimizer.OptimizerConfig`` when the daemon's "optimizer"
    # conf section enables it, else None (loop off).  Held untyped here
    # because config.py must not import the sched package (cycle); the
    # daemon boot-validates the section via OptimizerConfig.from_conf.
    optimizer: Optional[object] = None
    # executor heartbeat timeout killer (mesos/heartbeat.clj:66-147);
    # disabled by default like the reference (marked deprecated there)
    heartbeat_enabled: bool = False
    heartbeat_timeout_ms: int = 60_000
    # offensive-job stifling in the rank cycle (scheduler.clj:2205-2257);
    # None disables the filter
    offensive_job_limits: Optional[OffensiveJobLimits] = None

    # pool-regex planes (reference: config.clj pools
    # {:default-containers [{:pool-regex :container}], :default-env,
    # :valid-gpu-models}); first match wins, None/missing = not configured
    default_containers: List[tuple] = field(default_factory=list)
    default_envs: List[tuple] = field(default_factory=list)
    valid_gpu_models: List[tuple] = field(default_factory=list)
    # operator k8s policy mirrored into /settings on EVERY node (api-only
    # followers included); the k8s backends receive the same values as
    # constructor kwargs (reference: config :kubernetes
    # :disallowed-container-paths / :disallowed-var-names)
    kubernetes_disallowed_container_paths: List[str] = \
        field(default_factory=list)
    kubernetes_disallowed_var_names: List[str] = field(default_factory=list)

    _compiled: List[tuple] = field(default_factory=list, repr=False)

    def _pool_match(self, table: List[tuple], pool_name: str):
        for rx, val in table:
            if re.search(rx, pool_name):
                return val
        return None

    def default_container_for_pool(self, pool_name: str) -> Optional[Dict]:
        """reference: get-default-container-for-pool, rest/api.clj:719"""
        return self._pool_match(self.default_containers, pool_name)

    def default_env_for_pool(self, pool_name: str) -> Dict[str, str]:
        return self._pool_match(self.default_envs, pool_name) or {}

    def gpu_models_for_pool(self, pool_name: str) -> Optional[List[str]]:
        """reference: get-gpu-models-on-pool, rest/api.clj:724"""
        return self._pool_match(self.valid_gpu_models, pool_name)

    def matcher_for_pool(self, pool_name: str) -> MatcherConfig:
        if not self._compiled and self.pool_matchers:
            self._compiled = [(re.compile(rx), mc) for rx, mc in self.pool_matchers]
        for rx, mc in self._compiled:
            if rx.search(pool_name):
                return mc
        return self.default_matcher

    def pool_quota(self, pool_name: str) -> Optional[PoolQuota]:
        return self.pool_quotas.get(pool_name)
