"""ctypes binding for the native job client (``native/jobclient.cpp``).

The reference's programmatic embedding surface is the Java jobclient
(reference: jobclient/java/.../JobClient.java — batched submit/query/abort,
retry, JobListener poll loop, impersonation, basic auth).  This build's
native equivalent is ``libcookjobclient.so``: a dependency-free C++
HTTP/1.1 client any C/C++ program can link, bound here for Python use and
for the test suite.  The pure-Python :class:`cook_tpu.client.JobClient`
remains the ergonomic Python surface; this class proves and exercises the
native one.

Builds the library on first use (same pattern as watch_queue.py); raises
:class:`RuntimeError` when no toolchain is available.
"""

from __future__ import annotations

import ctypes
import json
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_SRC = _REPO_ROOT / "native" / "jobclient.cpp"
_BUILD_DIR = _REPO_ROOT / "native" / "build"
_LIB = _BUILD_DIR / "libcookjobclient.so"

_STATUS_CB_T = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_char_p,
                                ctypes.c_void_p)


def _build_library() -> Optional[Path]:
    from .build import build_if_stale
    return build_if_stale([_SRC], _LIB, ["-shared", "-fPIC"],
                          timeout_s=120)


_lib_handle = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib_handle, _lib_tried
    if _lib_tried:
        return _lib_handle
    _lib_tried = True
    path = _build_library()
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    lib.cjc_create.restype = ctypes.c_void_p
    lib.cjc_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                               ctypes.c_char_p]
    lib.cjc_destroy.argtypes = [ctypes.c_void_p]
    for fn in ("cjc_set_basic_auth",):
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_char_p]
    for fn in ("cjc_set_bearer", "cjc_set_impersonate"):
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.cjc_last_error.restype = ctypes.c_char_p
    lib.cjc_last_error.argtypes = [ctypes.c_void_p]
    lib.cjc_free.argtypes = [ctypes.c_void_p]
    lib.cjc_request.restype = ctypes.c_int
    lib.cjc_request.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_void_p)]
    lib.cjc_submit.restype = ctypes.c_int
    lib.cjc_submit.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_void_p)]
    lib.cjc_submit2.restype = ctypes.c_int
    lib.cjc_submit2.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_void_p)]
    lib.cjc_group_query.restype = ctypes.c_int
    lib.cjc_group_query.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_void_p)]
    for fn in ("cjc_query", "cjc_kill", "cjc_group_kill"):
        getattr(lib, fn).restype = ctypes.c_int
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_void_p)]
    lib.cjc_retry.restype = ctypes.c_int
    lib.cjc_retry.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_int, ctypes.POINTER(ctypes.c_void_p)]
    lib.cjc_wait.restype = ctypes.c_int
    lib.cjc_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_long, ctypes.c_long,
                             ctypes.POINTER(ctypes.c_void_p),
                             ctypes.POINTER(ctypes.c_int)]
    lib.cjc_listen.restype = ctypes.c_void_p
    lib.cjc_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_long, _STATUS_CB_T, ctypes.c_void_p]
    lib.cjc_listen_stop.argtypes = [ctypes.c_void_p]
    _lib_handle = lib
    return lib


def native_available() -> bool:
    return _load() is not None


class NativeJobClientError(RuntimeError):
    def __init__(self, message: str, status: int = -1, body: str = ""):
        super().__init__(message)
        self.status = status
        self.body = body


class NativeJobClient:
    """Python handle over ``libcookjobclient.so``."""

    def __init__(self, host: str, port: int, user: str = "default",
                 basic_auth: Optional[Tuple[str, str]] = None,
                 bearer: Optional[str] = None,
                 impersonate: Optional[str] = None):
        lib = _load()
        if lib is None:
            raise RuntimeError("native jobclient unavailable (no g++?)")
        self._lib = lib
        self._h = lib.cjc_create(host.encode(), port, user.encode())
        if basic_auth:
            lib.cjc_set_basic_auth(self._h, basic_auth[0].encode(),
                                   basic_auth[1].encode())
        if bearer:
            lib.cjc_set_bearer(self._h, bearer.encode())
        if impersonate:
            lib.cjc_set_impersonate(self._h, impersonate.encode())
        self._listeners: List[ctypes.c_void_p] = []
        self._cb_refs: List[object] = []  # keep callbacks alive

    def close(self) -> None:
        if self._h is not None:
            for lh in self._listeners:
                self._lib.cjc_listen_stop(lh)
            self._listeners.clear()
            self._lib.cjc_destroy(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ----------------------------------------------------------- plumbing
    def _take(self, out: ctypes.c_void_p) -> str:
        if not out.value:
            return ""
        try:
            return ctypes.cast(out, ctypes.c_char_p).value.decode()
        finally:
            self._lib.cjc_free(out)

    def _check(self, status: int, body: str, ok=(200, 201)) -> None:
        if status < 0:
            raise NativeJobClientError(
                self._lib.cjc_last_error(self._h).decode() or
                "transport error", status, body)
        if status not in ok:
            raise NativeJobClientError(
                f"HTTP {status}: {body[:200]}", status, body)

    def request(self, method: str, path: str, body: str = "") -> Tuple[int, str]:
        out = ctypes.c_void_p()
        status = self._lib.cjc_request(self._h, method.encode(),
                                       path.encode(), body.encode(),
                                       ctypes.byref(out))
        return status, self._take(out)

    # ---------------------------------------------------------------- api
    def submit(self, jobs: List[Dict], pool: Optional[str] = None,
               groups: Optional[List[Dict]] = None) -> List[str]:
        out = ctypes.c_void_p()
        if groups:
            status = self._lib.cjc_submit2(
                self._h, json.dumps(jobs).encode(),
                json.dumps(groups).encode(), (pool or "").encode(),
                ctypes.byref(out))
        else:
            status = self._lib.cjc_submit(
                self._h, json.dumps(jobs).encode(), (pool or "").encode(),
                ctypes.byref(out))
        body = self._take(out)
        self._check(status, body)
        return json.loads(body)["jobs"]

    def group(self, uuids: Sequence[str],
              detailed: bool = False) -> List[Dict]:
        """Group query (reference: the Java client's Group support)."""
        out = ctypes.c_void_p()
        status = self._lib.cjc_group_query(
            self._h, ",".join(uuids).encode(), 1 if detailed else 0,
            ctypes.byref(out))
        body = self._take(out)
        self._check(status, body)
        return json.loads(body)

    def kill_groups(self, uuids: Sequence[str]) -> Dict:
        out = ctypes.c_void_p()
        status = self._lib.cjc_group_kill(
            self._h, ",".join(uuids).encode(), ctypes.byref(out))
        body = self._take(out)
        self._check(status, body)
        return json.loads(body) if body else {}

    def query(self, uuids: Sequence[str]) -> List[Dict]:
        out = ctypes.c_void_p()
        status = self._lib.cjc_query(self._h, ",".join(uuids).encode(),
                                     ctypes.byref(out))
        body = self._take(out)
        self._check(status, body)
        return json.loads(body)

    def kill(self, uuids: Sequence[str]) -> Dict:
        out = ctypes.c_void_p()
        status = self._lib.cjc_kill(self._h, ",".join(uuids).encode(),
                                    ctypes.byref(out))
        body = self._take(out)
        self._check(status, body)
        return json.loads(body) if body else {}

    def retry(self, uuid: str, retries: int) -> Dict:
        out = ctypes.c_void_p()
        status = self._lib.cjc_retry(self._h, uuid.encode(), retries,
                                     ctypes.byref(out))
        body = self._take(out)
        self._check(status, body)
        return json.loads(body) if body else {}

    def wait(self, uuids: Sequence[str], timeout_s: float = 300.0,
             poll_s: float = 0.2) -> List[Dict]:
        out = ctypes.c_void_p()
        done = ctypes.c_int(0)
        status = self._lib.cjc_wait(self._h, ",".join(uuids).encode(),
                                    int(timeout_s * 1000),
                                    int(poll_s * 1000),
                                    ctypes.byref(out), ctypes.byref(done))
        body = self._take(out)
        self._check(status, body)
        if not done.value:
            raise TimeoutError(f"jobs not completed within {timeout_s}s")
        return json.loads(body)

    def listen(self, uuids: Sequence[str],
               callback: Callable[[str, str], None],
               interval_s: float = 0.2) -> None:
        """Invoke ``callback(uuid, state)`` on every state change of the
        tracked jobs (reference: JobClient.java JobListener loop)."""

        @_STATUS_CB_T
        def cb(uuid_b, state_b, _arg):
            try:
                callback(uuid_b.decode(), state_b.decode())
            except Exception:
                pass  # never let Python exceptions cross the C boundary

        self._cb_refs.append(cb)
        lh = self._lib.cjc_listen(self._h, ",".join(uuids).encode(),
                                  int(interval_s * 1000), cb, None)
        self._listeners.append(lh)
