"""ctypes binding for the native pack kernels (``native/pack.cpp``).

The device-resident cycle state (ISSUE 7) keeps the entity pack on
device and feeds it deltas; the per-cycle host work that remains is a
handful of array passes that were Python/numpy hot loops:

* ``pack_diff`` — delta EXTRACTION: positions where the freshly staged
  rows/flags differ from the resident pack's host shadow (the scatter
  batch shipped to the device);
* ``order_merge`` — the columnar index's order-cache repair tail: apply
  sorted deletes + inserts across the four parallel order arrays in one
  native pass (state/index.py ``_repair_order``);
* ``prune_rows`` — post-match APPLY: drop launched/conflicted positions
  from the published queue's row list.

Every entry point has a vectorized-numpy fallback used when no C++
toolchain is available (same build-on-first-use pattern as
watch_queue.py / jobclient.py; tests gate on :func:`native_available`
via the ``native`` pytest marker so a toolchain-less environment skips
instead of failing)."""

from __future__ import annotations

import ctypes
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_SRC = _REPO_ROOT / "native" / "pack.cpp"
_BUILD_DIR = _REPO_ROOT / "native" / "build"
_LIB = _BUILD_DIR / "libcookpack.so"

_lib_handle = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib_handle, _lib_tried
    if _lib_tried:
        return _lib_handle
    _lib_tried = True
    from .build import build_if_stale
    path = build_if_stale([_SRC], _LIB, ["-shared", "-fPIC"], timeout_s=120)
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    lib.cpk_diff_pack.restype = ctypes.c_long
    lib.cpk_diff_pack.argtypes = [ctypes.c_void_p] * 4 + [
        ctypes.c_long, ctypes.c_void_p]
    lib.cpk_order_merge.restype = ctypes.c_long
    lib.cpk_order_merge.argtypes = (
        [ctypes.c_void_p] * 4 + [ctypes.c_long, ctypes.c_long]
        + [ctypes.c_void_p, ctypes.c_long]
        + [ctypes.c_void_p] * 5 + [ctypes.c_long]
        + [ctypes.c_void_p] * 4)
    lib.cpk_prune_rows.restype = ctypes.c_long
    lib.cpk_prune_rows.argtypes = [
        ctypes.c_void_p, ctypes.c_long, ctypes.c_void_p, ctypes.c_long,
        ctypes.c_void_p]
    _lib_handle = lib
    return lib


def native_available() -> bool:
    """True when libcookpack built (g++ present); the numpy fallbacks
    keep every caller working without it."""
    return _load() is not None


def _ptr(a: np.ndarray) -> ctypes.c_void_p:
    return ctypes.c_void_p(a.ctypes.data)


# ------------------------------------------------------------------ diff
def pack_diff(rows_old: np.ndarray, rows_new: np.ndarray,
              flags_old: np.ndarray, flags_new: np.ndarray) -> np.ndarray:
    """Flat positions where (rows, flags) differ — the resident pack's
    scatter batch.  Inputs are same-shape i32 / u8 arrays (any shape;
    compared raveled)."""
    ro = np.ascontiguousarray(rows_old, dtype=np.int32).ravel()
    rn = np.ascontiguousarray(rows_new, dtype=np.int32).ravel()
    fo = np.ascontiguousarray(flags_old, dtype=np.uint8).ravel()
    fn = np.ascontiguousarray(flags_new, dtype=np.uint8).ravel()
    n = ro.size
    lib = _load()
    if lib is None:
        return np.flatnonzero((ro != rn) | (fo != fn)).astype(np.int32)
    out = np.empty(n, dtype=np.int32)
    k = lib.cpk_diff_pack(_ptr(ro), _ptr(rn), _ptr(fo), _ptr(fn),
                          ctypes.c_long(n), _ptr(out))
    return out[:k].copy()


# ----------------------------------------------------------- order merge
def order_merge(kb: np.ndarray, st: np.ndarray, uid: np.ndarray,
                rows: np.ndarray, del_pos: np.ndarray, ins_pos: np.ndarray,
                akb: Optional[np.ndarray], ast: Optional[np.ndarray],
                auid: Optional[np.ndarray], arows: Optional[np.ndarray],
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Apply sorted deletes (positions into the original arrays) and
    inserts (np.insert semantics against the post-delete array) to the
    four parallel order-cache arrays.  ``kb``/``akb`` are fixed-width
    byte-string arrays (S-dtype)."""
    na = len(ins_pos) if akb is not None else 0
    nd = len(del_pos)
    n = len(rows)
    lib = _load()
    if lib is None:
        if nd:
            kb = np.delete(kb, del_pos)
            st = np.delete(st, del_pos)
            uid = np.delete(uid, del_pos)
            rows = np.delete(rows, del_pos)
        if na:
            kb = np.insert(kb, ins_pos, akb)
            st = np.insert(st, ins_pos, ast)
            uid = np.insert(uid, ins_pos, auid)
            rows = np.insert(rows, ins_pos, arows)
        return kb, st, uid, rows
    knb = kb.dtype.itemsize
    m = n - nd + na
    out_kb = np.empty(m, dtype=kb.dtype)
    out_st = np.empty(m, dtype=np.int64)
    out_uid = np.empty(m, dtype=np.int32)
    out_rows = np.empty(m, dtype=np.int64)
    if na:
        akb = np.ascontiguousarray(akb)
        ast = np.ascontiguousarray(ast, dtype=np.int64)
        auid = np.ascontiguousarray(auid, dtype=np.int32)
        arows = np.ascontiguousarray(arows, dtype=np.int64)
        ins_pos = np.ascontiguousarray(ins_pos, dtype=np.int64)
    kb = np.ascontiguousarray(kb)
    st = np.ascontiguousarray(st, dtype=np.int64)
    uid = np.ascontiguousarray(uid, dtype=np.int32)
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    del_pos = np.ascontiguousarray(del_pos, dtype=np.int64)
    got = lib.cpk_order_merge(
        _ptr(kb), _ptr(st), _ptr(uid), _ptr(rows),
        ctypes.c_long(n), ctypes.c_long(knb),
        _ptr(del_pos), ctypes.c_long(nd),
        _ptr(ins_pos) if na else None,
        _ptr(akb) if na else None, _ptr(ast) if na else None,
        _ptr(auid) if na else None, _ptr(arows) if na else None,
        ctypes.c_long(na),
        _ptr(out_kb), _ptr(out_st), _ptr(out_uid), _ptr(out_rows))
    assert got == m, (got, m)
    return out_kb, out_st, out_uid, out_rows


# ------------------------------------------------------------ apply side
def prune_rows(rows: np.ndarray, drop_pos: np.ndarray) -> np.ndarray:
    """``rows`` (i32) minus the entries at ``drop_pos`` (sorted unique
    positions) — the published queue's launched/conflicted prune."""
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    if not len(drop_pos):
        return rows
    drop = np.ascontiguousarray(drop_pos, dtype=np.int64)
    lib = _load()
    if lib is None:
        keep = np.ones(len(rows), dtype=bool)
        keep[drop] = False
        return rows[keep]
    out = np.empty(len(rows), dtype=np.int32)
    k = lib.cpk_prune_rows(_ptr(rows), ctypes.c_long(len(rows)),
                           _ptr(drop), ctypes.c_long(len(drop)), _ptr(out))
    return out[:k].copy()
