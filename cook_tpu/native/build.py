"""Shared build-if-stale helper for the native (C++) runtime components.

One staleness rule and one error-reporting path for every g++ artifact
(libcooktransport / cook_agentd in cluster/remote.py, libcookrepl in
state/replication.py, the watch queue, the native jobclient) instead of
per-module copies that drift.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path
from typing import List, Optional, Sequence


def build_if_stale(sources: Sequence[Path], target: Path,
                   extra: List[str], timeout_s: float = 180.0
                   ) -> Optional[Path]:
    """Compile ``sources[0]`` (with ``sources[1:]`` as staleness inputs,
    e.g. included headers) into ``target`` unless the target is already
    newer than every source.  Returns the target path, or None when the
    toolchain is unavailable or the build fails (the compiler's stderr is
    surfaced — a syntax error must not masquerade as "no g++")."""
    existing = [p for p in sources if p.exists()]
    if not existing:
        return None
    src_mtime = max(p.stat().st_mtime for p in existing)
    if target.exists() and target.stat().st_mtime >= src_mtime:
        return target
    target.parent.mkdir(parents=True, exist_ok=True)
    try:
        subprocess.run(
            ["g++", "-O2", "-pthread", "-std=c++17", *extra,
             str(sources[0]), "-o", str(target)],
            check=True, capture_output=True, timeout=timeout_s)
        return target
    except subprocess.CalledProcessError as e:
        print(f"cook_tpu: native build of {target.name} failed:\n"
              f"{e.stderr.decode(errors='replace')[-2000:]}",
              file=sys.stderr)
        return None
    except (subprocess.SubprocessError, FileNotFoundError):
        return None
