from .watch_queue import (  # noqa: F401
    PyWatchQueue,
    ShardedWatchQueue,
    make_watch_queue,
    native_available,
)
