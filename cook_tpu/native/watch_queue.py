"""Python binding for the native sharded ordered executor.

Loads (building on first use) ``native/watch_queue.cpp`` via ctypes and
exposes :class:`ShardedWatchQueue`: submit(key, event) fan-in, per-key FIFO
processing on parallel shard threads.  Payloads stay on the Python side
(keyed by sequence number); the native layer owns routing, ordering, worker
threads, and flush accounting.

When no C++ toolchain is available the pure-Python :class:`PyWatchQueue`
provides identical semantics (shard threads + per-shard FIFO).
"""

from __future__ import annotations

import ctypes
import queue
import threading
from pathlib import Path
from typing import Any, Callable, Dict, Optional

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_SRC = _REPO_ROOT / "native" / "watch_queue.cpp"
_BUILD_DIR = _REPO_ROOT / "native" / "build"
_LIB = _BUILD_DIR / "libwatchqueue.so"

_CALLBACK_T = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_longlong,
                               ctypes.c_void_p)


def _build_library() -> Optional[Path]:
    from .build import build_if_stale
    return build_if_stale([_SRC], _LIB, ["-shared", "-fPIC"],
                          timeout_s=120)


_lib_handle = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib_handle, _lib_tried
    if _lib_tried:
        return _lib_handle
    _lib_tried = True
    path = _build_library()
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    lib.wq_create.restype = ctypes.c_void_p
    lib.wq_create.argtypes = [ctypes.c_int, _CALLBACK_T, ctypes.c_void_p]
    lib.wq_submit.restype = ctypes.c_int
    lib.wq_submit.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_longlong]
    lib.wq_processed.restype = ctypes.c_longlong
    lib.wq_processed.argtypes = [ctypes.c_void_p]
    lib.wq_pending.restype = ctypes.c_longlong
    lib.wq_pending.argtypes = [ctypes.c_void_p]
    lib.wq_flush.argtypes = [ctypes.c_void_p]
    lib.wq_destroy.argtypes = [ctypes.c_void_p]
    _lib_handle = lib
    return lib


def native_available() -> bool:
    return _load() is not None


class ShardedWatchQueue:
    """Native-backed sharded in-order executor.

    ``handler(key, payload)`` runs on shard threads; events with equal keys
    run in submission order (reference: ParallelWatchQueue.java semantics).
    """

    def __init__(self, handler: Callable[[str, Any], None],
                 shards: int = 19):
        lib = _load()
        if lib is None:
            raise RuntimeError("native watch queue unavailable "
                               "(no C++ toolchain?)")
        self._lib = lib
        self._handler = handler
        self._payloads: Dict[int, Any] = {}
        self._payload_lock = threading.Lock()
        self._seq = 0
        self._errors: list = []

        def _invoke(key: bytes, seq: int, _user) -> None:
            with self._payload_lock:
                payload = self._payloads.pop(seq, None)
            try:
                self._handler(key.decode(), payload)
            except Exception as e:  # noqa: BLE001 - surfaced via errors()
                self._errors.append(e)

        self._cb = _CALLBACK_T(_invoke)  # keep a reference: ctypes trampoline
        self._handle = lib.wq_create(shards, self._cb, None)
        if not self._handle:
            raise RuntimeError("wq_create failed")

    def submit(self, key: str, payload: Any = None) -> None:
        with self._payload_lock:
            self._seq += 1
            seq = self._seq
            self._payloads[seq] = payload
        rc = self._lib.wq_submit(self._handle, key.encode(), seq)
        if rc != 0:
            with self._payload_lock:
                self._payloads.pop(seq, None)
            raise RuntimeError("submit on closed queue")

    def flush(self) -> None:
        self._lib.wq_flush(self._handle)

    @property
    def processed(self) -> int:
        return int(self._lib.wq_processed(self._handle))

    @property
    def pending(self) -> int:
        return int(self._lib.wq_pending(self._handle))

    def errors(self) -> list:
        return list(self._errors)

    def close(self) -> None:
        if self._handle:
            self._lib.wq_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class PyWatchQueue:
    """Pure-Python fallback with identical semantics."""

    def __init__(self, handler: Callable[[str, Any], None],
                 shards: int = 19):
        self._handler = handler
        self._queues = [queue.Queue() for _ in range(shards)]
        self._stop = threading.Event()
        self._submitted = 0
        self._processed = 0
        self._count_lock = threading.Lock()
        self._flush_cv = threading.Condition(self._count_lock)
        self._errors: list = []
        self._threads = []
        for q in self._queues:
            t = threading.Thread(target=self._run, args=(q,), daemon=True)
            t.start()
            self._threads.append(t)

    def _run(self, q: "queue.Queue") -> None:
        while True:
            item = q.get()
            if item is None:
                return
            key, payload = item
            try:
                self._handler(key, payload)
            except Exception as e:  # noqa: BLE001
                self._errors.append(e)
            with self._count_lock:
                self._processed += 1
                self._flush_cv.notify_all()

    def submit(self, key: str, payload: Any = None) -> None:
        if self._stop.is_set():
            raise RuntimeError("submit on closed queue")
        with self._count_lock:
            self._submitted += 1
        shard = hash(key) % len(self._queues)
        self._queues[shard].put((key, payload))

    def flush(self) -> None:
        with self._flush_cv:
            self._flush_cv.wait_for(
                lambda: self._processed >= self._submitted)

    @property
    def processed(self) -> int:
        with self._count_lock:
            return self._processed

    @property
    def pending(self) -> int:
        with self._count_lock:
            return self._submitted - self._processed

    def errors(self) -> list:
        return list(self._errors)

    def close(self) -> None:
        self._stop.set()
        for q in self._queues:
            q.put(None)


def make_watch_queue(handler: Callable[[str, Any], None],
                     shards: int = 19):
    """Native when buildable, Python otherwise."""
    if native_available():
        return ShardedWatchQueue(handler, shards)
    return PyWatchQueue(handler, shards)
