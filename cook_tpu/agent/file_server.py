"""Sandbox file server.

The sidecar's file-access API (reference: sidecar/cook/sidecar/
file_server.py:136-235, replicating the Mesos agent /files endpoints over
COOK_WORKDIR):

  GET /files/read?path=&offset=&length=   -> {"data": ..., "offset": n}
  GET /files/download?path=               -> raw bytes
  GET /files/browse?path=                 -> [{path, size, mode, mtime, nlink}]

All paths are resolved under the sandbox root; traversal outside it is a
404 (the reference hides existence of outside paths).
"""

from __future__ import annotations

import json
import os
import stat
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional

MAX_READ_LENGTH = 4 * 1024 * 1024


class _FilesHandler(BaseHTTPRequestHandler):
    root: Path = Path(".")
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # pragma: no cover
        pass

    def _respond_json(self, status: int, payload) -> None:
        data = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _resolve(self, raw_path: str) -> Optional[Path]:
        if not raw_path:
            return None
        candidate = (self.root / raw_path.lstrip("/")).resolve()
        root = self.root.resolve()
        if candidate != root and root not in candidate.parents:
            return None
        return candidate if candidate.exists() else None

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        if parsed.path == "/readiness-probe":
            # kubelet readiness for the sidecar container (the probe
            # endpoint pod_spec wires into the pod's readinessProbe)
            return self._respond_json(200, {"status": "ok"})
        params = urllib.parse.parse_qs(parsed.query)
        raw_path = (params.get("path") or [""])[0]
        target = self._resolve(raw_path)
        if parsed.path == "/files/read":
            if target is None or not target.is_file():
                return self._respond_json(404, {"error": "no such file"})
            if "offset" not in params:
                # Mesos files/read semantics (kept by the reference sidecar):
                # omitting offset returns the current file size, which is how
                # clients (e.g. tail) discover where the end is.
                return self._respond_json(
                    200, {"data": "", "offset": target.stat().st_size})
            offset = int(params["offset"][0])
            length = min(int((params.get("length") or [str(MAX_READ_LENGTH)])[0]),
                         MAX_READ_LENGTH)
            if offset < 0 or length < 0:
                return self._respond_json(400, {"error": "negative offset/length"})
            with open(target, "rb") as f:
                f.seek(offset)
                data = f.read(length)
            # surrogateescape keeps arbitrary bytes round-trippable: a chunk
            # boundary may split a multibyte character, and the client glues
            # chunks back together with .encode('utf-8', 'surrogateescape')
            return self._respond_json(200, {
                "data": data.decode("utf-8", errors="surrogateescape"),
                "offset": offset})
        if parsed.path == "/files/download":
            if target is None or not target.is_file():
                return self._respond_json(404, {"error": "no such file"})
            data = target.read_bytes()
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Disposition",
                             f'attachment; filename="{target.name}"')
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if parsed.path == "/files/browse":
            if not raw_path:
                target = self.root.resolve()  # empty path = sandbox root
            if target is None or not target.is_dir():
                return self._respond_json(404, {"error": "no such directory"})
            entries = []
            for child in sorted(target.iterdir()):
                st = child.stat()
                entries.append({
                    "path": str(child.relative_to(self.root.resolve())),
                    "size": st.st_size,
                    "nlink": st.st_nlink,
                    "mtime": int(st.st_mtime),
                    "mode": stat.filemode(st.st_mode),
                })
            return self._respond_json(200, entries)
        return self._respond_json(404, {"error": "no such endpoint"})


class SandboxFileServer:
    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0):
        handler = type("BoundFiles", (_FilesHandler,), {"root": Path(root)})
        self.server = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self.server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def main(argv: Optional[list] = None) -> int:
    """The ``cook-sidecar`` entrypoint pod_spec wires into the sidecar
    container (``cook-sidecar <port>``; the sidecar image maps that name
    to ``python -m cook_tpu.agent.file_server``).  Serves the sandbox
    (``$COOK_SANDBOX``, default cwd) on 0.0.0.0:<port> until killed."""
    import signal
    import sys
    args = list(sys.argv[1:] if argv is None else argv)
    port = int(args[0]) if args else 28101
    root = os.environ.get("COOK_SANDBOX") or os.environ.get(
        "COOK_WORKDIR") or "."
    srv = SandboxFileServer(root, host="0.0.0.0", port=port)
    srv.start()
    print(f"cook-sidecar: serving {root} on :{srv.port}", flush=True)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    done.wait()
    srv.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover - container entrypoint
    raise SystemExit(main())
