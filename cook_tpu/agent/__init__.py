from .executor import (  # noqa: F401
    DEFAULT_PROGRESS_REGEX,
    ProgressWatcher,
    TaskExecutor,
    rest_progress_publisher,
)
from .file_server import SandboxFileServer  # noqa: F401
