"""On-node task executor.

The port of the reference's executor agent (reference: executor/cook/
executor.py:421-510, subprocess.py, progress.py:123-297):

 - runs the user command in its own process group/session so the whole tree
   can be signalled;
 - streams stdout/stderr into sandbox files;
 - watches a configurable progress regex in the output (and an optional
   explicit progress file), publishing sequenced updates to the scheduler's
   ``POST /progress/<task-id>`` endpoint (the sidecar path) or a local
   callback;
 - graceful kill via escalating signals to the process group
   (subprocess.py:102-232): SIGTERM, grace period, SIGKILL;
 - writes an exit-code sentinel into the sandbox.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import threading
import time
import urllib.request
from pathlib import Path
from typing import Callable, Dict, Optional

DEFAULT_PROGRESS_REGEX = r"progress:?\s+([0-9]*\.?[0-9]+)%?(?:\s+(.*))?"


class ProgressWatcher:
    """Extract monotone progress updates from output lines (reference:
    progress.py:123-297: latest-by-sequence, capped message length)."""

    def __init__(self, regex: str = DEFAULT_PROGRESS_REGEX,
                 publish: Optional[Callable[[int, int, str], None]] = None,
                 max_message_length: int = 512):
        self.pattern = re.compile(regex)
        self.publish = publish
        self.max_message_length = max_message_length
        self.sequence = 0
        self.last_percent: Optional[int] = None
        self.last_message = ""

    def observe_line(self, line: str) -> None:
        match = self.pattern.search(line)
        if not match:
            return
        try:
            percent = int(float(match.group(1)))
        except ValueError:
            return
        percent = max(0, min(100, percent))
        has_msg = match.lastindex is not None and match.lastindex >= 2
        message = ((match.group(2) if has_msg else "") or "") \
            .strip()[:self.max_message_length]
        self.sequence += 1
        self.last_percent = percent
        self.last_message = message
        if self.publish:
            self.publish(self.sequence, percent, message)


def rest_progress_publisher(api_url: str, task_id: str
                            ) -> Callable[[int, int, str], None]:
    """Publish to the scheduler's progress endpoint (the sidecar tracker's
    path, sidecar/cook/sidecar/tracker.py)."""

    def publish(sequence: int, percent: int, message: str) -> None:
        body = json.dumps({"progress_sequence": sequence,
                           "progress_percent": percent,
                           "progress_message": message}).encode()
        req = urllib.request.Request(
            f"{api_url}/progress/{task_id}", data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)
        except OSError:
            pass  # progress is best-effort

    return publish


class TaskExecutor:
    def __init__(self, command: str, sandbox: str,
                 env: Optional[Dict[str, str]] = None,
                 progress_regex: str = DEFAULT_PROGRESS_REGEX,
                 progress_publish: Optional[Callable] = None,
                 progress_file: Optional[str] = None,
                 kill_grace_period_s: float = 2.0,
                 shell: str = "/bin/sh",
                 resize_file: Optional[str] = None):
        self.command = command
        self.sandbox = Path(sandbox)
        self.env = dict(env or {})
        self.kill_grace_period_s = kill_grace_period_s
        self.shell = shell
        self.watcher = ProgressWatcher(progress_regex, progress_publish)
        # explicit progress file, tailed alongside stdout/stderr
        # (reference: :job/progress-output-file; progress.py watches the
        # EXECUTOR_PROGRESS_OUTPUT_FILE location)
        self.progress_file = (self.sandbox / progress_file
                              if progress_file else None)
        # elastic-gang resize event file (docs/GANG.md elasticity): the
        # checkpoint/grace protocol appends one JSON line per resize
        # advisory here, and its path is advertised to the task as
        # COOK_GANG_RESIZE_FILE before the fork
        self.resize_file = (self.sandbox / resize_file
                            if resize_file else None)
        self.process: Optional[subprocess.Popen] = None
        self.exit_code: Optional[int] = None
        self._reader_threads = []
        self._killed = False

    # ------------------------------------------------------------------ run
    def start(self) -> None:
        self.sandbox.mkdir(parents=True, exist_ok=True)
        env = dict(os.environ)
        env.update(self.env)
        env["COOK_WORKDIR"] = str(self.sandbox)
        if self.progress_file is not None:
            # advertised BEFORE the fork so the task can locate its file
            env["EXECUTOR_PROGRESS_OUTPUT_FILE"] = str(self.progress_file)
        if self.resize_file is not None:
            # advertised BEFORE the fork so an elastic-gang workload can
            # watch for resize advisories (docs/GANG.md: SIGUSR1 says
            # "look at the file"; the file says what is happening)
            env["COOK_GANG_RESIZE_FILE"] = str(self.resize_file)
        self.process = subprocess.Popen(
            [self.shell, "-c", self.command],
            cwd=str(self.sandbox), env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            start_new_session=True)  # own process group + session
        for stream, name in ((self.process.stdout, "stdout"),
                             (self.process.stderr, "stderr")):
            t = threading.Thread(target=self._pump, args=(stream, name),
                                 daemon=True)
            t.start()
            self._reader_threads.append(t)
        if self.progress_file is not None:
            t = threading.Thread(target=self._tail_progress_file,
                                 daemon=True)
            t.start()
            # joined by wait() so the final pass (after process exit) can
            # publish a progress line written just before the task exited
            self._reader_threads.append(t)

    def _tail_progress_file(self) -> None:
        """Tail the job's explicit progress file while the task runs; the
        file may not exist until the task writes it."""
        pos = 0
        while True:
            alive = self.process is not None and self.process.poll() is None
            try:
                with open(self.progress_file, "rb") as f:
                    f.seek(pos)
                    for raw in iter(f.readline, b""):
                        if not raw.endswith(b"\n") and alive:
                            break  # partial line: re-read next pass
                        pos += len(raw)
                        try:
                            self.watcher.observe_line(
                                raw.decode("utf-8", errors="replace"))
                        except Exception:
                            pass
            except OSError:
                pass
            if not alive:
                return
            time.sleep(0.1)

    def _pump(self, stream, name: str) -> None:
        """Stream output to the sandbox file, watching for progress
        (interleaving-safe: one writer per stream, io_helper.py)."""
        path = self.sandbox / name
        with open(path, "ab") as f:
            for raw in iter(stream.readline, b""):
                f.write(raw)
                f.flush()
                try:
                    self.watcher.observe_line(
                        raw.decode("utf-8", errors="replace"))
                except Exception:
                    pass

    def wait(self, timeout_s: Optional[float] = None) -> Optional[int]:
        if self.process is None:
            return None
        try:
            self.exit_code = self.process.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return None
        for t in self._reader_threads:
            t.join(timeout=5)
        (self.sandbox / "exit_code").write_text(str(self.exit_code))
        return self.exit_code

    # ----------------------------------------------------------------- kill
    def kill(self) -> int:
        """Escalating kill of the whole process group (reference:
        subprocess.py:102-232). Returns the exit code."""
        if self.process is None:
            return -1
        self._killed = True
        pgid = os.getpgid(self.process.pid)
        try:
            os.killpg(pgid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        deadline = time.time() + self.kill_grace_period_s
        while time.time() < deadline:
            if self.process.poll() is not None:
                break
            time.sleep(0.05)
        if self.process.poll() is None:
            try:
                os.killpg(pgid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        return self.wait(timeout_s=10) or self.process.returncode

    def notify_resize(self, event: Dict) -> None:
        """Relay an elastic-gang resize advisory to the workload
        (docs/GANG.md checkpoint/grace protocol): append one JSON line
        to the resize file, then SIGUSR1 the task's process group so a
        checkpoint-aware trainer wakes up and reads it.  Best-effort on
        both legs — the shrink itself executes through the ordinary
        kill at the grace deadline regardless."""
        if self.resize_file is not None:
            try:
                line = json.dumps({"ts": time.time(), **event})
                with open(self.resize_file, "a") as f:
                    f.write(line + "\n")
            except OSError:
                pass
        if self.process is not None and self.process.poll() is None:
            try:
                os.killpg(os.getpgid(self.process.pid), signal.SIGUSR1)
            except (ProcessLookupError, PermissionError):
                pass

    @property
    def running(self) -> bool:
        return self.process is not None and self.process.poll() is None


def main(argv=None) -> int:
    """``python -m cook_tpu.agent.executor`` — run one task command under
    the progress-tracking executor (the reference's :job/executor "cook"
    choice: the custom executor instead of the bare shell,
    executor/cook/executor.py:421-510).

    Configuration comes from the environment the launch path already
    provides (COOK_SANDBOX, COOK_TASK_ID) plus:
      COOK_PROGRESS_URL        scheduler base URL for POST /progress/:id
      COOK_PROGRESS_REGEX      per-job regex (:job/progress-regex-string)
      COOK_PROGRESS_FILE       per-job explicit progress file
      COOK_GANG_UUID/MIN/MAX   gang membership + elastic bounds (set by
                               the launch path, docs/GANG.md)
      COOK_GANG_RESIZE_FILE    resize-advisory file name (default
                               ``.cook-gang-resize.jsonl`` for gang
                               members; re-advertised to the task as an
                               absolute sandbox path)
      COOK_TRACEPARENT         W3C trace context propagated from the
                               launch path (sched/matcher.py): the
                               wrapper opens an ``agent.exec`` span
                               under it — retained in this process's
                               local span ring and appended to the
                               sandbox's ``trace_spans.jsonl`` so the
                               fleet trace collector can stitch the
                               exec leg onto the job's client-minted
                               timeline (docs/OBSERVABILITY.md)
    The command is argv (joined), exit code is the task's exit code.
    SIGUSR1 relays an elastic shrink advisory (checkpoint window open):
    the event is appended to the resize file and the signal forwarded to
    the task's process group (docs/GANG.md checkpoint/grace protocol).
    """
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        print("usage: python -m cook_tpu.agent.executor <command...>",
              file=sys.stderr)
        return 2
    command = " ".join(args)
    sandbox = os.environ.get("COOK_SANDBOX", ".")
    task_id = os.environ.get("COOK_TASK_ID", "")
    publish = None
    api_url = os.environ.get("COOK_PROGRESS_URL", "")
    if api_url and task_id:
        publish = rest_progress_publisher(api_url, task_id)
    resize_file = os.environ.get("COOK_GANG_RESIZE_FILE") or (
        ".cook-gang-resize.jsonl" if os.environ.get("COOK_GANG_UUID")
        else None)
    ex = TaskExecutor(
        command, sandbox=sandbox,
        progress_regex=os.environ.get("COOK_PROGRESS_REGEX",
                                      DEFAULT_PROGRESS_REGEX),
        progress_publish=publish,
        progress_file=os.environ.get("COOK_PROGRESS_FILE") or None,
        resize_file=resize_file)

    # The agent kills tasks by signalling the WRAPPER's process group, but
    # TaskExecutor puts the user command in its own session — forward the
    # kill (escalating SIGTERM -> grace -> SIGKILL on the child's tree,
    # reference: executor.py graceful-kill) or the workload would survive
    # its own task being killed.
    def forward_kill(signum, _frame):
        code = ex.kill()
        raise SystemExit(128 + signum if code is None else code)

    signal.signal(signal.SIGTERM, forward_kill)
    signal.signal(signal.SIGINT, forward_kill)

    # SIGUSR1 = elastic shrink advisory from the agent (docs/GANG.md
    # checkpoint/grace): relay to the workload — file event + forwarded
    # signal — and keep running; the kill comes separately at the grace
    # deadline
    def forward_resize(_signum, _frame):
        ex.notify_resize({"kind": "gang-resize", "direction": "shrink",
                          "gang": os.environ.get("COOK_GANG_UUID", ""),
                          "signal": "SIGUSR1"})

    signal.signal(signal.SIGUSR1, forward_resize)

    # Adopt a propagated trace context (W3C traceparent stamped into the
    # task env by the launch path): the exec leg joins the job's
    # client-minted trace under this process's own identity, so the
    # fleet-wide stitched export (GET /debug/trace) shows the agent-side
    # execution next to the leader's txn and the submission request.
    from ..utils import tracing
    remote = tracing.parse_traceparent(os.environ.get("COOK_TRACEPARENT"))
    if remote is not None:
        tracing.set_process_identity(
            "agent-" + (os.environ.get("COOK_HOSTNAME")
                        or os.uname().nodename))

    def run() -> int:
        ex.start()
        code = None
        while code is None:
            code = ex.wait(timeout_s=1.0)
        return code

    if remote is None:
        return run()
    with tracing.tracer.span("agent.exec", remote_parent=remote,
                             task=task_id or None,
                             gang=os.environ.get("COOK_GANG_UUID") or None
                             ) as sp:
        code = run()
        sp.set_tag("exit_code", code)
    # spans for this trace land in the sandbox as one JSON line each —
    # retrievable after the wrapper exits (the ring dies with it)
    try:
        with open(Path(sandbox) / "trace_spans.jsonl", "a") as f:
            for doc in tracing.tracer.traces(remote[0]):
                f.write(json.dumps(doc) + "\n")
    except OSError:
        pass  # trace retention is best-effort
    return code


if __name__ == "__main__":  # pragma: no cover - exercised via agent tests
    raise SystemExit(main())
