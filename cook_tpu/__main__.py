"""``python -m cook_tpu --config cook.json`` — the node entry point
(reference: scheduler/src/cook/components.clj:345-365 -main)."""

import sys

from .daemon import main

if __name__ == "__main__":
    sys.exit(main())
