"""Structured logging + the passport audit event stream.

Mirrors the reference's JSON structured logging with standardized keys
(reference: log_structured.clj:17-91) and the passport audit trail —
one JSON document per lifecycle event routed to a dedicated logger for
offline joining (reference: passport.clj:21-41).
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any, Dict, Optional

_structured = logging.getLogger("cook.structured")
_passport = logging.getLogger("cook.passport")


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc = {"ts": time.time(), "level": record.levelname.lower(),
               "logger": record.name, "message": record.getMessage()}
        extra = getattr(record, "doc", None)
        if extra:
            doc.update(extra)
        return json.dumps(doc, default=str)


def log_structured(level: int, message: str, *, pool: Optional[str] = None,
                   job: Optional[str] = None, instance: Optional[str] = None,
                   user: Optional[str] = None, **kw: Any) -> None:
    doc: Dict[str, Any] = {k: v for k, v in
                           [("pool", pool), ("job", job),
                            ("instance", instance), ("user", user)]
                           if v is not None}
    doc.update(kw)
    _structured.log(level, message, extra={"doc": doc})


class Passport:
    """Audit events: job-created, instance-launched, instance-completed,
    job-completed, preemption, ... (reference passport event types)."""

    def __init__(self, logger: Optional[logging.Logger] = None):
        self.logger = logger or _passport
        self.events: list = []  # in-memory tail for tests/debug endpoint
        self.max_events = 10_000

    def log(self, event_type: str, **data: Any) -> None:
        doc = {"event": event_type, "ts": time.time(), **data}
        self.logger.info(event_type, extra={"doc": doc})
        self.events.append(doc)
        if len(self.events) > self.max_events:
            del self.events[:len(self.events) // 2]


passport = Passport()


def wire_store_passport(store) -> None:
    """Subscribe the passport to a store's tx feed."""

    def on_events(tx_id: int, events) -> None:
        for e in events:
            passport.log(e.kind, tx_id=tx_id, **{
                k: v for k, v in e.data.items()})

    store.subscribe(on_events)
