"""Shared retry/backoff and per-compute-cluster circuit breaking.

One definition of "back off with full jitter" for every reconnect loop in
the tree (the k8s watch streams, the remote-agent transport, the REST
client), replacing the hand-rolled ``min(max(backoff*2, ...), cap)``
inline loops.  Full jitter — ``uniform(0, min(cap, base * 2**attempt))``
— is deliberate: a cluster-wide apiserver restart otherwise synchronizes
every scheduler's watch reconnects into a thundering herd (the classic
AWS-architecture-blog result; the reference leans on okhttp's own
backoff, api.clj:372-475).

:class:`CircuitBreaker` is the degradation half: consecutive backend
failures open the breaker, an open breaker makes the matcher route
launches to healthy clusters (``Scheduler.launchable_clusters``), and a
half-open probe after ``reset_timeout_s`` discovers recovery.  Breakers
live in the module-level :data:`breakers` registry keyed by compute
cluster name so backends, matcher, REST, and the CLI all observe one
truth; state is exported as ``cook_circuit_breaker_state`` (0 closed,
1 half-open, 2 open) on /metrics and via ``cs debug faults``.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

from .metrics import registry

STATE_CLOSED = "closed"
STATE_HALF_OPEN = "half-open"
STATE_OPEN = "open"

_STATE_GAUGE = {STATE_CLOSED: 0.0, STATE_HALF_OPEN: 1.0, STATE_OPEN: 2.0}


@dataclass
class RetryPolicy:
    """Jittered-exponential retry knobs (shared by :func:`retry_call` and
    :class:`Backoff`)."""

    max_attempts: int = 5
    base_delay_s: float = 0.1
    max_delay_s: float = 5.0


class Backoff:
    """Stateful full-jitter exponential backoff for reconnect loops.

    ``next_delay()`` returns the next sleep; ``reset()`` on a healthy
    connection restarts the ladder.  A seeded ``rng`` makes tests
    deterministic; the default draws from the module RNG so independent
    reconnectors desynchronize (the whole point of the jitter).
    """

    def __init__(self, base_s: float = 0.1, cap_s: float = 5.0,
                 rng: Optional[random.Random] = None):
        self.base_s = base_s
        self.cap_s = cap_s
        self._rng = rng or random
        self.attempts = 0

    def next_delay(self) -> float:
        ceiling = min(self.cap_s, self.base_s * (2.0 ** self.attempts))
        self.attempts += 1
        return self._rng.uniform(0.0, ceiling)

    def reset(self) -> None:
        self.attempts = 0


def retry_call(fn: Callable, *, policy: Optional[RetryPolicy] = None,
               retry_on: Tuple[type, ...] = (Exception,),
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None,
               on_retry: Optional[Callable[[int, BaseException], None]]
               = None):
    """Call ``fn`` with jittered-exponential retries on ``retry_on``
    exceptions.  The last failure propagates once ``max_attempts`` is
    exhausted — callers own the terminal handling, this owns the pacing."""
    policy = policy or RetryPolicy()
    backoff = Backoff(policy.base_delay_s, policy.max_delay_s, rng=rng)
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            if attempt >= policy.max_attempts:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(backoff.next_delay())


class CircuitBreaker:
    """Per-backend failure gate: closed -> open after
    ``failure_threshold`` consecutive failures; open -> half-open after
    ``reset_timeout_s``.  Half-open admits traffic until an outcome is
    recorded (the matcher consults once per pool per cycle, so the probe
    granularity is one cycle's launches): the first half-open success
    closes, the first failure reopens and restarts the heal timer.

    ``clock`` is injectable so the chaos simulator runs breakers in
    virtual time (a breaker that only heals in wall time would deadlock
    a faster-than-real-time run)."""

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._publish(STATE_CLOSED)

    def _publish(self, state: str) -> None:
        registry.gauge_set("cook_circuit_breaker_state",
                           _STATE_GAUGE[state], {"cluster": self.name})

    def _set_state(self, state: str) -> None:
        if state != self._state:
            self._state = state
            self._publish(state)
            registry.counter_inc("cook_circuit_breaker_transitions",
                                 labels={"cluster": self.name, "to": state})

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if self._state == STATE_OPEN and \
                self.clock() - self._opened_at >= self.reset_timeout_s:
            self._set_state(STATE_HALF_OPEN)

    def allow(self) -> bool:
        """May a launch be routed at this backend right now?  Open says
        no; half-open says yes (the probe that discovers recovery)."""
        with self._lock:
            self._maybe_half_open()
            return self._state != STATE_OPEN

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != STATE_CLOSED:
                self._set_state(STATE_CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._maybe_half_open()
            if self._state == STATE_HALF_OPEN:
                # the probe failed: back to open, restart the heal timer
                self._opened_at = self.clock()
                self._set_state(STATE_OPEN)
                return
            self._failures += 1
            if self._state == STATE_CLOSED and \
                    self._failures >= self.failure_threshold:
                self._opened_at = self.clock()
                self._set_state(STATE_OPEN)

    def trip(self) -> None:
        """Force open (operator/chaos hook)."""
        with self._lock:
            self._opened_at = self.clock()
            self._set_state(STATE_OPEN)

    def reset(self) -> None:
        with self._lock:
            self._failures = 0
            self._set_state(STATE_CLOSED)

    def to_doc(self) -> Dict:
        with self._lock:
            self._maybe_half_open()
            return {"state": self._state,
                    "consecutive_failures": self._failures,
                    "failure_threshold": self.failure_threshold,
                    "reset_timeout_s": self.reset_timeout_s}


class BreakerRegistry:
    """Process-wide breakers keyed by compute-cluster name.  A module
    singleton (like the metrics registry) so the backend that records
    failures and the matcher that routes around them need no plumbing;
    ``configure`` sets the defaults new breakers are minted with, and
    ``clock`` retargets every breaker's timebase (chaos/virtual time)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self.failure_threshold = 5
        self.reset_timeout_s = 30.0
        self.clock: Callable[[], float] = time.monotonic

    def configure(self, failure_threshold: Optional[int] = None,
                  reset_timeout_s: Optional[float] = None,
                  clock: Optional[Callable[[], float]] = None) -> None:
        with self._lock:
            if failure_threshold is not None:
                self.failure_threshold = failure_threshold
            if reset_timeout_s is not None:
                self.reset_timeout_s = reset_timeout_s
            if clock is not None:
                self.clock = clock
            for b in self._breakers.values():
                if failure_threshold is not None:
                    b.failure_threshold = failure_threshold
                if reset_timeout_s is not None:
                    b.reset_timeout_s = reset_timeout_s
                if clock is not None:
                    b.clock = clock

    def get(self, name: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(name)
            if b is None:
                b = CircuitBreaker(
                    name, failure_threshold=self.failure_threshold,
                    reset_timeout_s=self.reset_timeout_s, clock=self.clock)
                self._breakers[name] = b
            return b

    def states(self) -> Dict[str, Dict]:
        with self._lock:
            items = list(self._breakers.items())
        return {name: b.to_doc() for name, b in items}

    def reset(self) -> None:
        """Drop every breaker (tests/chaos setup)."""
        with self._lock:
            self._breakers.clear()
            self.failure_threshold = 5
            self.reset_timeout_s = 30.0
            self.clock = time.monotonic


breakers = BreakerRegistry()
