"""Scheduler flight recorder: a fixed ring of per-cycle CycleRecords.

The per-cycle correlation layer the span ring alone can't give: every
driver cycle (fused production dispatch, split rank/match, rebalance)
opens a :meth:`FlightRecorder.cycle` context that

  1. roots a ``cycle`` tracing span, so every nested span (pack, kernel
     dispatch, fetch, launch RPC) shares the cycle's trace_id and the
     whole cycle exports as one Chrome/Perfetto flamegraph
     (``GET /debug/trace?trace_id=``);
  2. collects the cycle's device telemetry — recompiles per kernel,
     host<->device bytes, device sync-wait time (fed by
     cook_tpu.ops.telemetry), head-of-line skip reasons, preemptions,
     jobs considered/placed;
  3. on exit harvests the trace's spans into per-phase durations
     (rank / match / launch / rebalance) and lands the finished record in
     a fixed-size ring served by ``GET /debug/cycles`` and the
     ``cook-tpu debug cycles`` CLI.

This is the repro of the reference's structured match-cycle log documents
(scheduler.clj match cycle logging + prometheus_metrics.clj with-duration
tri-recording), extended with the JAX-level counters the reference never
needed: a recompile storm or transfer regression shows up as a labeled
field on the slow cycle's record, not a mystery p99 blip.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from cook_tpu.utils import tracing
from cook_tpu.utils.metrics import registry

_DEFAULT_CAPACITY = 512

# span name -> canonical phase; phase durations on a CycleRecord are the
# sum of the trace's span durations per phase.  Only TOP-LEVEL phase spans
# are mapped (cycle.rank contains fused.pack; summing both would double
# count), the finer span names stay visible in the trace export.
PHASE_BY_SPAN = {
    "cycle.rank": "rank",
    "rank.cycle": "rank",
    "cycle.match": "match",
    "scheduler.pool-handler": "match",
    "cycle.launch": "launch",
    "rebalancer.pool": "rebalance",
}

_current_record: "contextvars.ContextVar[Optional[CycleRecord]]" = \
    contextvars.ContextVar("cook_cycle_record", default=None)

# process-wide shard identity (ISSUE 19): a sharded-controller process
# owns exactly ONE partition shard, so the id is process state, not
# per-record plumbing — set once at shard boot (sched/shard.py), stamped
# onto every CycleRecord minted after.  None = unsharded (classic
# single-controller daemon): records export shard=null and the summary
# roll-up stays flat.
_shard_id: Optional[int] = None


def set_shard(shard: Optional[int]) -> None:
    """Declare this process's shard id (one partition = one process);
    every CycleRecord minted after carries it."""
    global _shard_id
    _shard_id = None if shard is None else int(shard)


def current_shard() -> Optional[int]:
    return _shard_id


class CycleRecord:
    """One scheduler cycle's instrument-panel readings."""

    __slots__ = ("seq", "kind", "trace_id", "start_s", "duration_ms",
                 "phases", "detail_ms", "pools", "jobs_considered",
                 "jobs_placed", "skip_reasons", "preemptions", "recompiles",
                 "h2d_bytes", "d2h_bytes", "sync_wait_ms", "faults",
                 "error", "pipeline_depth", "pipeline_inflight",
                 "pipeline_conflicts", "delta_rows", "full_repacks",
                 "audit_events", "kernel_launches", "path", "shard", "_t0")

    def __init__(self, seq: int, kind: str):
        self.seq = seq
        self.kind = kind
        # which controller shard ran this cycle (ISSUE 19 sharded
        # controllers; None on the classic single process) — the key the
        # stitched /debug/cycles roll-up and fleet trace group by
        self.shard: Optional[int] = _shard_id
        self.trace_id: Optional[str] = None
        self.start_s = time.time()
        self.duration_ms = 0.0
        self.phases: Dict[str, float] = {}       # phase -> ms
        self.pools = 0
        self.jobs_considered = 0
        self.jobs_placed = 0
        self.skip_reasons: Dict[str, int] = {}   # reason -> count
        self.preemptions = 0
        self.recompiles: Dict[str, int] = {}     # kernel -> compiles
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.sync_wait_ms = 0.0
        # fault-point triggers and degradations observed during this
        # cycle (utils/faults.py + kernel/fused fallbacks): a degraded
        # cycle explains itself without cross-referencing logs
        self.faults: Dict[str, int] = {}
        self.error: Optional[str] = None
        # pipelined-driver readings (sched/pipeline.py): configured depth
        # (0 = sync driver), dispatches in flight when this cycle's step
        # finished staging, and reconciliation conflict drops applied
        # inside this cycle
        self.pipeline_depth = 0
        self.pipeline_inflight = 0
        self.pipeline_conflicts = 0
        # sub-phase breakdown the whole-phase durations hide (ISSUE 7
        # satellite): host staging split into pack (store->arrays) /
        # stage (arrays->wire form) / apply (outputs->transactions), so a
        # staging regression is diagnosable from /debug/cycles without a
        # profiler.  Plus the resident-pack readings: delta rows shipped
        # on-chip this cycle and full repacks (reason-labeled on
        # cook_resident_repack_total).
        self.detail_ms: Dict[str, float] = {}
        self.delta_rows = 0
        self.full_repacks = 0
        # per-job audit events recorded during this cycle (utils/audit.py):
        # the audit lane's own overhead meter — a cycle that recorded
        # nothing proves the quiet fast path stayed zero-work
        self.audit_events = 0
        # device kernel dispatches inside this cycle (ISSUE 14: every
        # InstrumentedJit call counts one) and the cycle path that made
        # them: "split" (per-stage XLA launches), "fused" (one XLA pool
        # cycle), "megakernel" (single Pallas launch), or "mixed" when
        # one cycle's dispatch groups took different paths — a path
        # regression (megakernel silently degrading to fused) is visible
        # in /debug/cycles and the Perfetto export
        self.kernel_launches = 0
        self.path: Optional[str] = None
        self._t0 = time.perf_counter()

    def to_doc(self) -> Dict[str, Any]:
        return {
            "seq": self.seq, "kind": self.kind, "trace_id": self.trace_id,
            "start": self.start_s, "duration_ms": round(self.duration_ms, 3),
            "phases_ms": {k: round(v, 3) for k, v in self.phases.items()},
            "pools": self.pools,
            "jobs_considered": self.jobs_considered,
            "jobs_placed": self.jobs_placed,
            "skip_reasons": dict(self.skip_reasons),
            "preemptions": self.preemptions,
            "recompiles": dict(self.recompiles),
            "h2d_bytes": self.h2d_bytes,
            "d2h_bytes": self.d2h_bytes,
            "sync_wait_ms": round(self.sync_wait_ms, 3),
            "faults": dict(self.faults),
            "pipeline_depth": self.pipeline_depth,
            "pipeline_inflight": self.pipeline_inflight,
            "pipeline_conflicts": self.pipeline_conflicts,
            "detail_ms": {k: round(v, 3) for k, v in self.detail_ms.items()},
            "delta_rows": self.delta_rows,
            "full_repacks": self.full_repacks,
            "audit_events": self.audit_events,
            "kernel_launches": self.kernel_launches,
            "path": self.path,
            "shard": self.shard,
            "error": self.error,
        }


class FlightRecorder:
    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._ring: "deque[CycleRecord]" = deque(maxlen=capacity)
        self._seq = 0
        self.enabled = True

    # ------------------------------------------------------------- lifecycle
    @contextmanager
    def cycle(self, kind: str = "cycle", **tags: Any):
        """Open (or join) the current cycle record.  Re-entrant: a nested
        call (e.g. a sub-step that can also run standalone) joins the
        enclosing record instead of splitting the cycle in two."""
        cur = _current_record.get()
        if not self.enabled or cur is not None:
            yield cur
            return
        with self._lock:
            self._seq += 1
            rec = CycleRecord(self._seq, kind)
        token = _current_record.set(rec)
        try:
            with tracing.span("cycle", kind=kind, seq=rec.seq, **tags) as sp:
                rec.trace_id = getattr(sp, "trace_id", None)
                yield rec
        except BaseException as exc:
            rec.error = f"{type(exc).__name__}: {exc}"
            raise
        finally:
            _current_record.reset(token)
            rec.duration_ms = (time.perf_counter() - rec._t0) * 1000.0
            self._finish(rec)

    def _finish(self, rec: CycleRecord) -> None:
        if rec.trace_id is not None:
            for doc in tracing.tracer.traces(rec.trace_id):
                phase = PHASE_BY_SPAN.get(doc["span"])
                if phase is not None:
                    rec.phases[phase] = rec.phases.get(phase, 0.0) \
                        + (doc.get("duration_ms") or 0.0)
        with self._lock:
            self._ring.append(rec)
        registry.observe("cook_cycle_duration_seconds",
                         rec.duration_ms / 1000.0, {"kind": rec.kind})
        if rec.jobs_considered:
            registry.counter_inc("cook_cycle_jobs_considered",
                                 rec.jobs_considered)
        if rec.jobs_placed:
            registry.counter_inc("cook_cycle_jobs_placed", rec.jobs_placed)

    # ------------------------------------------------------------- telemetry
    def current(self) -> Optional[CycleRecord]:
        return _current_record.get()

    def note_recompile(self, kernel: str, n: int = 1) -> None:
        rec = _current_record.get()
        if rec is not None:
            with self._lock:
                rec.recompiles[kernel] = rec.recompiles.get(kernel, 0) + n

    def note_transfer(self, direction: str, nbytes: int) -> None:
        rec = _current_record.get()
        if rec is not None:
            with self._lock:
                if direction == "h2d":
                    rec.h2d_bytes += int(nbytes)
                else:
                    rec.d2h_bytes += int(nbytes)

    def note_sync_wait(self, seconds: float) -> None:
        rec = _current_record.get()
        if rec is not None:
            with self._lock:
                rec.sync_wait_ms += seconds * 1000.0

    def note_skips(self, reasons: Dict[str, int]) -> None:
        """Head-of-line skip reasons histogram (why a pending job was
        passed over this cycle: over-quota, rate-limited, launch-filtered,
        offensive, unmatched, launch-failed)."""
        rec = _current_record.get()
        if rec is None:
            return
        with self._lock:
            for reason, n in reasons.items():
                if n:
                    rec.skip_reasons[reason] = \
                        rec.skip_reasons.get(reason, 0) + int(n)

    def note_preemptions(self, n: int) -> None:
        rec = _current_record.get()
        if rec is not None and n:
            with self._lock:
                rec.preemptions += int(n)

    def note_pipeline(self, depth: int, inflight: int) -> None:
        """Pipelined-driver shape of the current cycle (sched/pipeline.py):
        configured depth and dispatches in flight after staging."""
        rec = _current_record.get()
        if rec is not None:
            with self._lock:
                rec.pipeline_depth = int(depth)
                rec.pipeline_inflight = int(inflight)

    def note_pipeline_conflicts(self, n: int) -> None:
        """Reconciliation conflict drops (candidates re-validated against
        the store and dropped instead of double-launched) inside the
        current cycle."""
        rec = _current_record.get()
        if rec is not None and n:
            with self._lock:
                rec.pipeline_conflicts += int(n)

    def note_phase_detail(self, name: str, ms: float) -> None:
        """Sub-phase duration (pack / stage / apply) summed onto the
        current record's detail breakdown."""
        rec = _current_record.get()
        if rec is not None:
            with self._lock:
                rec.detail_ms[name] = rec.detail_ms.get(name, 0.0) \
                    + float(ms)

    def note_delta(self, rows: int) -> None:
        """Delta rows scatter-applied into the device-resident pack this
        cycle (0 on a quiet cycle; the steady-state guard asserts it)."""
        rec = _current_record.get()
        if rec is not None and rows:
            with self._lock:
                rec.delta_rows += int(rows)

    def note_repack(self, reason: str) -> None:
        """A full resident-pack repack (reason also labels
        cook_resident_repack_total)."""
        rec = _current_record.get()
        if rec is not None:
            with self._lock:
                rec.full_repacks += 1

    def note_audit(self, n: int = 1) -> None:
        """Per-job audit events (utils/audit.py) recorded inside the
        current cycle."""
        rec = _current_record.get()
        if rec is not None and n:
            with self._lock:
                rec.audit_events += int(n)

    def note_kernel_launch(self, kernel: str, n: int = 1) -> None:
        """One device kernel dispatch attributed to the current cycle
        (counted by InstrumentedJit on every call — the megakernel's
        headline is this number going to 1)."""
        rec = _current_record.get()
        if rec is not None and n:
            with self._lock:
                rec.kernel_launches += int(n)

    def note_path(self, path: str) -> None:
        """The cycle's dispatch path (split | fused | megakernel); two
        different notes inside one cycle record as "mixed".  Also tagged
        onto the live cycle span so the Perfetto export carries it."""
        rec = _current_record.get()
        if rec is None:
            return
        with self._lock:
            if rec.path is None or rec.path == path:
                rec.path = path
            else:
                rec.path = "mixed"
        sp = tracing.tracer.current()
        if sp is not None:
            sp.set_tag("path", rec.path)

    def note_fault(self, point: str, n: int = 1) -> None:
        """A fault-point trigger or degradation (kernel fallback, breaker
        reroute) attributed to the cycle it happened inside."""
        rec = _current_record.get()
        if rec is not None:
            with self._lock:
                rec.faults[point] = rec.faults.get(point, 0) + int(n)

    # ----------------------------------------------------------------- query
    def recent(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-last list of finished cycle record documents."""
        limit = int(limit)
        if limit <= 0:
            return []
        with self._lock:
            records = list(self._ring)
        return [r.to_doc() for r in records[-limit:]]

    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def recent_durations(self, kinds, limit: int) -> List[float]:
        """duration_ms of the newest ``limit`` records of the given kinds,
        oldest first — the SLO sweep's cheap periodic read (no to_doc
        dict materialization for the whole ring)."""
        with self._lock:
            records = list(self._ring)
        out = [r.duration_ms for r in records if r.kind in kinds]
        return out[-max(int(limit), 0):] if limit > 0 else []

    def summary(self, since_seq: int = 0) -> Dict[str, Any]:
        """Aggregate over records with seq > since_seq (the simulator and
        bench sections snapshot last_seq() at start and summarize their
        own cycles at the end).  A run longer than the ring capacity is
        reported with ``truncated``/``cycles_evicted`` so an aggregate
        over a partial window is never mistaken for the whole run."""
        with self._lock:
            records = [r for r in self._ring if r.seq > since_seq]
            oldest = self._ring[0].seq if self._ring else self._seq + 1
        if not records:
            return {"cycles": 0}
        evicted = max(0, oldest - since_seq - 1)
        durs = sorted(r.duration_ms for r in records)

        def pctl(q: float) -> float:
            idx = min(len(durs) - 1, int(round(q / 100.0 * (len(durs) - 1))))
            return round(durs[idx], 3)

        by_shard: Dict[int, List[float]] = {}
        for r in records:
            if r.shard is not None:
                by_shard.setdefault(r.shard, []).append(r.duration_ms)

        def _shard_agg(durations: List[float]) -> Dict[str, Any]:
            ds = sorted(durations)

            def sp(q: float) -> float:
                i = min(len(ds) - 1, int(round(q / 100.0 * (len(ds) - 1))))
                return round(ds[i], 3)

            return {"cycles": len(ds), "cycle_ms_p50": sp(50),
                    "cycle_ms_p99": sp(99)}

        by_kind: Dict[str, int] = {}
        recompiles: Dict[str, int] = {}
        skips: Dict[str, int] = {}
        faults: Dict[str, int] = {}
        detail: Dict[str, float] = {}
        for r in records:
            by_kind[r.kind] = by_kind.get(r.kind, 0) + 1
            for k, v in r.recompiles.items():
                recompiles[k] = recompiles.get(k, 0) + v
            for k, v in r.skip_reasons.items():
                skips[k] = skips.get(k, 0) + v
            for k, v in r.faults.items():
                faults[k] = faults.get(k, 0) + v
            for k, v in r.detail_ms.items():
                detail[k] = detail.get(k, 0.0) + v
        return {
            "cycles": len(records),
            **({"truncated": True, "cycles_evicted": evicted}
               if evicted else {}),
            "by_kind": by_kind,
            "cycle_ms_p50": pctl(50),
            "cycle_ms_p99": pctl(99),
            # per-shard roll-up (ISSUE 19): keyed by CycleRecord.shard,
            # present only when sharded cycles are in the window so the
            # classic single-process summary shape is unchanged
            **({"by_shard": {str(s): _shard_agg(d)
                             for s, d in sorted(by_shard.items())}}
               if by_shard else {}),
            "jobs_considered": sum(r.jobs_considered for r in records),
            "jobs_placed": sum(r.jobs_placed for r in records),
            "preemptions": sum(r.preemptions for r in records),
            "recompiles": recompiles,
            "skip_reasons": skips,
            "faults": faults,
            "pipeline_conflicts": sum(r.pipeline_conflicts
                                      for r in records),
            "h2d_bytes": sum(r.h2d_bytes for r in records),
            "d2h_bytes": sum(r.d2h_bytes for r in records),
            "sync_wait_ms": round(sum(r.sync_wait_ms for r in records), 3),
            "detail_ms": {k: round(v, 3) for k, v in detail.items()},
            "delta_rows": sum(r.delta_rows for r in records),
            "full_repacks": sum(r.full_repacks for r in records),
            "audit_events": sum(r.audit_events for r in records),
        }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()


recorder = FlightRecorder()
