"""Deterministic fault injection at named points in the hot paths.

The chaos-engineering layer (Basiri et al., IEEE Software 2016) for the
failure paths the survey says must stay *proven*, not assumed: store
commit/fsync, the replication stream, remote-cluster RPC and agent
heartbeat delivery, the k8s watch stream, kernel dispatch, and the
leader lease.  Each such site calls :meth:`FaultInjector.fire` (raise on
trigger) or :meth:`should_fire` (boolean branch) with its point name; a
disarmed injector reduces to one dict lookup, so production pays nothing.

Fault points are armed by name with either a probability (seeded RNG —
the same seed replays the same fault sequence) or an explicit schedule
of call indices (exact, for tests: "fail the 3rd journal append").
Every trigger increments ``cook_faults_injected_total{point=...}`` and
lands on the owning CycleRecord's ``faults`` map, so a degraded cycle
explains itself in ``/debug/cycles``.

Registered point names (the sites that consult this module):

==========================  ====================================================
``store.journal.append``    `state/store.py` — journal write fails (disk error)
``store.journal.fsync``     `state/store.py` — fsync fails after the write
``repl.stream``             `state/store.py` — stream down BEFORE the record
                            was written (clean abort)
``repl.ack``                `state/store.py` — follower ack never arrives
                            AFTER the record is durable locally
                            (indeterminate outcome)
``remote.rpc``              `cluster/remote.py` — agent launch RPC fails
``agent.heartbeat``         `sched/scheduler.py` — a heartbeat frame is dropped
``k8s.watch.disconnect``    `cluster/k8s/real_api.py` — watch stream drops
``k8s.watch.gone``          `cluster/k8s/real_api.py` — 410 Gone (watch gap)
``kernel.dispatch``         `sched/matcher.py` — XLA kernel dispatch raises
``fused.dispatch``          `sched/fused.py` — whole fused cycle dispatch raises
``leader.lease``            `sched/election.py` — lease acquire/renew fails
``cluster.launch``          `cluster/fake.py` — backend rejects a launch
``store.journal.torn_write``  `state/store.py` — a PREFIX of the frame lands
                            then the write fails (``arg`` = cut byte offset);
                            exercises the torn-tail excision discipline
``store.journal.bitflip``   `state/store.py` — one bit flips in the
                            just-written frame (``arg`` = byte offset), with
                            NO error surfaced: silent media corruption for
                            the CRC scrub/replay to catch
``store.journal.fsync_lie`` `state/store.py` — fsync reports EIO while the
                            page cache silently drops the dirty frame and
                            the next fsync succeeds (the ATC'20
                            "succeeds-after-failure" lie)
``store.journal.enospc``    `state/store.py` — ENOSPC on append: a clean
                            abort surfaced as StorageFullError (503 +
                            admission write-shed, never a dead daemon)
``fsatomic.fsync``          `utils/fsatomic.py` — fsync of an atomic-write
                            temp fails (checkpoint/fence publish aborts;
                            the orphaned temp is the hygiene sweep's prey)
==========================  ====================================================
"""

from __future__ import annotations

import random
import threading
from typing import Any, Callable, Dict, List, Optional

from .metrics import registry


class FaultInjected(RuntimeError):
    """Raised by :meth:`FaultInjector.fire` when a point triggers."""

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point}")
        self.point = point


class _Point:
    __slots__ = ("name", "probability", "schedule", "max_fires",
                 "calls", "fires", "arg")

    def __init__(self, name: str, probability: float = 0.0,
                 schedule: Optional[List[int]] = None,
                 max_fires: Optional[int] = None,
                 arg: Optional[Any] = None):
        self.name = name
        self.probability = float(probability)
        # explicit call indices (0-based) that fire, e.g. [2] = third call
        self.schedule = set(schedule or [])
        self.max_fires = max_fires
        self.calls = 0
        self.fires = 0
        # site-interpreted parameter (e.g. the byte offset a torn write
        # cuts at, or the byte a bitflip targets) — what lets the
        # crash-point harness sweep every record byte boundary
        self.arg = arg

    def to_doc(self) -> Dict[str, Any]:
        return {"probability": self.probability,
                "schedule": sorted(self.schedule),
                "max_fires": self.max_fires,
                "calls": self.calls, "fires": self.fires,
                **({"arg": self.arg} if self.arg is not None else {})}


class FaultInjector:
    """Seeded, thread-safe fault-point registry.  Disabled points cost
    one dict miss per consultation; the module singleton :data:`injector`
    is what the call sites import."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._points: Dict[str, _Point] = {}
        self._seed = seed

    # -------------------------------------------------------------- arming
    def reseed(self, seed: int) -> None:
        with self._lock:
            self._seed = seed
            self._rng = random.Random(seed)

    def arm(self, point: str, probability: float = 0.0,
            schedule: Optional[List[int]] = None,
            max_fires: Optional[int] = None,
            arg: Optional[Any] = None) -> None:
        with self._lock:
            self._points[point] = _Point(point, probability, schedule,
                                         max_fires, arg)

    def disarm(self, point: str) -> None:
        with self._lock:
            self._points.pop(point, None)

    def clear(self) -> None:
        with self._lock:
            self._points.clear()

    def configure(self, spec: Dict[str, Any]) -> None:
        """Arm from a config document:
        ``{"seed": 7, "points": {"remote.rpc": {"probability": 0.05},
        "store.journal.append": {"schedule": [3], "max_fires": 1}}}``.
        This is the shape `config.FaultInjectionConfig` and the daemon's
        ``"faults"`` conf section carry."""
        if "seed" in spec:
            self.reseed(int(spec["seed"]))
        for name, knobs in (spec.get("points") or {}).items():
            self.arm(name,
                     probability=float(knobs.get("probability", 0.0)),
                     schedule=list(knobs.get("schedule", [])),
                     max_fires=knobs.get("max_fires"),
                     arg=knobs.get("arg"))

    # ------------------------------------------------------------- firing
    def should_fire(self, point: str) -> bool:
        """True when the armed point triggers on this call.  Counts the
        call either way (schedules index by consultation order)."""
        with self._lock:
            p = self._points.get(point)
            if p is None:
                return False
            idx = p.calls
            p.calls += 1
            if p.max_fires is not None and p.fires >= p.max_fires:
                return False
            hit = idx in p.schedule or (
                p.probability > 0.0 and self._rng.random() < p.probability)
            if hit:
                p.fires += 1
        if hit:
            registry.counter_inc("cook_faults_injected",
                                 labels={"point": point})
            # a degraded cycle explains itself on its own CycleRecord
            from .flight import recorder
            recorder.note_fault(point)
        return hit

    def fire(self, point: str,
             exc_factory: Optional[Callable[[], BaseException]]
             = None) -> None:
        """Raise (``FaultInjected`` by default) when the point triggers."""
        if self.should_fire(point):
            raise (exc_factory() if exc_factory is not None
                   else FaultInjected(point))

    def point_arg(self, point: str) -> Optional[Any]:
        """The armed point's site-interpreted parameter (byte offsets
        for the disk-fault sites), or None when unarmed/unset."""
        with self._lock:
            p = self._points.get(point)
            return p.arg if p is not None else None

    # -------------------------------------------------------------- query
    def active(self) -> Dict[str, Dict[str, Any]]:
        """Armed points and their counters, for ``GET /debug/faults`` and
        ``cs debug faults``."""
        with self._lock:
            return {name: p.to_doc() for name, p in self._points.items()}

    @property
    def seed(self) -> int:
        return self._seed


injector = FaultInjector()
