from .logging import (  # noqa: F401
    JsonFormatter,
    Passport,
    log_structured,
    passport,
    wire_store_passport,
)
from .metrics import MetricsRegistry, registry  # noqa: F401
