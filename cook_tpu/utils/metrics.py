"""Minimal metrics registry with Prometheus text exposition.

Plays the role of the reference's tri-recorded metrics (reference:
prometheus_metrics.clj — 765 LoC of metric defs with a with-duration macro;
reporter.clj dropwizard wiring): counters, gauges, and duration histograms
keyed by (name, labels), exposed at /metrics.
"""

from __future__ import annotations

import contextvars
import re
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

#: context-scoped write suppression (propagates into copy_context worker
#: threads, like tracing's span context): the optimizer's
#: faster-than-real-time sim replay (sched/optimizer.py) drives a REAL
#: scheduler in-process, and its counters must not leak into the
#: production exposition — a replayed preemption is not a preemption
_suppressed: "contextvars.ContextVar[bool]" = \
    contextvars.ContextVar("cook_metrics_suppressed", default=False)

_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
            5.0, 10.0)

# wait/age histograms (queue latency SLOs) live on second-to-hour scales
# the default duration buckets can't resolve
LATENCY_BUCKETS = (1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
                   1800.0, 3600.0, 7200.0, 14400.0)


def _labels_key(labels: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


def _escape_label_value(value) -> str:
    """Prometheus text-format label escaping (exposition spec: label_value
    may contain any UTF-8 but ``\\``, ``"`` and line feeds must be escaped
    as ``\\\\``, ``\\"`` and ``\\n``).  Without this, a label like
    reason="no \"fit\"" corrupts the whole scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels_str(key: Tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


def _unescape_label_value(value: str) -> str:
    """Inverse of :func:`_escape_label_value` (federation parse side)."""
    out: List[str] = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            n = value[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(n, "\\" + n))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_LINE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+([^\s]+)\s*$')


def parse_exposition(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse Prometheus text exposition into ``(exposed_name, labels,
    value)`` triples — the federation scraper's read side (sched/fleet.py).
    Exposed names are kept VERBATIM (``_total``/``_bucket``/``_count``/
    ``_sum`` suffixes intact): federation re-labels and re-emits, it
    never re-interprets metric types.  Comment/HELP/TYPE lines and
    malformed lines are skipped (a member mid-restart must not poison
    the whole fleet view); non-finite values (``NaN``/``+Inf`` bucket
    bounds live in label values, not sample values) parse via float()."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if not m:
            continue
        name, labels_str, value_str = m.groups()
        try:
            value = float(value_str)
        except ValueError:
            continue
        labels: Dict[str, str] = {}
        if labels_str:
            for lm in _LABEL_RE.finditer(labels_str[1:-1]):
                labels[lm.group(1)] = _unescape_label_value(lm.group(2))
        out.append((name, labels, value))
    return out


def format_sample(name: str, labels: Dict[str, str], value: float) -> str:
    """One exposition line from an (exposed_name, labels, value) triple —
    the federation re-emit side, escaping-symmetric with parse."""
    return f"{name}{_labels_str(_labels_key(labels))} {value}"


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple], float] = {}
        self._gauges: Dict[Tuple[str, Tuple], float] = {}
        # histogram state is fixed-size: cumulative bucket counts + count/sum
        self._histograms: Dict[Tuple[str, Tuple], Dict] = {}
        # cardinality guard (docs/OBSERVABILITY.md): (metric, label) ->
        # (cap, scope-label) on DISTINCT label values.  Past the cap,
        # samples fold into value "other" and
        # cook_metrics_dropped_labels_total counts the fold — per-user
        # fairness gauges stay bounded at millions-of-users scale.  The
        # window is PER SCOPE value (default scope "pool"): each pool's
        # user population gets its own cap, so a later-swept pool's
        # legitimate top-K is never folded just because earlier pools
        # filled a global window.  Admission is first-come within a
        # window; publishers that want top-K-by-usage (sched/monitor.py)
        # sort before publishing and reset_label_window() each sweep.
        self._label_caps: Dict[Tuple[str, str],
                               Tuple[int, Tuple[str, ...]]] = {}
        self._label_seen: Dict[Tuple[str, str], Dict[Tuple, set]] = {}

    # ------------------------------------------------------ cardinality guard
    OTHER_LABEL = "other"

    def set_label_cap(self, name: str, label: str, cap: int,
                      scope: Tuple[str, ...] = ("pool",)) -> None:
        """Cap distinct values of ``label`` on metric ``name`` per
        distinct combination of the ``scope`` labels (empty tuple = one
        global window); overflow samples are re-labeled ``other``
        (idempotent re-registration)."""
        with self._lock:
            self._label_caps[(name, label)] = (int(cap), tuple(scope))
            self._label_seen.setdefault((name, label), {})

    def reset_label_window(self, name: str, label: str) -> None:
        """Forget which values currently hold a slot (a periodic
        publisher calls this each sweep so a NEW top-K can claim the
        slots; already-exported stale series are the publisher's to
        zero/clear)."""
        with self._lock:
            self._label_seen.get((name, label), {}).clear()

    def _guard_labels(self, name: str,
                      labels: Optional[Dict[str, str]]
                      ) -> Optional[Dict[str, str]]:
        """Apply label caps (caller does NOT hold the lock).  Returns
        possibly-rewritten labels; counts folds."""
        if not labels or not self._label_caps:
            return labels
        folded = None
        for label, value in list(labels.items()):
            key = (name, label)
            capinfo = self._label_caps.get(key)
            if capinfo is None or value == self.OTHER_LABEL:
                continue
            cap, scope = capinfo
            group = tuple(labels.get(s, "") for s in scope)
            with self._lock:
                seen = self._label_seen.setdefault(
                    key, {}).setdefault(group, set())
                if value in seen:
                    continue
                if len(seen) < cap:
                    seen.add(value)
                    continue
            if folded is None:
                folded = dict(labels)
            folded[label] = self.OTHER_LABEL
            key2 = ("cook_metrics_dropped_labels",
                    _labels_key({"metric": name, "label": label}))
            with self._lock:
                self._counters[key2] = self._counters.get(key2, 0.0) + 1.0
        return folded if folded is not None else labels

    @contextmanager
    def suppressed(self):
        """Suppress every metric WRITE made from this context (and from
        workers started via ``contextvars.copy_context().run`` under it)
        — the optimizer's sim replay runs whole schedulers in-process
        and their counters are simulation, not production truth."""
        token = _suppressed.set(True)
        try:
            yield
        finally:
            _suppressed.reset(token)

    def counter_inc(self, name: str, value: float = 1.0,
                    labels: Optional[Dict[str, str]] = None) -> None:
        if _suppressed.get():
            return
        key = (name, _labels_key(self._guard_labels(name, labels)))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def gauge_set(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        if _suppressed.get():
            return
        labels = self._guard_labels(name, labels)
        with self._lock:
            self._gauges[(name, _labels_key(labels))] = value

    def gauge_clear(self, name: str) -> None:
        """Drop every series of ``name`` — for gauges whose label sets
        name ephemeral entities (e.g. per-connection replication
        followers): re-set at each refresh, the series set stays bounded
        to what is live instead of accumulating frozen stale labels."""
        with self._lock:
            for key in [k for k in self._gauges if k[0] == name]:
                del self._gauges[key]

    def observe(self, name: str, value_s: float,
                labels: Optional[Dict[str, str]] = None,
                buckets: Optional[Tuple[float, ...]] = None) -> None:
        """Record one histogram observation.  ``buckets`` fixes the bound
        set on FIRST observation of a series (later values are ignored —
        cumulative bucket counts cannot be re-bucketed); default is the
        sub-second duration ladder, pass ``LATENCY_BUCKETS`` for
        second-to-hour wait times."""
        if _suppressed.get():
            return
        key = (name, _labels_key(self._guard_labels(name, labels)))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                bounds = tuple(buckets) if buckets is not None else _BUCKETS
                h = {"bounds": bounds, "buckets": [0] * len(bounds),
                     "count": 0, "sum": 0.0}
                self._histograms[key] = h
            for i, b in enumerate(h["bounds"]):
                if value_s <= b:
                    h["buckets"][i] += 1
            h["count"] += 1
            h["sum"] += value_s

    def observe_many(self, name: str, values_s,
                     labels: Optional[Dict[str, str]] = None,
                     buckets: Optional[Tuple[float, ...]] = None) -> None:
        """Bulk histogram observation: per-bucket counts are computed
        OUTSIDE the lock (one sort + searchsorted), then merged under one
        lock hold — the monitor's 100k-pending-job age sweep must not
        turn into 100k individual locked bucket scans."""
        if _suppressed.get():
            return
        import numpy as np
        vals = np.asarray(list(values_s), dtype=float)
        if vals.size == 0:
            return
        key = (name, _labels_key(self._guard_labels(name, labels)))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                bounds = tuple(buckets) if buckets is not None else _BUCKETS
                h = {"bounds": bounds, "buckets": [0] * len(bounds),
                     "count": 0, "sum": 0.0}
                self._histograms[key] = h
            bounds = h["bounds"]
        # cumulative "value <= bound" counts, vectorized and unlocked
        counts = np.searchsorted(np.sort(vals), np.asarray(bounds),
                                 side="right")
        total, vsum = int(vals.size), float(vals.sum())
        with self._lock:
            for i, c in enumerate(counts):
                h["buckets"][i] += int(c)
            h["count"] += total
            h["sum"] += vsum

    @contextmanager
    def time(self, name: str, labels: Optional[Dict[str, str]] = None):
        """The reference's with-duration macro."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0, labels)

    def series(self, name: str) -> List[Tuple[Dict[str, str], float]]:
        """Every current series of gauge/counter ``name`` as
        (labels dict, value) pairs — the structured accessor the
        /debug/health roll-up reads (snapshot() flattens labels into
        strings, which a consumer would have to re-parse)."""
        with self._lock:
            out = [(dict(k), v) for (n, k), v in self._gauges.items()
                   if n == name]
            out += [(dict(k), v) for (n, k), v in self._counters.items()
                    if n == name]
        return out

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "counters": {f"{n}{_labels_str(k)}": v
                             for (n, k), v in self._counters.items()},
                "gauges": {f"{n}{_labels_str(k)}": v
                           for (n, k), v in self._gauges.items()},
                "histogram_counts": {f"{n}{_labels_str(k)}": v["count"]
                                     for (n, k), v in self._histograms.items()},
            }

    def expose(self) -> str:
        """Prometheus text format."""
        lines: List[str] = []
        with self._lock:
            for (name, key), value in sorted(self._counters.items()):
                lines.append(f"{name}_total{_labels_str(key)} {value}")
            for (name, key), value in sorted(self._gauges.items()):
                lines.append(f"{name}{_labels_str(key)} {value}")
            for (name, key), h in sorted(self._histograms.items()):
                for i, b in enumerate(h.get("bounds", _BUCKETS)):
                    bucket_key = key + (("le", str(b)),)
                    lines.append(f"{name}_bucket{_labels_str(bucket_key)} "
                                 f"{h['buckets'][i]}")
                inf_key = key + (("le", "+Inf"),)
                lines.append(f"{name}_bucket{_labels_str(inf_key)} "
                             f"{h['count']}")
                lines.append(f"{name}_count{_labels_str(key)} {h['count']}")
                lines.append(f"{name}_sum{_labels_str(key)} {h['sum']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._label_caps.clear()
            self._label_seen.clear()


registry = MetricsRegistry()
