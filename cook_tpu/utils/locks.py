"""Named ordered locks + a dynamic lock-order/race sanitizer.

THE GLOBAL LOCK-ORDER CONTRACT (the single home of the rule that used to
live only in CHANGES.md prose — every module that nests two of these
locks must acquire them in ascending rank):

    ======  ==================  ==============================================
    rank    lock name           owner
    ======  ==================  ==============================================
    10      ``store.notify``    `state/store.py` — commit-ordered event drain
    15      ``read_replica``    `state/read_replica.py` — apply-loop/rebuild mutex
    18      ``elastic``         `sched/elastic.py` — resize-ledger mutex
    20      ``store``           `state/store.py` — the store's main RLock
    30      ``index``           `state/index.py` — columnar projection mutex
    40      ``audit``           `utils/audit.py` — per-job lane mutex
    50      ``repl.server``     `state/replication.py` — native-handle mutex
    55      ``repl.follower``   `state/replication.py` — native-handle mutex
    ======  ==================  ==============================================

    **Rank families** (the partitioned write plane, state/partition.py):
    a bracketed suffix scopes a lock to one partition without changing
    its rank — ``store[p0]``, ``store[p1]``, ``store.notify[p3]`` all
    carry their base name's declared rank.  SIBLING locks of one family
    (same base, different suffix — two partitions' store locks) carry
    the SAME rank, and same-rank cross-acquisition is ambiguous by
    construction: thread A holding ``store[p0]`` while taking
    ``store[p1]`` and thread B doing the reverse is a textbook deadlock
    the rank table cannot order.  The contract is therefore: **sibling
    locks of a rank family may never nest in each other** (the
    partitioned facade fans out sequentially, releasing each
    partition's lock before the next) — the sanitizer reports any
    sibling nesting as a ``sibling`` violation, and the bare base name
    counts as a sibling of its bracketed forms (``store`` inside
    ``store[p0]`` is equally unorderable).  Blocking-op allowlist
    entries apply family-wide: ``("store", "os.fsync")`` covers every
    ``store[pN]``.

Canonical nestings this encodes: ``store.notify → store`` (the drain loop
pops the event queue under the store lock), ``store.notify → index`` /
``store.notify → audit`` (tx-feed subscribers), ``store → audit``
(``flush_audit`` drains the advisory batch under the store lock — PR 7's
"store→audit is the single lock order everywhere"), ``store →
repl.server`` (journal append pokes/awaits the replication server), and
``read_replica → store`` (the read view rebuilds/applies into its store
while holding its own mutex).  Acquiring against the ranks is a
potential deadlock and is reported by the sanitizer.

How it works (Eraser-style lockset discipline, Savage et al. TOCS'97,
adapted to ordering): every :class:`NamedLock`/:class:`NamedRLock`
acquisition consults a per-thread held stack kept by a
:class:`LockMonitor`.  The monitor

* records the **acquisition-graph edge** (innermost held lock → lock
  being acquired) — one dict hit per *novel* edge, near-zero steady
  state cost, so the graph is recorded in production too and exposed on
  ``GET /debug/health`` under ``"locks"``;
* on a novel edge, runs a DFS **cycle check** — an A→B edge when B→A is
  already reachable is a potential deadlock — and checks the **declared
  rank order** above;
* when :meth:`LockMonitor.arm_blocking_detector` is armed (the tier-1
  conftest does this), patches ``os.fsync`` / ``time.sleep`` /
  ``socket.socket.connect`` / ``socket.socket.sendall`` so a **blocking
  syscall while holding a named lock** is recorded unless the
  (lock, op) pair is explicitly allowlisted (:data:`ALLOWED_BLOCKING`
  — e.g. the store's write-ahead ``os.fsync`` under the store lock is
  the durability contract itself, not a bug).

Violations increment ``cook_lock_violations_total{kind=...}`` and are
kept on the monitor for the tier-1 teardown assert and ``/debug/health``.
The static half of this rail — the lexical blocking-call-under-lock lint
— lives in ``cook_tpu/analysis`` (docs/ANALYSIS.md).
"""

from __future__ import annotations

import os
import socket
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Set, Tuple

#: (lock name, operation) pairs that are BY DESIGN blocking while held —
#: each entry is a documented contract, not an oversight.  Consulted by
#: BOTH rails: the armed runtime detector below AND the static
#: interprocedural blocking pass (cook_tpu/analysis/summaries.py parses
#: this literal), so the two agree by construction:
#:   - ("store", "os.fsync"): the write-ahead journal fsync (and the
#:     checkpoint snapshot's fsatomic fsync) must complete before the
#:     transaction installs / the journal truncates — durability IS the
#:     reason the lock is held (state/store.py _journal_append,
#:     _write_audit_record_locked, checkpoint).  Group commit moves the
#:     steady-state fsync off the lock; the inline path remains correct.
#:   - ("store", "fsatomic.fsync"): the same contract through
#:     utils/fsatomic.py (checkpoint snapshot write, journal_gen bump
#:     after a truncation) — at runtime the armed detector sees these
#:     as their inner os.fsync (already allowed); this entry is the
#:     static pass's name for the same sites.
#:   - ("store", "time.sleep"): none expected; not allowlisted.
#:   - ("partition.summaries.refresh", "socket.connect"/"socket.sendall"):
#:     the UserSummaryExchange peer fetch (shard control socket,
#:     sched/shard.py PeerSummaryFeed; federation cell HTTP,
#:     federation/summary.py) runs INSIDE the serialized sweep by
#:     design — the refresh lock is what guarantees a stalled sweep can
#:     never install an older peer table over a newer one while
#:     stamping it fresh (state/partition.py).  The fetch is bounded by
#:     the carrier's own request timeout, and no other lock family
#:     ranks under this one.
ALLOWED_BLOCKING: Set[Tuple[str, str]] = {
    ("store", "os.fsync"),
    ("store", "fsatomic.fsync"),
    ("partition.summaries.refresh", "socket.connect"),
    ("partition.summaries.refresh", "socket.sendall"),
}

_MAX_VIOLATIONS = 256
_MAX_BLOCKING_EVENTS = 256


def family(name: str) -> str:
    """A lock's rank family: the declared base name with any bracketed
    per-instance suffix stripped (``store[p2]`` → ``store``).  Families
    share one rank; siblings within a family may not nest (module doc)."""
    return name.split("[", 1)[0]


class LockOrderError(RuntimeError):
    """Raised in strict mode when an acquisition would create a cycle in
    the acquisition graph or invert the declared rank order."""


class _Held(threading.local):
    def __init__(self):
        self.stack: List["NamedLock"] = []


class LockMonitor:
    """Acquisition-graph recorder shared by every named lock.

    The module singleton :data:`monitor` is what production code uses;
    tests that deliberately construct violations build their own
    instance so the tier-1 teardown assert on the global one stays
    meaningful."""

    def __init__(self, strict: bool = False):
        self._mu = threading.Lock()
        self.strict = strict
        self._held = _Held()
        # (src name, dst name) -> acquisition count
        self.edges: Dict[Tuple[str, str], int] = {}
        self.violations: List[Dict[str, Any]] = []
        self.blocking_events: List[Dict[str, Any]] = []
        self.allowed_blocking: Set[Tuple[str, str]] = set(ALLOWED_BLOCKING)
        self._armed = False
        self._originals: Dict[str, Any] = {}

    # ------------------------------------------------------------ held stack
    def held(self) -> List["NamedLock"]:
        """Named locks this thread currently holds, outermost first."""
        return list(self._held.stack)

    def _note_acquiring(self, lock: "NamedLock") -> bool:
        """Pre-acquire hook: record the edge BEFORE blocking so an actual
        deadlock attempt still lands in the graph.  Returns True when the
        acquisition is re-entrant (same lock object already held by this
        thread — no edge, RLock semantics)."""
        stack = self._held.stack
        if not stack:
            return False
        for h in stack:
            if h is lock:
                return True
        src = stack[-1]
        if src.name != lock.name:
            self._add_edge(src, lock)
        return False

    def _note_acquired(self, lock: "NamedLock") -> None:
        self._held.stack.append(lock)

    def _note_released(self, lock: "NamedLock") -> None:
        stack = self._held.stack
        # LIFO in `with`-discipline code; scan from the end for safety
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    # ------------------------------------------------------------ the graph
    def _add_edge(self, src: "NamedLock", dst: "NamedLock") -> None:
        key = (src.name, dst.name)
        # steady-state fast path, UNLOCKED: bumping an existing key
        # neither resizes the dict (snapshot's locked iteration stays
        # safe) nor needs exactness (counts are advisory), and this
        # runs on every nested acquisition of the hot paths — the
        # monitor mutex is reserved for the once-per-pair novel case
        n = self.edges.get(key)
        if n is not None:
            self.edges[key] = n + 1
            return
        with self._mu:
            if key in self.edges:
                self.edges[key] += 1
                return
            self.edges[key] = 1
        # novel edge: the expensive checks run at most once per pair
        cycle = self._find_cycle(dst.name, src.name)
        if cycle is not None:
            # _find_cycle already returns the closed loop
            # (src -> dst -> ... -> src)
            self._violation("cycle", src, dst,
                            f"acquisition cycle {' -> '.join(cycle)}")
        if (src.order is not None and dst.order is not None
                and dst.order < src.order):
            self._violation(
                "order", src, dst,
                f"'{dst.name}' (rank {dst.order}) acquired while holding "
                f"'{src.name}' (rank {src.order}) — violates the declared "
                "lock-order contract (utils/locks.py)")
        elif (src.order is not None and dst.order is not None
                and dst.order == src.order
                and family(src.name) == family(dst.name)):
            # SIBLING locks of one rank family (two partitions' store
            # locks) are unorderable by construction: same rank, and the
            # opposite nesting is equally "legal" — which is exactly the
            # ABBA deadlock shape.  The partitioned-facade contract is
            # strictly sequential fan-out (release p_i before acquiring
            # p_{i+1}); any sibling nesting is a violation.
            self._violation(
                "sibling", src, dst,
                f"'{dst.name}' acquired while holding sibling "
                f"'{src.name}' (rank family "
                f"'{family(src.name)}', rank {src.order}) — sibling "
                "locks of a rank family may never nest "
                "(utils/locks.py partitioned-store contract)")

    def _find_cycle(self, start: str,
                    target: str) -> Optional[List[str]]:
        """DFS: path start -> ... -> target through recorded edges, i.e.
        the back-path that makes the new target->start edge a cycle."""
        with self._mu:
            adj: Dict[str, List[str]] = {}
            for (a, b) in self.edges:
                adj.setdefault(a, []).append(b)
        path = [start]
        seen = {start}

        def dfs(node: str) -> Optional[List[str]]:
            if node == target:
                return list(path)
            for nxt in adj.get(node, ()):
                if nxt in seen:
                    continue
                seen.add(nxt)
                path.append(nxt)
                got = dfs(nxt)
                if got is not None:
                    return got
                path.pop()
            return None

        if start == target:
            return [start]
        got = dfs(start)
        if got is not None:
            # present as src -> dst -> ... -> src
            return [target] + got
        return None

    def _violation(self, kind: str, src: "NamedLock", dst: "NamedLock",
                   message: str) -> None:
        doc = {"kind": kind, "from": src.name, "to": dst.name,
               "message": message,
               "thread": threading.current_thread().name,
               "stack": "".join(traceback.format_stack(limit=8)[:-2])}
        with self._mu:
            if len(self.violations) < _MAX_VIOLATIONS:
                self.violations.append(doc)
        from .metrics import registry
        registry.counter_inc("cook_lock_violations", labels={"kind": kind})
        if self.strict:
            raise LockOrderError(message)

    # ------------------------------------------------- blocking-call sensor
    def note_blocking(self, op: str, detail: str = "") -> None:
        """A blocking operation is about to run on this thread: record a
        violation when any held named lock does not allowlist it.  Called
        by the armed patches below; explicit call sites may also use it
        for blocking operations the generic patches cannot see (native
        waits)."""
        stack = self._held.stack
        if not stack:
            return
        bad = [h.name for h in stack
               if (h.name, op) not in self.allowed_blocking
               and (family(h.name), op) not in self.allowed_blocking]
        if not bad:
            return
        key = (op, tuple(bad))
        doc = {"kind": "blocking", "op": op, "held": bad,
               "detail": detail,
               "thread": threading.current_thread().name,
               "stack": "".join(traceback.format_stack(limit=10)[:-3])}
        with self._mu:
            # dedup per (op, held-set): a hot site must not flood the ring
            for ev in self.blocking_events:
                if (ev["op"], tuple(ev["held"])) == key:
                    ev["count"] = ev.get("count", 1) + 1
                    return
            if len(self.blocking_events) < _MAX_BLOCKING_EVENTS:
                doc["count"] = 1
                self.blocking_events.append(doc)
        from .metrics import registry
        registry.counter_inc("cook_lock_violations",
                             labels={"kind": "blocking"})

    def arm_blocking_detector(self) -> None:
        """Patch the generic blocking entry points (os.fsync, time.sleep,
        socket connect/sendall) to consult :meth:`note_blocking`.  Armed
        by the tier-1 conftest; idempotent."""
        if self._armed:
            return
        self._armed = True
        mon = self
        self._originals = {
            "os.fsync": os.fsync,
            "time.sleep": time.sleep,
            "socket.connect": socket.socket.connect,
            "socket.sendall": socket.socket.sendall,
        }

        def fsync(fd, _orig=os.fsync):
            mon.note_blocking("os.fsync")
            return _orig(fd)

        def sleep(secs, _orig=time.sleep):
            # sleep(0) is a bare yield, not a blocking wait
            if secs:
                mon.note_blocking("time.sleep", detail=str(secs))
            return _orig(secs)

        def connect(sock, addr, _orig=socket.socket.connect):
            mon.note_blocking("socket.connect", detail=str(addr))
            return _orig(sock, addr)

        def sendall(sock, *args, _orig=socket.socket.sendall):
            mon.note_blocking("socket.sendall")
            return _orig(sock, *args)

        os.fsync = fsync
        time.sleep = sleep
        socket.socket.connect = connect
        socket.socket.sendall = sendall

    def disarm_blocking_detector(self) -> None:
        if not self._armed:
            return
        os.fsync = self._originals["os.fsync"]
        time.sleep = self._originals["time.sleep"]
        socket.socket.connect = self._originals["socket.connect"]
        socket.socket.sendall = self._originals["socket.sendall"]
        self._originals = {}
        self._armed = False

    # --------------------------------------------------------------- report
    def observed_edges(self) -> List[str]:
        """The FAMILY-normalized observed edge set
        (``["store.notify->store", ...]``): each entry says a lock of
        the first family was held while one of the second was acquired
        at least once this process.  This is the dynamic half of the
        static-vs-observed lock-coverage diff (``cs lint
        --lock-coverage``, ``/debug/health`` → ``locks``; the static
        half comes from cook_tpu/analysis) — family-normalized because
        the static analysis cannot tell ``store[p0]`` from
        ``store[p1]`` in an f-string, and the diff must compare like
        with like."""
        with self._mu:
            fams = {(family(a), family(b)) for (a, b) in self.edges}
        return sorted(f"{a}->{b}" for a, b in fams)

    def snapshot(self) -> Dict[str, Any]:
        """The ``/debug/health`` ``"locks"`` block: observed edge set +
        violation counters (full violation docs stay on the monitor; the
        health surface carries counts and the first few messages)."""
        with self._mu:
            edges = [{"from": a, "to": b, "count": n}
                     for (a, b), n in sorted(self.edges.items())]
            fams = {(family(a), family(b)) for (a, b) in self.edges}
            violations = list(self.violations)
            blocking = list(self.blocking_events)
        return {
            "armed": self._armed,
            "edges": edges,
            "observed_edges": sorted(f"{a}->{b}" for a, b in fams),
            "violations": len(violations),
            "blocking_events": sum(e.get("count", 1) for e in blocking),
            "problems": [v["message"] for v in violations[:5]]
            + [f"blocking {e['op']} while holding {e['held']}"
               for e in blocking[:5]],
        }

    def check(self) -> List[str]:
        """Human-readable list of every recorded violation (cycle/order
        inversions AND unallowlisted blocking events) — the tier-1
        teardown asserts this is empty."""
        with self._mu:
            out = [f"[{v['kind']}] {v['message']}\n{v['stack']}"
                   for v in self.violations]
            out += [f"[blocking] {e['op']} ({e.get('detail', '')}) while "
                    f"holding {e['held']} x{e.get('count', 1)}\n"
                    f"{e['stack']}" for e in self.blocking_events]
        return out

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.violations.clear()
            self.blocking_events.clear()


class NamedLock:
    """``threading.Lock`` with a name and an optional declared rank,
    reporting acquisitions to a :class:`LockMonitor` (see module doc for
    the rank table).  ``order=None`` opts out of the declared-order check
    (cycle detection still applies)."""

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str, order: Optional[int] = None,
                 monitor: Optional[LockMonitor] = None):
        self.name = name
        self.order = order
        self._monitor = monitor if monitor is not None else _monitor()
        self._lock = self._factory()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        reentrant = self._monitor._note_acquiring(self)
        ok = self._lock.acquire(blocking, timeout)
        if ok and not reentrant:
            self._monitor._note_acquired(self)
        return ok

    def release(self) -> None:
        self._lock.release()
        self._monitor._note_released(self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} {self.name!r}>"


class NamedRLock(NamedLock):
    """Re-entrant variant: nested acquisitions by the owning thread add
    no edges (the monitor tracks one held entry per outermost hold).
    Release tracking relies on ``with``-discipline (LIFO), which is how
    every adopter uses it."""

    _factory = staticmethod(threading.RLock)

    def release(self) -> None:
        self._lock.release()
        try:
            still_owned = self._lock._is_owned()
        except AttributeError:  # pragma: no cover - exotic RLock impl
            still_owned = False
        if not still_owned:
            # this release dropped the OUTERMOST hold: the held entry
            # (pushed once per outermost acquire) retires with it
            self._monitor._note_released(self)

    def locked(self) -> bool:  # RLock has no .locked() pre-3.12
        try:
            if self._lock._is_owned():
                # a bare try-acquire would succeed re-entrantly and
                # report "unlocked" to the very thread holding it
                return True
        except AttributeError:  # pragma: no cover - exotic RLock impl
            pass
        if self._lock.acquire(blocking=False):
            self._lock.release()
            return False
        return True


def _monitor() -> LockMonitor:
    return monitor


#: the process-wide monitor every production named lock reports to
monitor = LockMonitor()


# convenience factories carrying the declared ranks from the module doc
_DECLARED_ORDER = {
    "store.notify": 10,
    "read_replica": 15,
    "elastic": 18,
    "store": 20,
    "index": 30,
    "audit": 40,
    "repl.server": 50,
    "repl.follower": 55,
}


def named_lock(name: str, monitor: Optional[LockMonitor] = None
               ) -> NamedLock:
    """A :class:`NamedLock` with the rank declared in the module-doc
    contract table (None = unordered, cycle detection only).  A
    bracketed suffix (``store[p1]``) inherits its rank family's rank —
    and the sibling no-nesting rule that comes with it."""
    return NamedLock(name, order=_DECLARED_ORDER.get(family(name)),
                     monitor=monitor)


def named_rlock(name: str, monitor: Optional[LockMonitor] = None
                ) -> NamedRLock:
    return NamedRLock(name, order=_DECLARED_ORDER.get(family(name)),
                      monitor=monitor)
