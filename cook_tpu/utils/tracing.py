"""Tracing spans around scheduler stages and kernel dispatches.

Plays the role of the reference's OpenTracing integration: every stage of
the match path is wrapped in a span carrying pool/cluster tags (reference:
scheduler.clj:2438 `scheduler.pool-handler`, scheduler.clj:662-671
`match-offer-to-scheduler.fenzo-schedule-once`,
kubernetes/compute_cluster.clj:425 `k8s.launch-tasks`). Durations are
tri-recorded the way the reference records them (prometheus_metrics.clj
with-duration + structured match-cycle log documents): each finished span

  1. observes `cook_span_duration_seconds{span=..., <tags>}` on the global
     metrics registry,
  2. emits a structured JSON log line on the `cook.trace` logger,
  3. lands in an in-memory ring buffer served by the /debug REST endpoint.

Spans nest via a thread-local stack so kernel dispatch spans inherit a
trace id from the enclosing cycle span — enough to reconstruct per-cycle
flamegraphs offline without an external collector (zero-egress friendly).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from cook_tpu.utils.metrics import registry

_log = logging.getLogger("cook.trace")

_MAX_FINISHED = 4096


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "tags",
                 "start_s", "duration_s", "error")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 tags: Dict[str, Any]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.tags = tags
        self.start_s = time.time()
        self.duration_s: Optional[float] = None
        self.error: Optional[str] = None

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def to_doc(self) -> Dict[str, Any]:
        return {"span": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start": self.start_s, "duration_ms":
                round((self.duration_s or 0.0) * 1000.0, 3),
                "error": self.error, **self.tags}


class Tracer:
    def __init__(self) -> None:
        self._tls = threading.local()
        self._lock = threading.Lock()
        self.finished: List[Dict[str, Any]] = []
        self.enabled = True

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    @contextmanager
    def span(self, name: str, **tags: Any):
        """Open a span; tags with None values are dropped (matches the
        reference's optional pool/cluster tags)."""
        if not self.enabled:
            yield _NOOP_SPAN
            return
        tags = {k: v for k, v in tags.items() if v is not None}
        parent = self.current()
        trace_id = parent.trace_id if parent else uuid.uuid4().hex[:16]
        parent_id = parent.span_id if parent else None
        sp = Span(name, trace_id, parent_id, tags)
        self._stack().append(sp)
        t0 = time.perf_counter()
        try:
            yield sp
        except BaseException as exc:
            sp.error = f"{type(exc).__name__}: {exc}"
            raise
        finally:
            sp.duration_s = time.perf_counter() - t0
            self._stack().pop()
            self._record(sp)

    def _record(self, sp: Span) -> None:
        metric_labels = {"span": sp.name}
        for key in ("pool", "cluster"):
            if key in sp.tags:
                metric_labels[key] = str(sp.tags[key])
        registry.observe("cook_span_duration_seconds", sp.duration_s or 0.0,
                         metric_labels)
        doc = sp.to_doc()
        _log.debug(sp.name, extra={"doc": doc})
        with self._lock:
            self.finished.append(doc)
            if len(self.finished) > _MAX_FINISHED:
                del self.finished[:_MAX_FINISHED // 2]

    def recent(self, limit: int = 100,
               name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            if name is None:
                return self.finished[-limit:]
            docs = [d for d in self.finished if d["span"] == name]
        return docs[-limit:]

    def traces(self, trace_id: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [d for d in self.finished if d["trace_id"] == trace_id]

    def reset(self) -> None:
        with self._lock:
            self.finished.clear()


class _NoopSpan:
    def set_tag(self, key: str, value: Any) -> None:
        pass


_NOOP_SPAN = _NoopSpan()

tracer = Tracer()


def span(name: str, **tags: Any):
    """Module-level shorthand: `with tracing.span("match.cycle", pool=p):`"""
    return tracer.span(name, **tags)
