"""Tracing spans around scheduler stages and kernel dispatches.

Plays the role of the reference's OpenTracing integration: every stage of
the match path is wrapped in a span carrying pool/cluster tags (reference:
scheduler.clj:2438 `scheduler.pool-handler`, scheduler.clj:662-671
`match-offer-to-scheduler.fenzo-schedule-once`,
kubernetes/compute_cluster.clj:425 `k8s.launch-tasks`). Durations are
tri-recorded the way the reference records them (prometheus_metrics.clj
with-duration + structured match-cycle log documents): each finished span

  1. observes `cook_span_duration_seconds{span=..., <tags>}` on the global
     metrics registry,
  2. emits a structured JSON log line on the `cook.trace` logger,
  3. lands in an in-memory ring buffer served by the /debug REST endpoint.

Spans nest via a ``contextvars`` stack so kernel dispatch spans inherit a
trace id from the enclosing cycle span — enough to reconstruct per-cycle
flamegraphs offline without an external collector (zero-egress friendly).
Context variables (unlike the previous thread-local stack) survive the
async/executor boundaries the fused dispatch path uses: a launch thread
started under ``contextvars.copy_context().run`` keeps its kernel spans
under the owning cycle's trace_id, while plain ``threading.Thread``
workers still start with an empty stack (fresh root traces).

The whole span ring of one trace can be exported as Chrome/Perfetto
trace-event JSON (:meth:`Tracer.export_chrome_trace`), served by
``GET /debug/trace?trace_id=`` — load it in ``chrome://tracing`` or
https://ui.perfetto.dev to see the cycle flamegraph.
"""

from __future__ import annotations

import contextvars
import logging
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from cook_tpu.utils.metrics import registry

_log = logging.getLogger("cook.trace")

_MAX_FINISHED = 4096

# The span stack is an immutable tuple in a context variable: each span
# push/pop is a set/reset, so a context copied into an executor sees a
# consistent snapshot and mutations never leak between contexts.
_stack_var: "contextvars.ContextVar[tuple]" = contextvars.ContextVar(
    "cook_span_stack", default=())

# Per-request phase accumulator (rest/instrument.py): while a collector
# dict is installed, every finished span adds its duration under its
# name — the request handler reads back a {span-name: seconds} breakdown
# ("how much of this POST was replication ack wait") without walking the
# span ring.  None (the default) costs one contextvar read per span.
_phases_var: "contextvars.ContextVar[Optional[dict]]" = \
    contextvars.ContextVar("cook_req_phases", default=None)


@contextmanager
def collect_phases():
    """Install a fresh per-request phase dict; yields it.  Nested
    collectors shadow (each request owns exactly its own spans)."""
    phases: Dict[str, float] = {}
    token = _phases_var.set(phases)
    try:
        yield phases
    finally:
        _phases_var.reset(token)


# ------------------------------------------------------- process identity
# Every span is stamped with the identity of the PROCESS (fleet member)
# that recorded it — the grouping key the fleet trace collector turns
# into per-process Perfetto tracks (docs/OBSERVABILITY.md "Debugging the
# fleet").  The default is a process-global set once by the daemon at
# boot (node id); a contextvar override scopes a DIFFERENT identity to
# one request, so an in-process multi-server topology (tests, the
# simulator) still yields distinct per-member tracks out of one shared
# ring.
_proc_default = "cook"
_identity_var: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("cook_proc_identity", default=None)


def set_process_identity(name: str) -> None:
    """Install the process-global span identity (daemon boot: node id)."""
    global _proc_default
    _proc_default = str(name)


def process_identity() -> str:
    """The identity spans record right now (contextvar override wins)."""
    return _identity_var.get() or _proc_default


@contextmanager
def scoped_identity(name: Optional[str]):
    """Spans opened inside record under ``name`` instead of the process
    default — the REST handler scopes each request to its serving node's
    identity.  ``None`` is a no-op (keeps the ambient identity)."""
    if name is None:
        yield
        return
    token = _identity_var.set(str(name))
    try:
        yield
    finally:
        _identity_var.reset(token)


# ------------------------------------------------------ W3C trace context
# Propagated over the `traceparent` HTTP header (W3C Trace Context:
# 00-<32 hex trace-id>-<16 hex parent-id>-<2 hex flags>).  Internal span
# ids are 16-hex; they are zero-padded on the wire and the pad is
# stripped on parse, so an in-process client span and the server's
# http.request root share ONE trace id.
_PAD = "0" * 16


def make_traceparent(trace_id: Optional[str] = None,
                     span_id: Optional[str] = None) -> str:
    """A traceparent header value; mints a fresh trace when no ids are
    given (the client-side entry point)."""
    tid = (trace_id or uuid.uuid4().hex).lower()
    if len(tid) < 32:
        tid = tid.rjust(32, "0")
    sid = (span_id or uuid.uuid4().hex[:16]).lower()
    if len(sid) < 16:
        sid = sid.rjust(16, "0")
    return f"00-{tid[:32]}-{sid[:16]}-01"


def parse_traceparent(header: Optional[str]
                      ) -> Optional[tuple]:
    """(trace_id, parent_span_id) from a traceparent header, or None when
    absent/malformed (a garbage header must never 500 a request)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    _ver, tid, sid = parts[0], parts[1].lower(), parts[2].lower()
    try:
        int(tid, 16)
        int(sid, 16)
    except ValueError:
        return None
    if len(tid) != 32 or len(sid) != 16 or tid == "0" * 32:
        return None
    if tid.startswith(_PAD):
        tid = tid[16:]  # our own padded 16-hex form round-trips
    return tid, sid


class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "tags",
                 "start_s", "duration_s", "error", "proc")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 tags: Dict[str, Any]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.tags = tags
        self.start_s = time.time()
        self.duration_s: Optional[float] = None
        self.error: Optional[str] = None
        self.proc = process_identity()

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def to_doc(self) -> Dict[str, Any]:
        return {"span": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "proc": self.proc,
                "start": self.start_s, "duration_ms":
                round((self.duration_s or 0.0) * 1000.0, 3),
                "error": self.error, **self.tags}


class Tracer:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.finished: List[Dict[str, Any]] = []
        self.enabled = True
        # hot-path I/O spans (journal append / replication ack wait,
        # state/store.py): gated separately so the rest_plane bench can
        # A/B exactly the serving-plane instrumentation without touching
        # the cycle spans
        self.io_spans = True

    def current(self) -> Optional[Span]:
        st = _stack_var.get()
        return st[-1] if st else None

    @contextmanager
    def span(self, name: str, remote_parent: Optional[tuple] = None,
             **tags: Any):
        """Open a span; tags with None values are dropped (matches the
        reference's optional pool/cluster tags).  ``remote_parent`` is a
        propagated (trace_id, span_id) — e.g. a parsed ``traceparent``
        header — adopted only when no LOCAL parent is active (the
        in-process stack always wins)."""
        if not self.enabled:
            yield _NOOP_SPAN
            return
        tags = {k: v for k, v in tags.items() if v is not None}
        parent = self.current()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif remote_parent is not None:
            trace_id, parent_id = remote_parent
        else:
            trace_id, parent_id = uuid.uuid4().hex[:16], None
        sp = Span(name, trace_id, parent_id, tags)
        token = _stack_var.set(_stack_var.get() + (sp,))
        t0 = time.perf_counter()
        try:
            yield sp
        except BaseException as exc:
            sp.error = f"{type(exc).__name__}: {exc}"
            raise
        finally:
            sp.duration_s = time.perf_counter() - t0
            _stack_var.reset(token)
            self._record(sp)

    def _record(self, sp: Span) -> None:
        phases = _phases_var.get()
        if phases is not None:
            phases[sp.name] = phases.get(sp.name, 0.0) \
                + (sp.duration_s or 0.0)
        metric_labels = {"span": sp.name}
        for key in ("pool", "cluster"):
            if key in sp.tags:
                metric_labels[key] = str(sp.tags[key])
        registry.observe("cook_span_duration_seconds", sp.duration_s or 0.0,
                         metric_labels)
        doc = sp.to_doc()
        if _log.isEnabledFor(logging.DEBUG):
            _log.debug(sp.name, extra={"doc": doc})
        with self._lock:
            self.finished.append(doc)
            if len(self.finished) > _MAX_FINISHED:
                del self.finished[:_MAX_FINISHED // 2]

    def record_finished(self, name: str, duration_s: float,
                        **tags: Any) -> None:
        """Record an already-measured span under the CURRENT context —
        for costs incurred on a shared worker thread and attributed back
        to each awaiting request (the group committer's batched journal
        fsync / replication ack wait, state/store.py): the waiter calls
        this from its own request context once its batch resolves, so
        the shared round lands in the request's span tree, phase
        breakdown, and RED phase metrics like an inline span would."""
        if not self.enabled:
            return
        tags = {k: v for k, v in tags.items() if v is not None}
        parent = self.current()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = uuid.uuid4().hex[:16], None
        sp = Span(name, trace_id, parent_id, tags)
        sp.start_s = time.time() - max(duration_s, 0.0)
        sp.duration_s = max(duration_s, 0.0)
        self._record(sp)

    def recent(self, limit: int = 100,
               name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            if name is None:
                return self.finished[-limit:]
            # copy under the lock, filter OUTSIDE it: the name scan is
            # O(ring) python work that would otherwise stall every
            # concurrent span completion for its duration
            docs = list(self.finished)
        out: List[Dict[str, Any]] = []
        # newest-first scan honoring the limit: the common "recent N of a
        # hot span name" query stops after N hits instead of walking the
        # whole ring
        for d in reversed(docs):
            if d["span"] == name:
                out.append(d)
                if len(out) >= limit:
                    break
        out.reverse()
        return out

    def traces(self, trace_id: str) -> List[Dict[str, Any]]:
        with self._lock:
            docs = list(self.finished)
        return [d for d in docs if d["trace_id"] == trace_id]

    def trace_events(self, trace_id: str, tid: int = 1
                     ) -> List[Dict[str, Any]]:
        """One trace's spans as Chrome trace-event 'X' events on thread
        ``tid`` — the building block :meth:`export_chrome_trace` and the
        multi-track stitched export (``/debug/trace?job=``) share."""
        events: List[Dict[str, Any]] = []
        for d in self.traces(trace_id):
            args = {k: v for k, v in d.items()
                    if k not in ("span", "trace_id", "start", "duration_ms",
                                 "proc")
                    and v is not None}
            events.append({
                "name": d["span"],
                "cat": "cook",
                "ph": "X",
                "ts": round(d["start"] * 1e6, 3),
                "dur": max(round((d.get("duration_ms") or 0.0) * 1000.0, 3),
                           1.0),
                "pid": 1,
                "tid": tid,
                "args": args,
            })
        events.sort(key=lambda e: e["ts"])
        return events

    def export_chrome_trace(self, trace_id: str) -> Dict[str, Any]:
        """Export one trace's spans as Chrome trace-event JSON (the
        "JSON Array Format" with complete 'X' events), loadable in
        chrome://tracing and https://ui.perfetto.dev.

        ``ts``/``dur`` are microseconds; ``ts`` comes from the span's
        wall-clock start so events across processes line up.  Durations
        are clamped to >= 1 us: a zero-width event is dropped by some
        viewers, and every real span costs more than that anyway."""
        return {"traceEvents": self.trace_events(trace_id),
                "displayTimeUnit": "ms",
                "otherData": {"trace_id": trace_id}}

    def reset(self) -> None:
        with self._lock:
            self.finished.clear()


def track_meta(name: str, tid: int) -> Dict[str, Any]:
    """A Chrome-trace thread_name metadata event: names one stitched
    track (job lanes, the request track) in the Perfetto timeline."""
    return {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": name}}


def job_track_events(uuid: str, timeline: List[Dict[str, Any]],
                     tid: int = 2) -> List[Dict[str, Any]]:
    """One job's audit timeline (utils/audit.py event docs) as a named
    Chrome-trace TRACK of instant events, stitchable into any
    export_chrome_trace payload: the cycle flamegraph and the job's
    decision history line up on one Perfetto timeline
    (``/debug/trace?trace_id=...&job=<uuid>``).

    Audit timestamps are store-clock epoch ms (wall clock in
    production); span timestamps are wall-clock too, so the tracks align
    — under the simulator's virtual clock the job track keeps its own
    relative ordering but sits at virtual time."""
    if not timeline:
        return []
    # spans live on tid 1; each job track is its own lane (callers
    # stitching several jobs pass distinct tids)
    events: List[Dict[str, Any]] = [track_meta(f"job {uuid}", tid)]
    for ev in timeline:
        args = dict(ev.get("data") or {})
        if ev.get("count", 1) > 1:
            args["count"] = ev["count"]
        name = ev["kind"]
        if name == "skip" and args.get("reason"):
            name = f"skip:{args['reason']}"
        events.append({
            "name": name, "cat": "cook.audit", "ph": "i",
            "ts": round(ev["ts"] * 1000.0, 3), "pid": 1, "tid": tid,
            "s": "t", "args": args})
    return events


def _proc_sort_key(proc: str) -> tuple:
    """Stable track ordering for the stitched fleet export: the client
    track first (it owns the root span), the leader next, everyone else
    alphabetical — so every export of the same topology reads the same
    way top-to-bottom in Perfetto."""
    if proc.startswith("client"):
        rank = 0
    elif "leader" in proc or proc.startswith("cook"):
        rank = 1
    else:
        rank = 2
    return (rank, proc)


def fleet_trace_events(span_docs: List[Dict[str, Any]],
                       base_pid: int = 10) -> List[Dict[str, Any]]:
    """Merged span docs (each carrying its recording process in ``proc``)
    as Chrome trace events on PER-PROCESS tracks: every distinct proc
    gets its own ``pid`` with ``process_name`` + ``process_sort_index``
    metadata, so the gang-launch path shows leader txn, partition fsync,
    agent exec, and barrier release as separate swimlanes on one
    timeline (the Dapper stitch, docs/OBSERVABILITY.md).

    Spans are deduplicated by ``(proc, span_id)`` — the fleet collector
    fans out to every member and a member may return spans another
    member (or the local ring) already contributed."""
    procs = sorted({str(d.get("proc") or "?") for d in span_docs},
                   key=_proc_sort_key)
    pid_of = {p: base_pid + i for i, p in enumerate(procs)}
    events: List[Dict[str, Any]] = []
    for i, p in enumerate(procs):
        pid = pid_of[p]
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": p}})
        events.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"sort_index": i}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": 1, "args": {"name": "spans"}})
    seen = set()
    for d in span_docs:
        proc = str(d.get("proc") or "?")
        key = (proc, d.get("span_id"))
        if key in seen:
            continue
        seen.add(key)
        args = {k: v for k, v in d.items()
                if k not in ("span", "trace_id", "start", "duration_ms",
                             "proc")
                and v is not None}
        events.append({
            "name": d.get("span", "?"),
            "cat": "cook",
            "ph": "X",
            "ts": round(float(d.get("start") or 0.0) * 1e6, 3),
            "dur": max(round((d.get("duration_ms") or 0.0) * 1000.0, 3),
                       1.0),
            "pid": pid_of[proc],
            "tid": 1,
            "args": args,
        })
    events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0)))
    return events


def export_fleet_trace(span_docs: List[Dict[str, Any]], trace_id: str,
                       members: Optional[List[Dict[str, Any]]] = None
                       ) -> Dict[str, Any]:
    """One stitched fleet-wide Perfetto export for ``trace_id``: the
    per-process tracks of :func:`fleet_trace_events` plus the collection
    provenance (which members contributed / failed) in ``otherData`` so
    a partial stitch is never mistaken for the whole fleet."""
    doc: Dict[str, Any] = {
        "traceEvents": fleet_trace_events(span_docs),
        "displayTimeUnit": "ms",
        "otherData": {"trace_id": trace_id, "fleet": True},
    }
    if members is not None:
        doc["otherData"]["members"] = members
    return doc


class _NoopSpan:
    def set_tag(self, key: str, value: Any) -> None:
        pass


_NOOP_SPAN = _NoopSpan()

tracer = Tracer()


def span(name: str, **tags: Any):
    """Module-level shorthand: `with tracing.span("rank.cycle", pool=p):`"""
    return tracer.span(name, **tags)
