"""Per-job scheduling audit trail: the Dapper-style per-entity lane the
per-cycle aggregates can't give (Sigelman et al., 2010; the scheduler
analogue of Monarch's entity-scoped monitoring, Adams et al., VLDB 2020).

The flight recorder (utils/flight.py) answers "what did cycle N do"; this
module answers **"why isn't MY job running"** — the dominant support
question of a fair-share multitenant scheduler (the reference carries an
unscheduled-jobs explainer and per-job instance history for exactly this
reason).  Every decision path records bounded per-job events:

  submitted -> ranked (queue position, DRU context) -> admission deferrals
  (rate-limit / cap / gang cohort reasons) -> match skip reasons -> gang
  cohort outcomes -> pipeline reconcile drops -> launch intent -> launch
  ack -> instance transitions -> preemption (victim AND beneficiary, with
  the DRU delta that justified it) -> terminal state.

Design constraints, in order:

1. **Bounded.**  Per-job lanes are capped (repeated advisory events —
   "ranked at position 7", "skipped: rate-limited" — COALESCE into one
   event with a count instead of churning the lane), the job map is an
   LRU with a global cap, and lifecycle events survive lane eviction
   preferentially.  A quiet pool records nothing: the resident driver's
   zero-work fast path stays zero-work.
2. **Attribution, not re-derivation.**  Decision paths already
   materialize the data (skip-reason vectors, gang partial maps, victim
   lists, reconcile masks); :func:`note_skips` turns exactly those into
   per-job events AND the flight recorder's aggregate histogram from ONE
   mapping, so the per-job sums reconcile with the aggregates by
   construction (tests/test_audit.py attribution parity).
3. **Survives failover.**  Events marked durable ride the store's redo
   journal as ``{"a": [...]}`` records (state/store.py): lifecycle events
   are journaled atomically with their transaction, advisory events are
   flushed once per cycle (first occurrence per coalesce key — counts
   drift after the first flush is an accepted economy).  Journal bytes
   replicate to standbys like any other record, so a promoted leader
   replays the trail and ``cs why`` keeps answering for pre-failover
   jobs.

Surfaces: ``GET /debug/job/<uuid>/timeline``, ``GET /unscheduled_jobs``
(history), ``cs why <uuid>``, and per-job instant-event tracks stitched
into the Chrome/Perfetto trace export (``/debug/trace?job=``).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from cook_tpu.utils.flight import recorder as _flight
from cook_tpu.utils.locks import named_lock
from cook_tpu.utils.metrics import registry

# event kinds that are one-shot lifecycle facts: never coalesced, last to
# be evicted from a full lane, journaled atomically with their txn
LIFECYCLE_KINDS = frozenset({
    "submitted", "committed", "launched", "launch-ack", "launch-denied",
    "instance", "requeued", "preempted", "preemption-benefit", "terminal",
})

# advisory kinds: high-frequency per-cycle attributions, coalesced by key
# ("ranked" by kind alone — its position just updates; skips by reason)
_COALESCE_BY_KIND = frozenset({"ranked"})

# skip/defer reasons that are FAIRNESS throttles rather than capacity or
# constraint misses — the wait-phase classifier (sched/monitor.py) and
# `cs why` read this split
FAIRNESS_REASONS = frozenset({
    "over-quota", "rate-limited", "cap-reserved", "gang-deferred",
    "offensive", "launch-filtered", "admission-throttled",
})
CONSTRAINT_REASONS = frozenset({"gang-partial"})


class _Ev:
    __slots__ = ("ts", "ts_last", "kind", "data", "count", "flushed")

    def __init__(self, ts: int, kind: str, data: Optional[Dict[str, Any]]):
        self.ts = ts
        self.ts_last = ts
        self.kind = kind
        self.data = data or {}
        self.count = 1
        self.flushed = False

    def to_doc(self) -> Dict[str, Any]:
        doc = {"ts": self.ts, "kind": self.kind, "count": self.count}
        if self.ts_last != self.ts:
            doc["ts_last"] = self.ts_last
        if self.data:
            doc["data"] = dict(self.data)
        return doc

    def to_wire(self, uuid: str) -> Dict[str, Any]:
        w = {"u": uuid, "k": self.kind, "t": self.ts}
        if self.count > 1:
            w["n"] = self.count
        if self.data:
            w["d"] = dict(self.data)
        return w


class _Lane:
    """One job's bounded event lane + its coalesce index."""

    __slots__ = ("events", "by_key", "last_reason")

    def __init__(self):
        self.events: List[_Ev] = []
        self.by_key: Dict[Any, _Ev] = {}
        self.last_reason: Optional[str] = None


class AuditTrail:
    """Bounded per-job decision-event lanes (see module doc)."""

    def __init__(self, clock: Optional[Callable[[], int]] = None,
                 max_jobs: int = 100_000, per_job: int = 64):
        # "audit" ranks ABOVE "store" in the global lock-order contract
        # (utils/locks.py): store->audit is the single nesting direction
        # everywhere (flush_audit drains under the store lock)
        self._lock = named_lock("audit")
        self._lanes: "OrderedDict[str, _Lane]" = OrderedDict()
        self._clock = clock or (lambda: int(time.time() * 1000))
        self.enabled = True
        #: journal durable events (the store consults this before
        #: embedding/appending audit records)
        self.journal = True
        #: brownout stage >= 1 (sched/admission.py): fold the advisory
        #: flush — pending advisory events stop being serialized to the
        #: journal (the in-memory lanes keep everything, so `cs why`
        #: still answers; only pre-failover durability of ADVISORY
        #: detail is shed).  Lifecycle events ride their own txn
        #: records and are untouched.
        self.shed_advisory = False
        #: advisory events folded (not journaled) while shedding —
        #: surfaced via stats() so the brownout's cost is visible
        self.shed_count = 0
        self.max_jobs = max_jobs
        self.per_job = per_job
        # durable events awaiting a journal flush (Store.flush_audit)
        self._pending: List[Tuple[str, _Ev]] = []
        # cook_audit_events_total accumulator: the hot paths record one
        # batch per TRANSACTION (thousands per cycle), so the labeled
        # registry increment is deferred to publish_metrics() — once per
        # cycle — instead of paying label-key hashing per batch
        self._ev_counts: Dict[str, int] = {}
        # fairness-plane cache: pool -> {user -> DRU} at the last
        # monitor sweep, attached to ranked events and `cs why` output
        # ("DRU at rank time", refreshed at the sweep cadence).  Each
        # sweep REPLACES a pool's table (set_user_dru), so departed
        # users age out instead of leaking for the leader's lifetime.
        self._user_dru: Dict[str, Dict[str, float]] = {}

    def configure(self, conf) -> None:
        """Apply config.AuditConfig (scheduler boot)."""
        self.enabled = bool(conf.enabled)
        self.journal = bool(conf.journal)
        self.max_jobs = int(conf.max_jobs)
        self.per_job = int(conf.per_job_events)

    # -------------------------------------------------------------- record
    def _record_one(self, uuid: str, kind: str,
                    data: Optional[Dict[str, Any]], ts: int,
                    count: int, durable: bool, loaded: bool) -> None:
        """One event append/coalesce; caller holds ``self._lock``.  The
        hot paths record THOUSANDS of events per cycle (every launch is
        3+ lifecycle events), so the lock round-trip, flight note, and
        metric increment are batched by the public entry points — this
        core is pure dict work.  Job eviction is INSERTION-ordered (the
        oldest-created lane goes first), not strict LRU: the earliest
        submissions are the likeliest terminal, and skipping the
        per-event move_to_end keeps the hot path flat."""
        lane = self._lanes.get(uuid)
        if lane is None:
            lane = self._lanes[uuid] = _Lane()
            while len(self._lanes) > self.max_jobs:
                self._lanes.popitem(last=False)
        key = None
        if kind in _COALESCE_BY_KIND:
            key = kind
        elif kind == "skip":
            key = (kind, (data or {}).get("reason"))
            lane.last_reason = (data or {}).get("reason")
        if key is not None:
            ev = lane.by_key.get(key)
            if ev is not None:
                # eviction scrubs by_key (below), so a hit is always
                # live — the coalesce path stays a true O(1) lookup
                ev.count += count
                ev.ts_last = ts
                if kind in _COALESCE_BY_KIND and data:
                    ev.data.update(data)
                return
        ev = _Ev(ts, kind, data)
        ev.count = count
        lane.events.append(ev)
        if key is not None:
            lane.by_key[key] = ev
        if len(lane.events) > self.per_job:
            # evict the oldest ADVISORY event first: "submitted" /
            # "launched" must outlive a thousand "ranked" updates
            for i, old in enumerate(lane.events):
                if old.kind not in LIFECYCLE_KINDS:
                    lane.events.pop(i)
                    break
            else:
                old = lane.events.pop(0)
            if lane.by_key:
                # scrub the evicted event's coalesce entry (tiny dict:
                # one entry per distinct reason) so by_key never holds
                # a dead reference the coalesce path could resurrect
                lane.by_key = {k: v for k, v in lane.by_key.items()
                               if v is not old}
        if durable and not loaded:
            self._pending.append((uuid, ev))

    def record(self, uuid: str, kind: str,
               data: Optional[Dict[str, Any]] = None, *,
               durable: bool = False, ts: Optional[int] = None,
               count: int = 1, _loaded: bool = False) -> None:
        if not self.enabled or not uuid:
            return
        if ts is None:
            ts = int(self._clock())
        with self._lock:
            self._record_one(uuid, kind, data, ts, count, durable,
                             _loaded)
            if not _loaded:
                # cook_audit_events_total covers EVERY recording path
                # (preempted/preemption-benefit arrive through here)
                self._ev_counts[kind] = \
                    self._ev_counts.get(kind, 0) + count
        if not _loaded:
            _flight.note_audit(count)

    def skips(self, mapping: Dict[str, Iterable], pool: Optional[str] = None
              ) -> None:
        """Per-job skip attribution: ``mapping`` is reason -> iterable of
        job uuids or (uuid, extra-data) tuples — the same structure whose
        lengths feed the flight recorder's aggregate histogram
        (:func:`note_skips` passes one mapping to both)."""
        if not self.enabled:
            return
        ts = int(self._clock())
        total = 0
        with self._lock:
            for reason, items in mapping.items():
                for item in items:
                    if isinstance(item, tuple):
                        uuid, extra = item
                        data = {"reason": reason, **extra}
                    else:
                        uuid, data = item, {"reason": reason}
                    if pool is not None:
                        data.setdefault("pool", pool)
                    self._record_one(str(uuid), "skip", data, ts, 1,
                                     True, False)
                    total += 1
        if total:
            _flight.note_audit(total)
            with self._lock:
                self._ev_counts["skip"] = \
                    self._ev_counts.get("skip", 0) + total

    def ranked(self, uuids: Iterable[str], positions: Iterable[int],
               pool: str, users: Optional[Iterable[str]] = None) -> None:
        """Per-cycle rank attribution for the ADMITTED candidate slots
        (bounded by the considerable cap, never [T]-sized): queue
        position now, plus the user's DRU from the fairness-plane cache
        when known."""
        if not self.enabled:
            return
        ts = int(self._clock())
        users = list(users) if users is not None else None
        dru_tab = self._user_dru.get(pool) or {}
        n = 0
        with self._lock:
            for i, (uuid, pos) in enumerate(zip(uuids, positions)):
                data: Dict[str, Any] = {"pos": int(pos), "pool": pool}
                if users is not None:
                    dru = dru_tab.get(users[i])
                    if dru is not None:
                        data["dru"] = round(dru, 4)
                self._record_one(str(uuid), "ranked", data, ts, 1,
                                 True, False)
                n += 1
        if n:
            _flight.note_audit(n)
            with self._lock:
                self._ev_counts["ranked"] = \
                    self._ev_counts.get("ranked", 0) + n

    # ----------------------------------------------------------- tx events
    def on_tx_events(self, events) -> None:
        """Lifecycle events off the store's transaction feed
        (state/store.py TxEvent).  Durability for these does NOT go
        through the pending flush: the store journals them atomically
        with their transaction (``"a"`` key on the txn record), so they
        are marked pre-flushed here."""
        if not self.enabled:
            return
        ts = None
        by_kind: Dict[str, int] = {}
        with self._lock:
            for e in events:
                wire = tx_event_to_audit(e)
                if wire is None:
                    continue
                if ts is None:
                    ts = int(self._clock())
                uuid, kind, data = wire
                self._record_one(uuid, kind, data, ts, 1, False, False)
                by_kind[kind] = by_kind.get(kind, 0) + 1
            for kind, n in by_kind.items():
                self._ev_counts[kind] = self._ev_counts.get(kind, 0) + n
        if by_kind:
            _flight.note_audit(sum(by_kind.values()))

    def publish_metrics(self) -> None:
        """Push the accumulated per-kind event counts onto
        ``cook_audit_events_total`` — called once per scheduler cycle
        (Store.flush_audit) and from stats(), so the registry sees the
        same totals without per-transaction label hashing."""
        with self._lock:
            counts, self._ev_counts = self._ev_counts, {}
        for kind, n in counts.items():
            registry.counter_inc("cook_audit_events", float(n),
                                 {"kind": kind})

    def discard_pending(self) -> None:
        """Drop pending durable events WITHOUT serializing them — the
        no-journal store's once-per-cycle pressure valve (there is no
        durability to provide; the in-memory lanes keep everything)."""
        with self._lock:
            pending, self._pending = self._pending, []
            for _uuid, ev in pending:
                ev.flushed = True

    # ------------------------------------------------------------ fairness
    def set_user_dru(self, pool: str, table: Dict[str, float]) -> None:
        """Replace a pool's DRU cache wholesale (the monitor sweep's
        publish path): users absent from the new table are gone —
        bounded by the CURRENT user population, never cumulative."""
        with self._lock:
            self._user_dru[pool] = {u: float(v) for u, v in table.items()}

    def user_dru(self, pool: str, user: str) -> Optional[float]:
        with self._lock:
            tab = self._user_dru.get(pool)
            return tab.get(user) if tab is not None else None

    def user_dru_table(self, pool: str) -> Dict[str, float]:
        """Copy of a pool's whole per-user DRU table (the fairness
        plane's objective signal for the goodput optimizer,
        sched/optimizer.py)."""
        with self._lock:
            tab = self._user_dru.get(pool)
            return dict(tab) if tab is not None else {}

    def last_reason(self, uuid: str) -> Optional[str]:
        """The job's most recent skip/defer reason (wait-phase
        classification input; O(1))."""
        with self._lock:
            lane = self._lanes.get(uuid)
            return lane.last_reason if lane is not None else None

    def last_reasons(self, uuids) -> Dict[str, Optional[str]]:
        """Bulk :meth:`last_reason` under ONE lock hold — the monitor's
        whole-pending-queue sweep must not pay 100k lock round-trips
        contending with the scheduler's hot-path record() calls."""
        with self._lock:
            lanes = self._lanes
            return {u: (lane.last_reason
                        if (lane := lanes.get(u)) is not None else None)
                    for u in uuids}

    # ----------------------------------------------------------- durability
    def drain_durable(self) -> List[Dict[str, Any]]:
        """Wire docs for durable events not yet journaled (Store.
        flush_audit calls this once per cycle).  Coalesced events are
        journaled at their first flush only; later count bumps stay
        in-memory (bounded journal growth).  Under brownout stage >= 1
        (``shed_advisory``, sched/admission.py) the flush FOLDS:
        pending events are marked flushed without serializing — zero
        journal bytes, in-memory lanes intact, `cs why` keeps
        answering; only pre-failover durability of advisory detail is
        shed."""
        with self._lock:
            pending, self._pending = self._pending, []
            out = []
            for uuid, ev in pending:
                if ev.flushed:
                    continue
                ev.flushed = True
                if self.shed_advisory:
                    self.shed_count += 1
                    continue
                out.append(ev.to_wire(uuid))
            return out

    def load(self, records: List[Dict[str, Any]]) -> None:
        """Rebuild lanes from journal ``"a"`` records (replay at store
        open / leader promotion).  Loaded events never re-pend: the
        journal copy they came from is already in this store's journal."""
        if not self.enabled:
            return
        with self._lock:
            for r in records:
                try:
                    self._record_one(
                        r["u"], r["k"], r.get("d"),
                        int(r.get("t") or 0), int(r.get("n", 1)),
                        False, True)
                except (KeyError, TypeError, ValueError):
                    continue  # a malformed advisory record won't stop a boot

    def export_wire(self, max_events: int = 100_000) -> List[Dict[str, Any]]:
        """Every lane's events as wire docs — NEWEST lanes first under
        the cap (checkpoint compaction re-seeds the truncated journal
        with this): when the trail is bigger than the cap, it is the
        recently-submitted ACTIVE jobs whose failover continuity
        matters, not the oldest (mostly terminal) lanes.  A truncation
        is logged — a silent partial re-seed would read as full
        continuity."""
        selected: List[Tuple[str, List[_Ev]]] = []
        total = 0
        truncated = False
        with self._lock:
            for uuid, lane in reversed(self._lanes.items()):
                if total + len(lane.events) > max_events:
                    truncated = True
                    break
                selected.append((uuid, list(lane.events)))
                total += len(lane.events)
        # selection prioritizes the newest lanes, but the WIRE order is
        # oldest-first: load() re-inserts in wire order, and an inverted
        # order would make the newest pre-checkpoint jobs the first
        # evicted once the lane cap bites after a replay
        out = [ev.to_wire(uuid)
               for uuid, events in reversed(selected)
               for ev in events]
        if truncated:
            import logging
            logging.getLogger(__name__).warning(
                "audit re-seed truncated at %d events: only the newest "
                "lanes keep pre-compaction timeline continuity",
                len(out))
        return out

    # ---------------------------------------------------------------- query
    def timeline(self, uuid: str) -> List[Dict[str, Any]]:
        """The job's event documents in insertion (time) order."""
        with self._lock:
            lane = self._lanes.get(uuid)
            if lane is None:
                return []
            return [ev.to_doc() for ev in lane.events]

    def jobs_tracked(self) -> int:
        with self._lock:
            return len(self._lanes)

    def pending_durable_count(self) -> int:
        """Durable events still buffered for the journal — the cheap
        per-sweep read behind the ``audit_queue`` saturation signal
        (sched/fleet.py); :meth:`stats` walks every lane, this holds
        the lock for one ``len``."""
        with self._lock:
            return len(self._pending)

    def stats(self) -> Dict[str, Any]:
        """Aggregate counts for the simulator summary / tests."""
        self.publish_metrics()
        by_kind: Dict[str, int] = {}
        with self._lock:
            for lane in self._lanes.values():
                for ev in lane.events:
                    by_kind[ev.kind] = by_kind.get(ev.kind, 0) + ev.count
            return {"jobs": len(self._lanes), "by_kind": by_kind,
                    "pending_durable": len(self._pending),
                    "shed_advisory": self.shed_advisory,
                    "shed_count": self.shed_count}

    def skip_counts(self) -> Dict[str, int]:
        """Per-reason sums over every job's skip events — the attribution
        side of the parity check against the flight recorder's aggregate
        skip histogram."""
        counts: Dict[str, int] = {}
        with self._lock:
            for lane in self._lanes.values():
                for ev in lane.events:
                    if ev.kind == "skip":
                        r = ev.data.get("reason", "?")
                        counts[r] = counts.get(r, 0) + ev.count
        return counts

    def reset(self) -> None:
        with self._lock:
            self._lanes.clear()
            self._pending.clear()
            self._user_dru.clear()


def tx_event_to_audit(e) -> Optional[Tuple[str, str, Dict[str, Any]]]:
    """TxEvent -> (job uuid, audit kind, data), or None for kinds the
    trail doesn't track.  One mapping shared by the live feed
    (AuditTrail.on_tx_events) and the store's journal append (which
    embeds the same docs in the txn record for replay).  Branches are
    frequency-ordered: at 1000 launches/cycle this runs thousands of
    times per cycle."""
    kind, data = e.kind, e.data
    if kind == "instance-status":
        d = {"task": data.get("task_id"), "to": data.get("new")}
        reason = data.get("reason")
        if reason is not None:
            d["reason"] = reason
        return data["job"], "instance", d
    if kind == "job-state":
        new = data.get("new")
        if new == "completed":
            return data["uuid"], "terminal", {}
        if new == "waiting" and data.get("old") == "running":
            return data["uuid"], "requeued", {}
        return None
    if kind == "instance-created":
        d = {"task": data.get("task_id"), "host": data.get("hostname")}
        gang = data.get("gang")
        if gang:
            d["gang"] = gang
        # serving-plane stitch points (docs/OBSERVABILITY.md): the
        # submission request's trace id and the trace of the cycle that
        # placed the job — /debug/trace?job= resolves both from here
        if data.get("trace"):
            d["trace"] = data["trace"]
        if data.get("cycle_trace"):
            d["cycle_trace"] = data["cycle_trace"]
        return data["job"], "launched", d
    if kind == "launch-ack":
        return data["job"], "launch-ack", {"task": data.get("task_id")}
    if kind == "job-created":
        d = {"user": data.get("user"), "pool": data.get("pool")}
        if data.get("trace"):
            d["trace"] = data["trace"]
        return data["uuid"], "submitted", d
    return None


def note_skips(trail: Optional[AuditTrail],
               mapping: Dict[str, Iterable],
               pool: Optional[str] = None) -> None:
    """Attributed skip noting: ONE mapping (reason -> job uuids, or
    (uuid, extra) tuples) feeds both the flight recorder's aggregate
    histogram and the per-job audit lanes, so the two can never drift
    (the attribution-parity invariant)."""
    counts = {}
    for reason, items in mapping.items():
        items = list(items)
        mapping[reason] = items
        if items:
            counts[reason] = len(items)
    if counts:
        _flight.note_skips(counts)
    if trail is not None and trail.enabled and counts:
        trail.skips({r: mapping[r] for r in counts}, pool=pool)


def wait_phase(reason: Optional[str], over_share: bool) -> str:
    """Classify WHY a pending job is waiting (the fairness plane's
    queue-latency split, sched/monitor.py):

    - ``fairness`` — throttled by a fair-share mechanism (quota, rate
      limit, reserved cap, gang admission) or the user is at/over share
      with no contrary signal;
    - ``constraints`` — the job (or its gang) can't be placed for
      constraint/topology reasons;
    - ``capacity`` — placeable in principle, no host has room (or no
      attribution yet and the user is under share)."""
    if reason in FAIRNESS_REASONS:
        return "fairness"
    if reason in CONSTRAINT_REASONS or reason == "constraints":
        return "constraints"
    if reason in ("unmatched", "launch-failed", "pipeline-conflict",
                  "pipeline-speculative"):
        return "capacity"
    return "fairness" if over_share else "capacity"
