"""Atomic durable small-file writes shared by the fencing machinery.

Three components persist a monotonic counter with identical durability
needs — the store's shared-dir epoch claim, the elector's election-epoch
mint, and the journal write-generation bump.  One implementation keeps
the ordering rule (write temp → flush → fsync → rename) in one place.

Locking contract: these helpers fsync and therefore BLOCK.  The one
caller allowed to invoke them while holding a named lock is the store's
checkpoint/fence path under the ``store`` lock — an allowlisted
blocking-under-lock site, because snapshot-then-truncate must be atomic
against concurrent writers.  The global lock-order contract (which lock
may nest inside which, and which blocking ops are allowed where) has
ONE home: the ``cook_tpu/utils/locks.py`` module docstring and its
``ALLOWED_BLOCKING`` table (docs/ANALYSIS.md) — it used to live only in
CHANGES.md prose.  ``cs lint`` enforces the static half; the tier-1
lock sanitizer enforces it at runtime.
"""

from __future__ import annotations

import os
from typing import Optional


def read_int_file(path: str, default: Optional[int] = None
                  ) -> Optional[int]:
    """The integer in ``path``, or ``default`` when missing/corrupt."""
    try:
        with open(path, encoding="utf-8") as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return default


def write_atomic_text(path: str, text: str) -> None:
    """Durably replace ``path`` with ``text``: temp file, fsync, rename,
    fsync of the containing directory.  A power loss leaves either the
    old or the new content, never a torn or REGRESSED one — POSIX does
    not guarantee the rename itself survives power loss without the
    directory fsync.

    The temp name is writer-unique (pid + thread id): two concurrent
    writers of the SAME path (e.g. the elector's position-publisher
    thread racing the promotion path on one candidate file) must each
    rename their own temp — a shared ``.tmp`` name let one writer's
    os.replace consume the other's temp file and crash it with
    FileNotFoundError.  The temp is DOT-PREFIXED: consumers that scan
    directories by filename prefix (the elector's candidate sidecars)
    must never parse a crash-orphaned temp as a live entry."""
    import threading
    head, tail = os.path.split(path)
    tmp = os.path.join(
        head, f".{tail}.tmp.{os.getpid()}.{threading.get_ident()}")
    # lazy import: fsatomic must stay import-light (the fencing paths
    # pull it in before most of the package exists)
    from .faults import injector as _faults
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
            f.flush()
            _faults.fire(
                "fsatomic.fsync",
                lambda: OSError(5, "injected fsync failure on "
                                   "atomic-write temp"))
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # unique temp names are never reused by later writers, so a
        # failed write must clean its own up or they accumulate
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # some filesystems refuse directory fsync; best effort


def write_atomic_int(path: str, value: int) -> None:
    """:func:`write_atomic_text` for the monotonic counters (election
    epochs, journal generations) that must never regress across
    crashes."""
    write_atomic_text(path, str(value))
