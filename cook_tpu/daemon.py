"""Process shell: config file -> store -> election -> clusters -> scheduler
-> REST, serving until leadership loss.

The equivalent of the reference's ``-main`` component graph (reference:
scheduler/src/cook/components.clj:345-365 -main + eager component compile
:257-343) and its leader-selector lifecycle (mesos.clj:153-328): every node
serves the REST API immediately; one node wins the election and becomes the
scheduler; on leadership loss the process EXITS NONZERO so a supervisor
restarts it clean (mesos.clj:296-313 System/exit).  ``api_only`` nodes never
campaign and 307-redirect leader-only requests (config.clj:692).

Config file is JSON or TOML:

    {
      "port": 12321,
      "host": "127.0.0.1",
      "data_dir": "/var/lib/cook",        # durable store (snapshot+journal)
      "election_dir": "/var/lib/cook",    # lock shared by contending nodes
      "api_only": false,
      "admins": ["admin"],
      "impersonators": [],
      "basic_auth_users": null,           # {"user": "password"} or null=open
      "clusters": [
        {"factory": "cook_tpu.cluster.fake.factory",
         "kwargs": {"name": "fake-1", "n_hosts": 4}}
      ],
      "plugins": {},                      # PluginRegistry.from_config spec
      "scheduler": {"cycle_mode": "fused", "rank_backend": "tpu", ...}
    }
"""

from __future__ import annotations

import importlib
import json
import os
import signal
import re
import sys
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from .config import Config
from .policy import PluginRegistry, QueueLimits, RateLimits
from .rest.api import (ApiError, ApiServer, CookApi,
                       check_container_wire_bytes, check_env_wire_bytes)
from .sched import Scheduler
from .sched.election import FileLeaderElector
from .state.store import Store

# Config fields settable straight from the "scheduler" config section.
_SCALAR_CONFIG_FIELDS = (
    "rank_interval_seconds", "match_interval_seconds", "max_over_quota_jobs",
    "cycle_mode", "default_pool", "autoscaling_enabled",
    "lingering_task_interval_seconds", "straggler_interval_seconds",
    "monitor_interval_seconds", "max_tasks_per_host", "heartbeat_enabled",
    "heartbeat_timeout_ms", "orphaned_cluster_grace_seconds",
    "columnar_index", "resident_pack", "quantized_wire",
)


def load_config_file(path: str) -> Dict:
    text = Path(path).read_text()
    if path.endswith(".toml"):
        import tomllib
        return tomllib.loads(text)
    return json.loads(text)


def build_scheduler_config(spec: Dict) -> Config:
    cfg = Config()
    for key in _SCALAR_CONFIG_FIELDS:
        if key in spec and hasattr(cfg, key):
            setattr(cfg, key, spec[key])
    if "default_matcher" in spec:
        for k, v in spec["default_matcher"].items():
            if not hasattr(cfg.default_matcher, k):
                # a typo'd KEY silently keeping the default would let an
                # operator believe a knob is set (e.g. "auto_paking")
                raise ValueError(
                    f"unknown default_matcher key {k!r}")
            setattr(cfg.default_matcher, k, v)
        # setattr bypasses dataclass construction: re-validate so a
        # typo'd backend/auto_packing VALUE also fails the BOOT
        cfg.default_matcher.__post_init__()
    if "rebalancer" in spec:
        for k, v in spec["rebalancer"].items():
            if hasattr(cfg.rebalancer, k):
                setattr(cfg.rebalancer, k, v)
    if "task_constraints" in spec:
        # submission-time limits (reference: config.clj :task-constraints)
        for k, v in spec["task_constraints"].items():
            if hasattr(cfg.task_constraints, k):
                setattr(cfg.task_constraints, k, v)
    if "slo" in spec:
        # queue-latency / cycle-duration objectives (docs/OBSERVABILITY.md)
        for k, v in spec["slo"].items():
            if not hasattr(cfg.slo, k):
                raise ValueError(f"unknown slo key {k!r}")
            setattr(cfg.slo, k, v)
    if "faults" in spec:
        # deterministic fault injection (docs/ROBUSTNESS.md): arming from
        # config is explicit chaos opt-in, applied by the scheduler at
        # takeover.  A typo'd knob must fail the boot, not silently arm
        # nothing while the operator believes chaos is running.
        for k, v in spec["faults"].items():
            if not hasattr(cfg.faults, k):
                raise ValueError(f"unknown faults key {k!r}")
            setattr(cfg.faults, k, v)
        cfg.faults.enabled = bool(spec["faults"].get(
            "enabled", bool(cfg.faults.points)))
    if "circuit_breaker" in spec:
        for k, v in spec["circuit_breaker"].items():
            if not hasattr(cfg.circuit_breaker, k):
                raise ValueError(f"unknown circuit_breaker key {k!r}")
            setattr(cfg.circuit_breaker, k, v)
    if "pipeline" in spec:
        # pipelined fused cycles + compile-cache warmup
        # (docs/PERFORMANCE.md): a typo'd knob fails the BOOT — a
        # silently-defaulted depth would run a driver the operator
        # didn't choose
        from .config import PipelineConfig
        cfg.pipeline = PipelineConfig.from_conf(spec["pipeline"])
    if "audit" in spec:
        # per-job scheduling audit trail (docs/OBSERVABILITY.md); a
        # typo'd knob fails the boot like the pipeline section
        from .config import AuditConfig
        cfg.audit = AuditConfig.from_conf(spec["audit"])
    if "http" in spec:
        # serving-plane request observability (docs/OBSERVABILITY.md);
        # boot-validated like the pipeline/audit sections
        from .config import HttpConfig
        cfg.http = HttpConfig.from_conf(spec["http"])
    if "serving" in spec:
        # serving-plane scale-out: follower read fleet + group-commit
        # admission batching (docs/DEPLOY.md, docs/PERFORMANCE.md); a
        # typo'd knob fails the boot like the sections above
        from .config import ServingConfig
        cfg.serving = ServingConfig.from_conf(spec["serving"])
    if "partitions" in spec:
        # partitioned write plane (docs/DEPLOY.md): pool-group store/
        # journal shards; the routing map is validated HERE so a typo'd
        # index fails the boot, not the first submission to that pool
        from .config import PartitionConfig
        cfg.partitions = PartitionConfig.from_conf(spec["partitions"])
    if "elastic" in spec:
        # elastic-gang resize plane (docs/GANG.md elasticity): grace
        # window + resize cadence; a typo'd knob fails the boot like
        # the sections above
        from .config import ElasticConfig
        cfg.elastic = ElasticConfig.from_conf(spec["elastic"])
    if "optimizer" in spec:
        # the goodput optimizer loop (sched/optimizer.py): factories,
        # interval, and the nested goodput knobs are ALL validated at
        # boot — from_conf constructs the cycler once, so a typo'd
        # candidate list or a non-positive interval fails here, not at
        # the first cycle half a minute into leadership
        from .sched.optimizer import OptimizerConfig
        cfg.optimizer = OptimizerConfig.from_conf(spec["optimizer"])
    if "fleet" in spec:
        # fleet observability plane (docs/OBSERVABILITY.md): federation
        # scrape cadence, trace fan-out timeout, static extra members,
        # and the saturation red lines; a typo'd knob fails the boot
        # like the sections above
        from .config import FleetConfig
        cfg.fleet = FleetConfig.from_conf(spec["fleet"])
    if "admission" in spec:
        # layered admission + brownout ladder (docs/ROBUSTNESS.md,
        # docs/DEPLOY.md overload runbook): per-user/per-IP buckets,
        # the adaptive level's hysteresis band, and the stage
        # thresholds are ALL validated at boot — a typo'd knob or an
        # out-of-order ladder must fail here, not during the first
        # overload it was configured to survive
        from .config import AdmissionConfig
        cfg.admission = AdmissionConfig.from_conf(spec["admission"])
    if "storage" in spec:
        # storage-integrity plane (docs/ROBUSTNESS.md "WAL v2"): scrub
        # cadence/chunk, corruption self-heal, hygiene-sweep age; a
        # typo'd knob fails the boot like the sections above
        from .config import StorageConfig
        cfg.storage = StorageConfig.from_conf(spec["storage"])
        from .state import integrity as _integrity
        # Store.open's hygiene sweep runs before any config object is
        # reachable from the store, so the knob lands module-level
        _integrity.HYGIENE_MIN_AGE_S = \
            float(cfg.storage.hygiene_min_age_seconds)
    k8s = spec.get("kubernetes") or {}
    cfg.kubernetes_disallowed_container_paths = list(
        k8s.get("disallowed_container_paths", []))
    cfg.kubernetes_disallowed_var_names = list(
        k8s.get("disallowed_var_names", []))
    # pool-regex planes (reference config shape: [{"pool-regex": ...,
    # "container"/"env"/"valid-models": ...}])
    for conf_key, attr, value_key in (
            ("default_containers", "default_containers", "container"),
            ("default_envs", "default_envs", "env"),
            ("valid_gpu_models", "valid_gpu_models", "valid-models")):
        table = []
        for e in spec.get(conf_key) or []:
            rx, val = e.get("pool-regex"), e.get(value_key)
            if rx is None or val is None:
                print(f"cook_tpu: ignoring malformed {conf_key} entry "
                      f"{e!r} (needs pool-regex + {value_key})",
                      file=sys.stderr)
                continue
            try:
                # fail the BOOT on a bad pattern, not every submission
                re.compile(rx)
            except re.error as exc:
                raise ValueError(
                    f"invalid pool-regex {rx!r} in {conf_key}: {exc}")
            _check_plane_wire_bytes(conf_key, value_key, val)
            table.append((rx, val))
        setattr(cfg, attr, table)
    return cfg


def _check_plane_wire_bytes(conf_key: str, value_key: str, val) -> None:
    """Fail the BOOT when a pool-default container/env embeds NUL or the
    \\x1e wire separator — otherwise every job in the pool would be
    refused at the transport guard (or 500 at submission), an opaque
    failure for a purely operator-side mistake."""
    try:
        if value_key == "env":
            if not isinstance(val, dict):
                # check_env_wire_bytes skips non-dicts, but a list here
                # would TypeError every submission to the pool — fail boot
                raise ApiError(400, "env must be a map of VAR to value")
            check_env_wire_bytes(val)
        elif value_key == "container":
            check_container_wire_bytes(val)
    except ApiError as exc:
        raise ValueError(f"{conf_key}: {exc.message}") from exc


def build_authenticators(conf: Dict) -> Optional[List]:
    """Authentication chain from config (reference: the auth middleware
    selection, components.clj:266-284 + config :authorization).

    Keys: ``gssapi_service`` ("HTTP") enables SPNEGO/Kerberos validation
    (needs the gssapi package + a keytab; construction fails the boot
    fast when they're absent), ``hmac_ticket_secret`` enables the KDC-free
    signed-ticket scheme, ``basic_auth_users`` a password table.  Any of
    them configured makes authentication mandatory; none = open
    (trusted-header) mode handled by CookApi itself."""
    from .rest.auth import (BasicAuthenticator, GssapiAuthenticator,
                            HmacTokenAuthenticator)
    chain: List = []
    if conf.get("gssapi_service"):
        chain.append(GssapiAuthenticator(service=conf["gssapi_service"]))
    if conf.get("hmac_ticket_secret"):
        chain.append(HmacTokenAuthenticator(conf["hmac_ticket_secret"]))
    if conf.get("basic_auth_users") and chain:
        # with a chain, basic joins it; alone, CookApi's own basic path
        # (the basic_auth_users kwarg) keeps handling it
        chain.append(BasicAuthenticator(conf["basic_auth_users"]))
    return chain or None


def build_clusters(specs: List[Dict], store: Store,
                   config: Optional[Config] = None) -> List:
    """Dotted-path cluster factories, the analog of the reference's
    factory-fn template instantiation (compute_cluster.clj:483-497).

    ``config`` threads the operator's scheduler-level k8s policy
    (disallowed container paths / var names) into any k8s backend that
    didn't receive its own explicit kwargs — config is the cross-node
    source of truth (/settings reports it on every node)."""
    clusters = []
    for spec in specs or []:
        path = spec["factory"]
        module, _, attr = path.rpartition(".")
        factory = getattr(importlib.import_module(module), attr)
        kwargs = dict(spec.get("kwargs", {}))
        cluster = factory(store=store, **kwargs)
        if config is not None \
                and hasattr(cluster, "disallowed_container_paths"):
            # the scheduler-level policy is a GLOBAL FLOOR: every k8s
            # backend enforces it in addition to its own kwargs, so the
            # /settings union reports exactly what is enforced
            cluster.disallowed_container_paths |= set(
                config.kubernetes_disallowed_container_paths)
            cluster.disallowed_var_names |= set(
                config.kubernetes_disallowed_var_names)
        clusters.append(cluster)
    return clusters


class CookDaemon:
    """One node's lifecycle.  ``run()`` blocks until shutdown and returns
    the process exit code (nonzero on leadership loss, the supervisor
    restart contract)."""

    def __init__(self, conf: Dict, port_override: Optional[int] = None,
                 api_only: Optional[bool] = None):
        self.conf = conf
        self.host = conf.get("host", "127.0.0.1")
        self.port = port_override if port_override is not None \
            else int(conf.get("port", 0))
        self.api_only = bool(conf.get("api_only", False)
                             if api_only is None else api_only)
        self.data_dir = conf.get("data_dir")
        self.exit_code = 0
        self._done = threading.Event()
        self._lock = threading.Lock()
        self.store: Optional[Store] = None
        self.scheduler: Optional[Scheduler] = None
        self.api: Optional[CookApi] = None
        self.server: Optional[ApiServer] = None
        self.elector: Optional[FileLeaderElector] = None
        # socket journal replication (state/replication.py): leader serves
        # its local journal; standbys mirror it into THEIR local data_dir
        self.repl_server = None
        self.repl_follower = None
        self._repl_stop = threading.Event()
        self._repl_thread: Optional[threading.Thread] = None
        # coordinated promotion (quorum-aware failover): a standby also
        # serves its own mirror (standby→standby catch-up) and publishes
        # its replication position into the election medium
        self.standby_server = None
        self._node_id: str = ""
        self._fence_thread: Optional[threading.Thread] = None
        # follower read fleet (state/read_replica.py): a standby's live
        # journal-applied store, served by the REST layer with the
        # bounded-staleness contract (docs/DEPLOY.md)
        self.read_view = None
        # monotonic timestamp of the last NOT-superseded fence verdict
        # (_fence_superseded's short-TTL cache)
        self._fence_cache: Optional[float] = None
        # fleet observability plane (sched/fleet.py): federation scraper
        # + trace fan-out over the candidate registry's topology
        self.fleet = None
        # multi-cell federation front door (federation/): a "federation"
        # conf section makes this process a stateless router over N
        # cells — no store, no journal, no election
        self.federation = None

    # -------------------------------------------------------------- assembly
    def start(self) -> None:
        conf = self.conf
        # ------------------------------------------------ federation role
        fed = conf.get("federation")
        if fed is not None:
            # the front door is sovereign-cell-agnostic by construction:
            # combining it with cell state in one process would couple
            # the router's availability to one cell's journal — exactly
            # the blast-radius federation exists to remove.  Refuse the
            # combination at boot, like every other conf contradiction.
            clashing = [k for k in ("scheduler", "clusters", "replication",
                                    "shared_data_dir", "data_dir",
                                    "election_dir", "election")
                        if conf.get(k)]
            if clashing:
                raise ValueError(
                    "a \"federation\" section makes this process a "
                    "stateless front-door router; it cannot also carry "
                    f"cell state (drop {', '.join(sorted(clashing))} or "
                    "run them as separate cell daemons — docs/DEPLOY.md "
                    "multi-cell federation)")
            from .federation.rest import build_federation_node
            # boot-validates the section (FederationConfig.from_conf):
            # unknown keys, malformed cells, bad tiers all fail HERE
            self.federation = build_federation_node(
                fed, host=self.host, port=self.port)
            self.federation.start()
            self.node_url = self.federation.url
            self._node_id = f"{self.host}-{self.federation.port}"
            from .utils import tracing
            tracing.set_process_identity(self._node_id)
            return
        # shared_data_dir: the data dir is on shared storage reachable from
        # every scheduler host (the Datomic-transactor slot).  Followers
        # load a replay-only view (no journal attach — their appends would
        # interleave with the leader's); the election winner re-opens
        # FENCED at the next epoch in _on_leadership, which also replays
        # everything the previous leader committed.
        sd = conf.get("shared_data_dir")
        self.shared_data = bool(sd)
        if isinstance(sd, str) and sd:
            # shared_data_dir may BE the path (the name invites it).  It
            # always wins over data_dir: fencing a node-local dir while
            # the operator believes shared-journal failover is active
            # would silently lose ALL state on the first real failover.
            if self.data_dir and self.data_dir != sd:
                print(f"cook_tpu: shared_data_dir={sd!r} overrides "
                      f"data_dir={self.data_dir!r} (HA state must live "
                      "on the shared path)", flush=True)
            self.data_dir = sd
        # "replication": {...} — HA over SEPARATE node-local data dirs:
        # the leader streams its journal to standbys over the native
        # framed-TCP carrier (no shared filesystem; the Datomic
        # networked-store slot, datomic.clj:79).  Mutually exclusive with
        # shared_data_dir, which wins (both configured would double-apply).
        self.repl_conf = dict(conf.get("replication") or {})
        self.replication = bool(self.repl_conf) and not self.shared_data \
            and bool(self.data_dir)
        if self.replication:
            from .config import ReplicationConfig
            # a typo'd knob fails the BOOT, like the scheduler sections
            self.repl_cfg = ReplicationConfig.from_conf(self.repl_conf)
        if self.repl_conf and self.shared_data:
            print("cook_tpu: replication ignored (shared_data_dir wins)",
                  flush=True)
        if self.repl_conf and not self.shared_data and not self.data_dir:
            # silently running a pure in-memory store while the operator
            # believes sync replication protects the state would lose
            # everything on the first restart
            raise ValueError("replication requires a data_dir (the "
                             "local journal to replicate)")
        sched_spec = dict(conf.get("scheduler", {}))
        self.sched_config = build_scheduler_config(sched_spec)
        # partitioned write plane (docs/DEPLOY.md): P > 1 shards the
        # store + journal by pool group.  Config is validated at boot;
        # P = 1 keeps the classic single Store (compatibility mode).
        pc = self.sched_config.partitions
        self.partitioned = pc.count > 1
        if self.partitioned:
            if self.shared_data or self.replication:
                # each partition carries its OWN replication topology;
                # wiring P topologies through one daemon's follower
                # loop is the multi-host half of this plane and ships
                # with the federation work — refusing beats silently
                # mirroring one journal of P
                raise ValueError(
                    "partitions.count > 1 is not yet supported together "
                    "with shared_data_dir/replication in one daemon; "
                    "run the partitioned plane standalone (per-partition "
                    "replication is exercised by sim --chaos-failover "
                    "--partitions)")
            if self.sched_config.columnar_index \
                    or self.sched_config.resident_pack:
                # the columnar projection is per-store; the partitioned
                # facade serves the entity path
                print("cook_tpu: partitions>1 pins columnar_index/"
                      "resident_pack off (entity path)", flush=True)
                self.sched_config.columnar_index = False
                self.sched_config.resident_pack = False
            from .state.partition import PartitionedStore, PartitionMap
            pmap = PartitionMap(count=pc.count, pools=pc.pools)
            if pc.shards or pc.shard_pools:
                # boot-time cross-check (ISSUE 19 satellite): the
                # PartitionMap pool groups and the mesh pool_sharding
                # layout must be the SAME partition — a mismatched
                # declaration silently double-owns or orphans a pool's
                # resident buffers, so it fails the boot here with the
                # offending pool named (ShardAlignmentError is a
                # ValueError: same config-error surface as the sections
                # around it)
                from .parallel.mesh import validate_shard_alignment
                validate_shard_alignment(pmap, pc.shards or 1,
                                         pc.shard_pools)
            if not self.data_dir:
                self.store = PartitionedStore(
                    [Store(partition=i) for i in range(pc.count)], pmap,
                    summary_max_age_s=pc.summary_max_age_seconds)
            else:
                # per-partition lease claims: each shard dir fences at
                # its own epoch (the N-leases-over-P-partitions layout)
                self.store = PartitionedStore.open(
                    self.data_dir, pmap,
                    summary_max_age_s=pc.summary_max_age_seconds)
        elif not self.data_dir:
            self.store = Store()
        elif self.shared_data or self.replication:
            # follower view until elected (replication: the native
            # follower mirrors the leader's bytes into this same local
            # dir; the election winner re-opens fenced in _on_leadership)
            os.makedirs(self.data_dir, exist_ok=True)
            self.store = Store.replay_only(self.data_dir)
        else:
            self.store = Store.open(self.data_dir)
        # dynamic cluster creation may instantiate exactly the factories
        # the operator already declared (plus an explicit allowlist)
        self.sched_config.cluster_factory_allowlist = sorted(
            {c["factory"] for c in conf.get("clusters", [])}
            | set(conf.get("cluster_factory_allowlist", [])))
        self.rank_backend = sched_spec.get("rank_backend", "tpu")
        self.plugins = PluginRegistry.from_config(conf.get("plugins", {}))
        self.rate_limits = RateLimits()
        self.queue_limits = QueueLimits(store=self.store)

        # REST serves on every node from the start (api-only nodes 307
        # leader-only requests via the elector's published URL)
        self.api = CookApi(
            self.store, scheduler=None, config=self.sched_config,
            plugins=self.plugins, rate_limits=self.rate_limits,
            queue_limits=self.queue_limits,
            admins=conf.get("admins"), impersonators=conf.get("impersonators"),
            basic_auth_users=conf.get("basic_auth_users"),
            authenticators=build_authenticators(conf),
            cors_origins=conf.get("cors_origins"),
            ip_requests_per_minute=conf.get("ip_requests_per_minute"))
        self.server = ApiServer(self.api, host=self.host, port=self.port)
        self.server.start()
        self.node_url = f"http://{self.host}:{self.server.port}"
        self._node_id = f"{self.host}-{self.server.port}"
        # this process's span identity: every span recorded from here on
        # carries it, so the fleet-stitched Perfetto export renders this
        # node as its own process track (docs/OBSERVABILITY.md)
        from .utils import tracing
        tracing.set_process_identity(self._node_id)
        self.api.instance = self._node_id

        election = conf.get("election", {})
        if election.get("mode") == "k8s-lease":
            # distributed election over the cluster backend's Lease object
            # (the ZK/Curator slot; no extra infrastructure needed)
            from .cluster.k8s.real_api import RealKubernetesApi
            from .sched.election import LeaseLeaderElector
            api = RealKubernetesApi(
                namespace=election.get("namespace", "cook"),
                kubeconfig=election.get("kubeconfig"),
                base_url=election.get("base_url"),
                token=election.get("token"),
                verify_tls=election.get("verify_tls", True))
            self.elector = LeaseLeaderElector(
                api, identity=election.get("identity") or self.node_url,
                node_url=self.node_url,
                lease_name=election.get("lease_name",
                                        "cook-scheduler-leader"),
                duration_s=float(election.get("duration_seconds", 15.0)),
                on_leadership=self._on_leadership, on_loss=self._on_loss)
        else:
            election_dir = conf.get("election_dir") or self.data_dir
            if not election_dir:
                # no explicit election_dir and no data_dir: a
                # single-process election with nothing to share.  The
                # old fallback was the cwd, which littered
                # cook-leader.lock{,.epoch,.leader} into whatever
                # directory the process (or a test) started from; a
                # per-process tempdir keeps the same semantics with no
                # droppings
                import tempfile
                election_dir = tempfile.mkdtemp(prefix="cook-election-")
            self.elector = FileLeaderElector(
                str(Path(election_dir) / "cook-leader.lock"), self.node_url,
                on_leadership=self._on_leadership, on_loss=self._on_loss)
        self.api.elector = self.elector
        self.api.node_url = self.node_url
        if self.sched_config.fleet.enabled:
            # metrics federation + fleet trace fan-out share ONE
            # topology source: the election medium's candidate registry
            # (standbys publish url/ts there each position interval),
            # plus any statically-configured extra members
            from .sched.fleet import FleetScraper
            from .state.replication import known_members
            fleet_cfg = self.sched_config.fleet
            self.fleet = FleetScraper(
                fleet_cfg,
                members_fn=lambda: known_members(
                    self.elector, self._node_id, self.node_url,
                    leader=self.scheduler is not None,
                    extra=fleet_cfg.members))
            self.api.fleet = self.fleet
        if self.replication:
            if not conf.get("election_dir"):
                # without an explicit SHARED election dir the elector
                # falls back to the node-local data_dir: every node wins
                # its own private election and promotes — split brain
                # with zero mirroring, silently
                raise ValueError(
                    "replication requires an explicit election_dir "
                    "(a path shared by every scheduler host — the "
                    "election authority)")
            if not hasattr(self.elector, "lock_path"):
                # the replication address is published through the file
                # elector's directory; proceeding would mean standbys
                # never mirror while sync commits pass vacuously — the
                # operator believes in durability that does not exist
                raise ValueError(
                    "replication requires the file-based elector "
                    "(election_dir); the k8s-lease elector does not "
                    "publish a replication address")
            # build the native library NOW, outside any lock: the first
            # ReplicationFollower/Server construction otherwise triggers
            # a g++ compile (up to ~3 min) inside _lock, stalling a
            # concurrent _on_leadership promotion for the whole build
            from .state.replication import replication_available
            if not replication_available():
                raise ValueError(
                    "replication requires the native toolchain "
                    "(libcookrepl failed to build — see stderr)")
            self.api.repl_dir = self.data_dir  # /debug/replication panel
            self._repl_thread = threading.Thread(
                target=self._follow_leader_loop, daemon=True)
            self._repl_thread.start()
            if self.sched_config.serving.follower_reads:
                # promote the byte mirror to a LIVE read store: this
                # standby serves bounded-staleness GETs instead of
                # redirecting them (ROADMAP item 1's read fleet).
                # Subscribe via on_swap() AFTER the assignments — the
                # method invokes the callback immediately with the
                # view's store, so api.store is re-pointed even when
                # the mirror never re-bases again (a restarted standby
                # resuming an intact mirror by delta would otherwise
                # serve the frozen boot-time replay forever)
                from .state.read_replica import FollowerReadView
                self.read_view = FollowerReadView(
                    self.data_dir,
                    interval_s=self.sched_config.serving
                    .apply_interval_seconds)
                self.api.read_view = self.read_view
                self.read_view.on_swap(self._on_view_swap)
        elif self.data_dir and not self.shared_data:
            # single-node durable leader: the group-commit stage
            # amortizes fsync across concurrent REST writers
            self._maybe_enable_group_commit()
        if not self.api_only:
            self.elector.campaign()

    def _on_view_swap(self, store: Store) -> None:
        """The read view rebuilt its store (initial build / mirror
        re-base): the REST layer must serve the fresh object.  A
        promoted leader ignores late swaps — promotion owns the store."""
        if self.scheduler is None and self.read_view is not None:
            self.store = store
            self.api.store = store
            self.queue_limits.store = store

    def _maybe_enable_group_commit(self) -> None:
        sv = self.sched_config.serving
        if sv.group_commit and self.store is not None:
            self.store.enable_group_commit(
                window_ms=sv.group_commit_window_ms,
                max_batch=sv.group_commit_max_batch)

    def _on_leadership(self) -> None:
        """PROCESS-GLOBAL TRANSITION: this node becomes THE scheduler
        (reference: LeaderSelectorListener.takeLeadership mesos.clj:193)."""
        try:
            # Takeover BLOCKS under the daemon's role lock by design:
            # journal replay + fsync, peer catch-up, and one-time
            # native-library builds must all complete before this node
            # may serve — the lock IS the promotion barrier, and role
            # flips are rare (election cadence, not request cadence).
            # The transitive-blocking pragmas below acknowledge each
            # blocking subtree (docs/ANALYSIS.md).
            with self._lock:
                if self.replication:
                    # cs-lint: allow=lock-transitive-blocking
                    self._promote_replicated()
                elif self.shared_data and self.data_dir:
                    # take over the SHARED journal: claim the next epoch
                    # (fencing out the previous leader's late appends) and
                    # replay everything it committed, then serve queries
                    # from the fenced store
                    # cs-lint: allow=lock-transitive-blocking
                    self.store = Store.open(self.data_dir, epoch="auto")
                    self.api.store = self.store
                    self.queue_limits.store = self.store
                    self._maybe_enable_group_commit()
                clusters = build_clusters(self.conf.get("clusters", []),
                                          self.store,
                                          config=self.sched_config)
                # cs-lint: allow=lock-transitive-blocking
                self.scheduler = Scheduler(
                    self.store, self.sched_config, clusters,
                    rank_backend=self.rank_backend, plugins=self.plugins,
                    rate_limits=self.rate_limits)
                self.scheduler.run()
                self.api.scheduler = self.scheduler
                if self.fleet is not None:
                    # the leader's monitor sweep drives federation
                    # scrapes (followers run no Monitor; their /metrics
                    # and /debug/fleet nudge the self-gated scraper)
                    self.scheduler.monitor.fleet = self.fleet
        except Exception:
            # A failed takeover (bad cluster factory, store corruption...)
            # must NOT leave this node holding the leader lock with no
            # scheduler: exit nonzero so the supervisor restarts us and a
            # peer can win the election.
            import traceback
            traceback.print_exc()
            self.exit_code = 1
            self._done.set()

    def _promote_replicated(self) -> None:
        """Become the leader of a socket-replicated deployment —
        COORDINATED promotion (quorum-aware failover, docs/DEPLOY.md):

        1. stop mirroring, publish this node's final replication
           position, and hold a candidacy window so every live standby's
           position is on the table;
        2. rank candidates by ``(synced, epoch, offset)`` (Raft's vote
           comparison, Ongaro & Ousterhout §5.4.1); if a synced peer is
           strictly ahead, pull the missing delta from it over the
           framed-TCP carrier first (Viewstamped Replication's
           view-change state transfer) — winning the lock race must not
           mean losing the tail only the most-advanced mirror holds;
        3. re-open the local mirror FENCED at the election epoch, with
           the fence authority pointed at the SHARED election epoch file
           so a later successor's mint fences this leader's appends,
           checkpoints, and REST writes end-to-end;
        4. serve replication to the next generation — losers re-follow
           the address published here.

        The reference equivalent is the new leader re-reading the
        networked store (mesos.clj:153-328)."""
        from .state import replication as repl
        if self.read_view is not None:
            # the promoted store owns the directory now; the read view's
            # replica store is superseded by the authoritative open below
            self.read_view.stop()
            self.read_view = None
            self.api.read_view = None
        if self.repl_follower is not None:
            self.repl_follower.stop()
            self.repl_follower = None
            self.api.repl_follower = None
        cfg = self.repl_cfg
        # ---- candidacy window: collect peer positions, rank, catch up
        my_pos = repl.candidate_position(self.data_dir)
        self.elector.publish_candidate(self._node_id, dict(
            my_pos, url=self.node_url, ts=time.time()))
        if cfg.candidacy_window_seconds > 0:
            self._repl_stop.wait(cfg.candidacy_window_seconds)
        peers = {nid: pos
                 for nid, pos in self.elector.read_candidates().items()
                 if nid != self._node_id}
        ahead = repl.choose_successor(my_pos, peers,
                                      stale_s=cfg.position_stale_seconds)
        if ahead is not None and not my_pos.get("synced"):
            # a live SYNCED candidate holds state this node lacks (we
            # are genesis or mid-catch-up): winning the lock race must
            # not install an empty/partial authority over it
            raise RuntimeError(
                f"candidate {ahead[0]} is synced ahead of this "
                "unsynced node; yielding the takeover")
        if ahead is not None:
            peer_id, pos = ahead
            host, _, port = str(pos.get("catchup", "")).rpartition(":")
            print(f"cook_tpu: candidate {peer_id} is ahead "
                  f"(epoch {pos.get('epoch')}, offset "
                  f"{pos.get('offset')} > {my_pos.get('offset')}); "
                  f"pulling delta from {host}:{port}", flush=True)
            if not host or not repl.catch_up_from_peer(
                    host, int(port or 0), self.data_dir,
                    int(pos.get("offset") or 0),
                    timeout_s=cfg.catchup_timeout_seconds):
                # the better-synced peer is live but unreachable: failing
                # the takeover (exit nonzero, lock released) lets THAT
                # peer win with its longer log instead of us truncating
                # history it holds
                raise RuntimeError(
                    f"could not catch up from better-synced candidate "
                    f"{peer_id} at {pos.get('catchup')!r}; yielding the "
                    "takeover so it can win")
        # Promotion gate (see assert_promotable): refusing raises into
        # _on_leadership's failed-takeover path — exit nonzero, lock
        # released, a synced peer wins instead.
        repl.assert_promotable(self.data_dir)
        self.elector.clear_candidate(self._node_id)
        if self.standby_server is not None:
            # the real replication server replaces the catch-up server
            self.standby_server.stop()
            self.standby_server = None
        epoch = self.elector.epoch if self.elector is not None else None
        self.store = Store.open(self.data_dir,
                                epoch=epoch if epoch is not None
                                else "auto", shared=False)
        authority = self._epoch_authority_path()
        if authority is not None:
            # fence against the SHARED election epoch, not the local
            # claim file nobody else writes: a successor's mint must
            # reject this node's late appends/checkpoints
            self.store.attach_fence_authority(str(authority))
        self.api.store = self.store
        self.queue_limits.store = self.store
        self.repl_server = repl.ReplicationServer(
            self.data_dir, int(cfg.listen_port))
        self.repl_server.epoch = self.store._journal_epoch
        self.store.attach_replication(
            self.repl_server, sync=bool(cfg.sync),
            timeout_s=float(cfg.ack_timeout_seconds),
            min_followers=int(cfg.min_sync_followers))
        self.api.repl_server = self.repl_server  # surfaced in GET /info
        self.api.fence_guard = self._fence_superseded
        # write-path admission batching: one fsync + one ack round per
        # batch of concurrent REST submissions (docs/PERFORMANCE.md)
        self._maybe_enable_group_commit()
        host = cfg.advertise_host or self.host
        self._publish_repl_addr(f"{host}:{self.repl_server.port}",
                                self.store._journal_epoch)
        self._fence_thread = threading.Thread(
            target=self._fence_watch_loop, daemon=True,
            name="repl-fence-watch")
        self._fence_thread.start()
        print(f"cook_tpu: replication leader serving "
              f"{host}:{self.repl_server.port} "
              f"(epoch {self.store._journal_epoch})", flush=True)

    def _repl_addr_path(self) -> Optional[Path]:
        lock = getattr(self.elector, "lock_path", None)
        return Path(str(lock) + ".repl") if lock is not None else None

    def _epoch_authority_path(self) -> Optional[Path]:
        return getattr(self.elector, "epoch_path", None)

    def _publish_repl_addr(self, addr: str,
                           epoch: Optional[int] = None) -> None:
        path = self._repl_addr_path()
        if path is None:
            return
        from .utils.fsatomic import write_atomic_text
        write_atomic_text(str(path), json.dumps(
            {"addr": addr, "epoch": epoch}))

    def _read_repl_addr(self) -> "tuple[Optional[str], Optional[int]]":
        """(addr, leader epoch) from the published file; tolerates the
        pre-coordination plain ``host:port`` format."""
        path = self._repl_addr_path()
        try:
            text = path.read_text().strip() if path else ""
        except OSError:
            return None, None
        if not text:
            return None, None
        try:
            doc = json.loads(text)
            return doc.get("addr") or None, doc.get("epoch")
        except ValueError:
            return text, None  # legacy plain address

    def _fence_superseded(self) -> bool:
        """True once a successor minted a HIGHER election epoch than the
        one this leader's store is fenced at — the REST write path flips
        to 503/redirect immediately (journal fencing alone only rejects
        the next append; reads of a stale leader are the client's
        redirect problem, writes must never be accepted).

        The NOT-superseded verdict is cached for a short TTL: every
        write AND every token-bearing read consults this guard, and a
        per-request epoch-file read would tax exactly the hot path the
        read fleet exists to lighten.  A fenced verdict is never cached
        stale — once True it recomputes (and stays True, since epochs
        only grow)."""
        now = time.monotonic()
        cached = self._fence_cache
        if cached is not None and now - cached < 0.25:
            return False
        authority = self._epoch_authority_path()
        store = self.store
        if authority is None or store is None \
                or store._journal_epoch is None:
            return False
        from .utils.fsatomic import read_int_file
        current = read_int_file(str(authority))
        superseded = current is not None and current > store._journal_epoch
        if not superseded:
            self._fence_cache = now
        return superseded

    def _fence_watch_loop(self) -> None:
        """Leader-side watchdog: a partitioned-but-alive deposed leader
        must stop SERVING, not just fail its next append — fence the
        replication server (standbys re-point at the successor's
        published address) and exit nonzero for the supervisor."""
        while not self._repl_stop.is_set():
            if self.repl_server is None:
                return
            if self._fence_superseded():
                print("cook_tpu: superseded by a higher election epoch; "
                      "fencing and exiting", flush=True)
                try:
                    self.repl_server.fence()
                except Exception:
                    pass
                self._on_loss()
                return
            self._repl_stop.wait(1.0)

    def _follow_leader_loop(self) -> None:
        """Standby side: keep a native follower mirroring whichever node
        currently publishes the replication address (re-pointing on
        failover), until this node is elected itself.  Each tick also
        publishes this standby's replication position ``(epoch, offset,
        synced)`` plus a catch-up address into the election medium — the
        inputs coordinated promotion ranks candidates by."""
        from .state import replication as repl
        cfg = self.repl_cfg
        current = None
        last_publish = 0.0
        while not self._repl_stop.is_set():
            if self.elector is not None and self.elector.is_leader:
                return  # _on_leadership owns (and stopped) the follower
            addr, leader_epoch = self._read_repl_addr()
            if addr and addr != current:
                try:
                    with self._lock:
                        if self.elector is not None \
                                and self.elector.is_leader:
                            return
                        if self.repl_follower is not None:
                            self.repl_follower.stop()
                        host, _, port = addr.rpartition(":")
                        if leader_epoch is not None:
                            # ranking orders mirrors of DIFFERENT
                            # leaderships by this epoch; the fsync'd
                            # epoch write and the follower's one-time
                            # native build block under the role lock by
                            # design — re-follow is the same rare
                            # transition as promotion above
                            # cs-lint: allow=lock-transitive-blocking
                            repl.record_followed_epoch(self.data_dir,
                                                       leader_epoch)
                        # cs-lint: allow=lock-transitive-blocking
                        self.repl_follower = repl.ReplicationFollower(
                            host, int(port), self.data_dir)
                        self.api.repl_follower = self.repl_follower
                        current = addr
                except Exception as e:
                    # a transient native-build failure or malformed
                    # address must not kill the standby's only mirror
                    # thread for the life of the process (sync commits
                    # would pass vacuously with zero mirrors) — log and
                    # retry on the next tick
                    print(f"cook_tpu: replication follower for {addr!r} "
                          f"failed ({e}); retrying", file=sys.stderr)
            now = time.time()
            if self.elector is not None and self.elector.is_leader:
                return  # promotion raced this tick: no stale publishes
            if now - last_publish >= cfg.position_interval_seconds:
                last_publish = now
                try:
                    if self.standby_server is None:
                        # serve our own mirror for standby→standby
                        # catch-up (the winner pulls its missing delta
                        # from whichever candidate is most advanced)
                        self.standby_server = repl.ReplicationServer(
                            self.data_dir, 0)
                    pos = repl.candidate_position(self.data_dir)
                    pos.update(
                        catchup=f"{cfg.advertise_host or self.host}:"
                                f"{self.standby_server.port}",
                        url=self.node_url, ts=now)
                    self.elector.publish_candidate(self._node_id, pos)
                except Exception as e:
                    print(f"cook_tpu: candidate-position publish failed "
                          f"({e}); retrying", file=sys.stderr)
            self._repl_stop.wait(0.5)

    def _on_loss(self) -> None:
        """Leadership lost -> exit nonzero; the supervisor restarts us
        (mesos.clj:296-313)."""
        self.exit_code = 1
        self._done.set()

    # ------------------------------------------------------------- lifecycle
    def run(self) -> int:
        self.start()
        signal.signal(signal.SIGTERM, self._sigterm)
        signal.signal(signal.SIGINT, self._sigterm)
        role = " (federation router)" if self.federation is not None \
            else (" (api-only)" if self.api_only else " (campaigning)")
        print(f"cook_tpu: serving {self.node_url}" + role, flush=True)
        self._done.wait()
        self.shutdown()
        return self.exit_code

    def _sigterm(self, _signum, _frame) -> None:
        self.exit_code = 0
        self._done.set()

    def shutdown(self) -> None:
        if self.federation is not None:
            self.federation.stop()
            self.federation = None
            return
        with self._lock:
            if self.scheduler is not None:
                self.scheduler.shutdown()
                for cluster in self.scheduler.clusters.values():
                    shutdown = getattr(cluster, "shutdown", None)
                    if shutdown:
                        try:
                            shutdown()
                        except Exception:
                            pass
        self._repl_stop.set()
        if self.read_view is not None:
            self.read_view.stop()
            self.read_view = None
        if self._repl_thread is not None:
            self._repl_thread.join(timeout=2.0)
        if self._fence_thread is not None:
            self._fence_thread.join(timeout=2.0)
        if self.repl_follower is not None:
            self.repl_follower.stop()
            self.repl_follower = None
        if self.standby_server is not None:
            self.standby_server.stop()
            self.standby_server = None
        if self.elector is not None and self._node_id:
            try:
                self.elector.clear_candidate(self._node_id)
            except Exception:
                pass
        if self.elector is not None:
            # resign AFTER scheduler stop; suppress on_loss (clean exit)
            self.elector.on_loss = None
            self.elector.resign()
        if self.server is not None:
            self.server.stop()
        if self.repl_server is not None:
            # after the final checkpoint would be better still, but
            # followers full-resync on reconnect anyway; stop last so
            # late acks don't block scheduler shutdown above
            self.repl_server.stop()
            self.repl_server = None
        if self.store is not None and self.data_dir:
            try:
                self.store.checkpoint()
            except Exception:
                pass


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m cook_tpu",
        description="Cook-TPU scheduler node (leader-elected)")
    parser.add_argument("--config", required=True,
                        help="JSON or TOML config file")
    parser.add_argument("--port", type=int, default=None,
                        help="override the configured REST port")
    parser.add_argument("--api-only", action="store_true", default=None,
                        help="serve the API without campaigning for leader")
    args = parser.parse_args(argv)
    conf = load_config_file(args.config)
    daemon = CookDaemon(conf, port_override=args.port,
                        api_only=args.api_only)
    return daemon.run()
