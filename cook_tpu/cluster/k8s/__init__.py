from .compute_cluster import KubernetesCluster  # noqa: F401
from .controller import (  # noqa: F401
    CookExpected,
    PodController,
    PodState,
    synthesize_pod_state,
)
from .fake_api import FakeKubernetesApi, FakeNode, FakePod, WatchEvent  # noqa: F401
