"""Shared kubernetes-adapter value types (neutral: returned by both the
in-process fake and the live-apiserver adapter)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease subset (holderIdentity, renewTime,
    leaseDurationSeconds, leaseTransitions) — the object behind k8s-native
    leader election.  ``renew_time_s`` is wall-clock epoch seconds (what a
    real apiserver stamps), so electors must compare against a wall clock.
    ``annotations`` carries the coordinated-promotion candidate positions
    (``cook.io/candidate-*``; sched/election.py) next to the holder-url
    annotation real leases already use.
    """

    name: str
    holder: str = ""
    holder_url: str = ""          # carried as an annotation on real leases
    renew_time_s: float = 0.0
    duration_s: float = 15.0
    transitions: int = 0
    annotations: Dict[str, str] = field(default_factory=dict)
