"""In-repo HTTP mock of the Kubernetes apiserver.

Serves the REST subset :class:`real_api.RealKubernetesApi` speaks —
list/get/create/delete pods, list nodes, chunked ``?watch=1`` streams
with resourceVersion semantics (including the 410 Gone watch-gap ERROR
event), and coordination/v1 leases with resourceVersion compare-and-swap
— in front of a :class:`fake_api.FakeKubernetesApi`, whose lifecycle
simulation hooks (``step``/``finish_pod``/``lose_node``/sticky deletion)
then drive the wire protocol.  This is what lets the real client adapter
execute every code path over real sockets without a cluster
(tests/test_k8s_real_api.py; reference for the behaviors mocked:
scheduler/src/cook/kubernetes/api.clj:372-734).

Fault injection for tests:
 - :meth:`drop_watch_streams` hard-closes active watch connections (the
   client must reconnect and resume from its last resourceVersion);
 - :meth:`compact` sets the history horizon so a watch from an older
   resourceVersion gets the 410 Gone ERROR event (client must relist).
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from .fake_api import FakeKubernetesApi, FakeNode, FakePod
from .real_api import RealKubernetesApi, rfc3339


def node_to_json(n: FakeNode) -> Dict:
    labels = dict(n.labels)
    labels.setdefault("cook-pool", n.pool)
    if n.gpu_model:
        labels.setdefault("gpu-model", n.gpu_model)
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": n.name, "labels": labels},
        "spec": {"taints": [{"key": k, "effect": "NoSchedule"}
                            for k in n.taints],
                 "unschedulable": n.unschedulable},
        "status": {"allocatable": {
            "cpu": str(n.cpus), "memory": f"{int(n.mem)}Mi",
            "nvidia.com/gpu": str(int(n.gpus))}},
    }


def pod_to_json(p: FakePod) -> Dict:
    labels = dict(p.labels)
    if p.synthetic:
        labels.setdefault("cook/synthetic", "true")
    meta: Dict = {"name": p.name, "labels": labels,
                  "annotations": dict(p.annotations),
                  "resourceVersion": str(p.resource_version)}
    if p.creation_ms:
        meta["creationTimestamp"] = rfc3339(p.creation_ms / 1000.0)
    if p.deleted:
        meta["deletionTimestamp"] = rfc3339((p.deletion_ms or 0) / 1000.0)
    status: Dict = {"phase": p.phase}
    if p.reason:
        status["reason"] = p.reason
    if p.unschedulable_reason:
        status["conditions"] = [{
            "type": "PodScheduled", "status": "False",
            "reason": "Unschedulable", "message": p.unschedulable_reason}]
    if p.exit_code is not None:
        status["containerStatuses"] = [{
            "name": "cook-job",
            "state": {"terminated": {"exitCode": p.exit_code,
                                     "reason": p.reason or "Completed"}}}]
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": meta,
        "spec": {"nodeName": p.node_name,
                 "containers": [{"name": "cook-job",
                                 "resources": {"requests": {
                                     "cpu": str(p.cpus),
                                     "memory": f"{int(p.mem)}Mi",
                                     **({"nvidia.com/gpu":
                                         str(int(p.gpus))}
                                        if p.gpus else {})}}}]},
        "status": status,
    }


def _status(code: int, reason: str, message: str = "") -> Dict:
    return {"kind": "Status", "apiVersion": "v1", "status": "Failure",
            "code": code, "reason": reason, "message": message}


class MockApiServer:
    """HTTP(S) front-end over a FakeKubernetesApi.  ``base_url`` is what
    a RealKubernetesApi should be pointed at.

    TLS (the reference's client stack is TLS everywhere —
    kubernetes/api.clj:372-475, project.clj:152-156): pass
    ``tls_cert``/``tls_key`` to serve https.  ``client_ca`` additionally
    REQUIRES a client certificate signed by that CA (mTLS) at the
    handshake.  ``bearer_token`` rejects any request without the
    matching ``Authorization: Bearer`` header with a k8s-shaped 401."""

    def __init__(self, fake: Optional[FakeKubernetesApi] = None,
                 host: str = "127.0.0.1",
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 client_ca: Optional[str] = None,
                 bearer_token: Optional[str] = None):
        self.fake = fake or FakeKubernetesApi()
        self._tls = bool(tls_cert)
        if (client_ca or tls_key) and not tls_cert:
            # a test passing client_ca alone would otherwise serve plain
            # HTTP and "pass" with zero mTLS enforcement
            raise ValueError("client_ca/tls_key require tls_cert")
        self.bearer_token = bearer_token
        self._lock = threading.Lock()
        self._leases: Dict[str, Dict] = {}   # name -> lease JSON
        self._lease_rv = 0
        self.min_rv = 0                       # 410 horizon (compact())
        self._drop_generation = 0             # bumping ends active streams
        self.last_created_bodies: List[Dict] = []  # golden-test capture
        self.requests: List[str] = []
        mock = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj: Dict) -> None:
                raw = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def _read_body(self) -> Dict:
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n)) if n else {}

            def _authorized(self) -> bool:
                """Bearer-token check (TLS client-cert identity is
                enforced earlier, at the handshake)."""
                if mock.bearer_token is None:
                    return True
                got = self.headers.get("Authorization") or ""
                if got == f"Bearer {mock.bearer_token}":
                    return True
                # drain the body first: an unread body left in a
                # keep-alive stream would be parsed as the next request
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    self.rfile.read(n)
                self._json(401, _status(401, "Unauthorized",
                                        "invalid bearer token"))
                return False

            def do_GET(self):
                mock.requests.append(f"GET {self.path}")
                if not self._authorized():
                    return
                u = urlparse(self.path)
                q = parse_qs(u.query)
                parts = [p for p in u.path.split("/") if p]
                if u.path == "/api/v1/nodes" and q.get("watch"):
                    return mock._serve_watch(self, "node", q)
                if u.path == "/api/v1/nodes":
                    return self._json(200, {
                        "kind": "NodeList",
                        "metadata": {"resourceVersion":
                                     str(mock.fake.resource_version)},
                        "items": [node_to_json(n)
                                  for n in mock.fake.nodes()]})
                # /api/v1/namespaces/{ns}/pods[/name]
                if len(parts) == 5 and parts[0] == "api" \
                        and parts[4] == "pods":
                    if q.get("watch"):
                        return mock._serve_watch(self, "pod", q)
                    return self._json(200, {
                        "kind": "PodList",
                        "metadata": {"resourceVersion":
                                     str(mock.fake.resource_version)},
                        "items": [pod_to_json(p)
                                  for p in mock.fake.pods()]})
                if len(parts) == 6 and parts[4] == "pods":
                    pod = mock.fake.pod(parts[5])
                    if pod is None:
                        return self._json(404, _status(404, "NotFound"))
                    return self._json(200, pod_to_json(pod))
                if "coordination.k8s.io" in u.path and parts[-2] == "leases":
                    with mock._lock:
                        lease = mock._leases.get(parts[-1])
                    if lease is None:
                        return self._json(404, _status(404, "NotFound"))
                    return self._json(200, lease)
                return self._json(404, _status(404, "NotFound", u.path))

            def do_POST(self):
                mock.requests.append(f"POST {self.path}")
                if not self._authorized():
                    return
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                body = self._read_body()
                if parts and parts[-1] == "pods":
                    mock.last_created_bodies.append(body)
                    pod = RealKubernetesApi._pod_from_json(body)
                    pod.spec = {"raw": body}
                    if not pod.creation_ms:
                        import time as _t
                        pod.creation_ms = int(_t.time() * 1000)
                    try:
                        mock.fake.create_pod(pod)
                    except ValueError:
                        return self._json(
                            409, _status(409, "AlreadyExists"))
                    return self._json(201, pod_to_json(pod))
                if parts and parts[-1] == "leases":
                    name = (body.get("metadata") or {}).get("name", "")
                    with mock._lock:
                        if name in mock._leases:
                            return self._json(
                                409, _status(409, "AlreadyExists"))
                        mock._lease_rv += 1
                        body.setdefault("metadata", {})["resourceVersion"] \
                            = str(mock._lease_rv)
                        mock._leases[name] = body
                    return self._json(201, body)
                return self._json(404, _status(404, "NotFound", u.path))

            def do_PUT(self):
                mock.requests.append(f"PUT {self.path}")
                if not self._authorized():
                    return
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                body = self._read_body()
                if len(parts) >= 2 and parts[-2] == "leases":
                    name = parts[-1]
                    with mock._lock:
                        cur = mock._leases.get(name)
                        if cur is None:
                            return self._json(404, _status(404, "NotFound"))
                        sent_rv = (body.get("metadata") or {}).get(
                            "resourceVersion")
                        cur_rv = (cur.get("metadata") or {}).get(
                            "resourceVersion")
                        if sent_rv is not None and sent_rv != cur_rv:
                            return self._json(
                                409, _status(409, "Conflict",
                                             "resourceVersion mismatch"))
                        mock._lease_rv += 1
                        body.setdefault("metadata", {})["resourceVersion"] \
                            = str(mock._lease_rv)
                        mock._leases[name] = body
                    return self._json(200, body)
                return self._json(404, _status(404, "NotFound", u.path))

            def do_DELETE(self):
                mock.requests.append(f"DELETE {self.path}")
                if not self._authorized():
                    return
                u = urlparse(self.path)
                q = parse_qs(u.query)
                parts = [p for p in u.path.split("/") if p]
                if len(parts) == 6 and parts[4] == "pods":
                    name = parts[5]
                    if mock.fake.pod(name) is None:
                        return self._json(404, _status(404, "NotFound"))
                    grace = q.get("gracePeriodSeconds")
                    mock.fake.delete_pod(
                        name,
                        grace_period_s=(float(grace[0]) if grace
                                        else None))
                    return self._json(200, _status(200, "Success"))
                return self._json(404, _status(404, "NotFound", u.path))

        self._httpd = ThreadingHTTPServer((host, 0), Handler)
        if self._tls:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            if client_ca:
                # mTLS: the handshake itself rejects clients without a
                # certificate signed by this CA
                ctx.load_verify_locations(cafile=client_ca)
                ctx.verify_mode = ssl.CERT_REQUIRED
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="mock-apiserver")

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "MockApiServer":
        self._thread.start()
        return self

    @property
    def base_url(self) -> str:
        host, port = self._httpd.server_address[:2]
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # ------------------------------------------------------- fault injection
    def drop_watch_streams(self) -> None:
        """Hard-end every active watch stream (a client must reconnect and
        resume from its last resourceVersion)."""
        self._drop_generation += 1

    def compact(self, min_rv: Optional[int] = None) -> None:
        """Move the watch-history horizon: a watch from an older
        resourceVersion gets the 410 Gone ERROR event (client relists)."""
        self.min_rv = (self.fake.resource_version if min_rv is None
                       else min_rv)

    # ------------------------------------------------------------- watching
    def _serve_watch(self, handler, kind: str, q) -> None:
        rv = int((q.get("resourceVersion") or ["0"])[0])
        timeout_s = float((q.get("timeoutSeconds") or ["30"])[0])
        generation = self._drop_generation

        def chunk(obj: Dict) -> bytes:
            raw = json.dumps(obj).encode() + b"\n"
            return hex(len(raw))[2:].encode() + b"\r\n" + raw + b"\r\n"

        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()
        if 0 < rv < self.min_rv:
            # watch gap: history before min_rv is compacted away
            handler.wfile.write(chunk({
                "type": "ERROR",
                "object": _status(410, "Gone", "too old resource version")}))
            handler.wfile.write(b"0\r\n\r\n")
            return
        events: "queue.Queue" = queue.Queue()

        def cb(evt):
            if evt.kind == kind:
                events.put(evt)

        self.fake.watch(cb, resource_version=rv)
        try:
            import time as _t
            deadline = _t.time() + timeout_s
            while _t.time() < deadline:
                if generation != self._drop_generation:
                    return  # fault injection: drop without clean close
                try:
                    evt = events.get(timeout=0.05)
                except queue.Empty:
                    continue
                obj = (pod_to_json(evt.obj) if kind == "pod"
                       else node_to_json(evt.obj))
                obj.setdefault("metadata", {})["resourceVersion"] = \
                    str(evt.resource_version)
                try:
                    handler.wfile.write(chunk(
                        {"type": evt.type, "object": obj}))
                    handler.wfile.flush()
                except (BrokenPipeError, ConnectionError):
                    return
            try:
                handler.wfile.write(b"0\r\n\r\n")  # clean timeout close
            except (BrokenPipeError, ConnectionError):
                pass
        finally:
            self.fake.unwatch(cb)
