"""In-process fake Kubernetes API.

The stand-in for a real k8s API server, mirroring how the reference unit
tests drive the controller/API layers without a cluster (reference:
scheduler/test/cook/test/kubernetes/*).  Implements the subset the backend
uses: node and pod objects, create/delete pod, watch streams with
resourceVersion resume, and a pod-lifecycle simulation the tests/simulator
can step (scheduled -> running -> succeeded/failed).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class FakeNode:
    name: str
    cpus: float
    mem: float
    gpus: float = 0.0
    pool: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[str] = field(default_factory=list)
    unschedulable: bool = False
    gpu_model: str = ""


from .types import Lease

FakeLease = Lease  # back-compat alias; the type itself is adapter-neutral


@dataclass
class FakePod:
    name: str
    node_name: Optional[str] = None        # set when scheduled
    phase: str = "Pending"                 # Pending|Running|Succeeded|Failed
    cpus: float = 0.0
    mem: float = 0.0
    gpus: float = 0.0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    deleted: bool = False                  # deletion timestamp set
    deletion_ms: Optional[int] = None      # when the delete was requested
    creation_ms: int = 0
    exit_code: Optional[int] = None
    reason: str = ""
    # scheduling condition message, e.g. "Unschedulable: taint mismatch"
    # (the PodScheduled=False condition the stuck-pod detector reads,
    # reference: kubernetes/api.clj:1820-1846)
    unschedulable_reason: str = ""
    synthetic: bool = False                # autoscaling placeholder
    resource_version: int = 0
    # rich pod spec compiled from the job (containers/volumes/env/
    # tolerations/priority...; reference: task-metadata->pod
    # kubernetes/api.clj:1370-1813)
    spec: Dict[str, object] = field(default_factory=dict)


class WatchEvent:
    __slots__ = ("kind", "type", "obj", "resource_version")

    def __init__(self, kind: str, type_: str, obj, resource_version: int):
        self.kind = kind          # "pod" | "node"
        self.type = type_         # ADDED | MODIFIED | DELETED
        self.obj = obj
        self.resource_version = resource_version


class FakeKubernetesApi:
    """Thread-safe fake API server with watches."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._nodes: Dict[str, FakeNode] = {}
        self._pods: Dict[str, FakePod] = {}
        self._leases: Dict[str, FakeLease] = {}
        self._rv = 0
        self._events: List[WatchEvent] = []
        self._watchers: List[Callable[[WatchEvent], None]] = []
        # simulation: pods auto-advance on step()
        self.auto_schedule = True
        # when True, graceful deletes linger in DELETING until
        # finish_deletion (exercises the controller's deleting arms)
        self.sticky_deletion = False

    # -------------------------------------------------------------- leases
    @staticmethod
    def _lease_copy(lease: Lease) -> Lease:
        # annotations is the one mutable field: a caller mutating the
        # returned copy must not reach back into the stored lease
        return Lease(**{**vars(lease),
                        "annotations": dict(lease.annotations)})

    def get_lease(self, name: str) -> Optional[Lease]:
        with self._lock:
            lease = self._leases.get(name)
            return self._lease_copy(lease) if lease else None

    def annotate_lease(self, name: str,
                       annotations: Dict[str, Optional[str]]) -> None:
        """Merge-patch the lease's metadata annotations (None deletes a
        key) — the coordination surface candidate positions ride
        (sched/election.py LeaseLeaderElector.publish_candidate)."""
        with self._lock:
            lease = self._leases.get(name)
            if lease is None:
                lease = Lease(name=name)
                self._leases[name] = lease
            for k, v in annotations.items():
                if v is None:
                    lease.annotations.pop(k, None)
                else:
                    lease.annotations[k] = str(v)

    def try_acquire_lease(self, name: str, identity: str, now_s: float,
                          duration_s: float = 15.0,
                          holder_url: str = "") -> Optional[Lease]:
        """Acquire-or-renew with the apiserver's compare-and-swap
        semantics: succeeds when the lease is unheld, expired, or already
        held by ``identity``; returns the updated lease or None when a
        live competitor holds it (the k8s leader-election recipe)."""
        with self._lock:
            lease = self._leases.get(name)
            if lease is None:
                lease = Lease(name=name)
                self._leases[name] = lease
            expired = now_s - lease.renew_time_s > lease.duration_s
            if lease.holder and lease.holder != identity and not expired:
                return None
            if lease.holder != identity:
                lease.transitions += 1  # new holder: fencing epoch bump
            lease.holder = identity
            lease.holder_url = holder_url
            lease.renew_time_s = now_s
            lease.duration_s = duration_s
            return self._lease_copy(lease)

    def release_lease(self, name: str, identity: str) -> None:
        """Explicit release on clean shutdown: clears the hold so a
        standby can acquire immediately (no TTL wait)."""
        with self._lock:
            lease = self._leases.get(name)
            if lease is not None and lease.holder == identity:
                lease.holder = ""
                lease.holder_url = ""
                lease.renew_time_s = 0.0

    # ------------------------------------------------------------- plumbing
    def _emit(self, kind: str, type_: str, obj) -> None:
        self._rv += 1
        if kind == "pod":
            obj.resource_version = self._rv
        event = WatchEvent(kind, type_, obj, self._rv)
        self._events.append(event)
        for w in list(self._watchers):
            w(event)

    def watch(self, callback: Callable[[WatchEvent], None],
              resource_version: int = 0) -> None:
        """Register a watcher; replays history after resource_version first
        (the resume semantics of kubernetes/api.clj:372-475)."""
        with self._lock:
            for event in self._events:
                if event.resource_version > resource_version:
                    callback(event)
            self._watchers.append(callback)

    def unwatch(self, callback: Callable[[WatchEvent], None]) -> None:
        with self._lock:
            if callback in self._watchers:
                self._watchers.remove(callback)

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._rv

    # ----------------------------------------------------------------- nodes
    def add_node(self, node: FakeNode) -> None:
        with self._lock:
            self._nodes[node.name] = node
            self._emit("node", "ADDED", node)

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self._nodes.pop(name, None)
            if node:
                self._emit("node", "DELETED", node)

    def nodes(self) -> List[FakeNode]:
        with self._lock:
            return list(self._nodes.values())

    # ------------------------------------------------------------------ pods
    def create_pod(self, pod: FakePod) -> None:
        with self._lock:
            if pod.name in self._pods:
                raise ValueError(f"pod {pod.name} already exists")
            self._pods[pod.name] = pod
            self._emit("pod", "ADDED", pod)

    def delete_pod(self, name: str, grace_period_s: Optional[float] = None,
                   now_ms: int = 0) -> None:
        """Graceful delete: marks deletion; the object disappears on the next
        lifecycle step (watch sees MODIFIED then DELETED).
        ``grace_period_s=0`` is the hard kill the controller issues for pods
        stuck DELETING past their deadline (controller.clj kill-pod-hard)."""
        with self._lock:
            pod = self._pods.get(name)
            if pod is None:
                return
            pod.deleted = True
            if pod.deletion_ms is None:
                pod.deletion_ms = now_ms
            if self.sticky_deletion and grace_period_s != 0:
                # simulate a slow kubelet: the pod lingers with its
                # deletionTimestamp set (synthesized state DELETING) until
                # finish_deletion or a grace-0 hard kill
                self._emit("pod", "MODIFIED", pod)
                return
            if pod.phase not in ("Succeeded", "Failed"):
                # killing a live pod fails it first
                pod.phase = "Failed"
                pod.reason = pod.reason or "Deleted"
                self._emit("pod", "MODIFIED", pod)
            # watchers run synchronously and may re-enter delete_pod;
            # pop so only one caller emits the DELETED event
            if self._pods.pop(name, None) is not None:
                self._emit("pod", "DELETED", pod)

    def finish_deletion(self, name: str) -> None:
        """Simulation hook: the kubelet finally releases a DELETING pod."""
        with self._lock:
            pod = self._pods.pop(name, None)
            if pod is not None:
                self._emit("pod", "DELETED", pod)

    def mark_unschedulable(self, name: str, reason: str) -> None:
        """Simulation hook: kube-scheduler reports PodScheduled=False."""
        with self._lock:
            pod = self._pods.get(name)
            if pod is not None:
                pod.unschedulable_reason = reason
                self._emit("pod", "MODIFIED", pod)

    def pods(self) -> List[FakePod]:
        with self._lock:
            return list(self._pods.values())

    def pod(self, name: str) -> Optional[FakePod]:
        with self._lock:
            return self._pods.get(name)

    # ------------------------------------------------------------ simulation
    def _fits(self, node: FakeNode, pod: FakePod,
              used: Dict[str, List[float]]) -> bool:
        u = used.get(node.name, [0.0, 0.0, 0.0])
        return (u[0] + pod.cpus <= node.cpus
                and u[1] + pod.mem <= node.mem
                and u[2] + pod.gpus <= node.gpus)

    def step(self) -> None:
        """Advance the cluster one tick: schedule pending pods (first-fit,
        the kube-scheduler stand-in) and start scheduled pods."""
        with self._lock:
            used: Dict[str, List[float]] = {}
            for pod in self._pods.values():
                if pod.node_name and pod.phase in ("Pending", "Running"):
                    u = used.setdefault(pod.node_name, [0.0, 0.0, 0.0])
                    u[0] += pod.cpus
                    u[1] += pod.mem
                    u[2] += pod.gpus
            for pod in list(self._pods.values()):
                if pod.phase == "Pending" and pod.node_name is None \
                        and self.auto_schedule:
                    for node in self._nodes.values():
                        if node.unschedulable:
                            continue
                        if self._fits(node, pod, used):
                            pod.node_name = node.name
                            u = used.setdefault(node.name, [0.0, 0.0, 0.0])
                            u[0] += pod.cpus
                            u[1] += pod.mem
                            u[2] += pod.gpus
                            self._emit("pod", "MODIFIED", pod)
                            break
                elif pod.phase == "Pending" and pod.node_name is not None:
                    pod.phase = "Running"
                    self._emit("pod", "MODIFIED", pod)

    def finish_pod(self, name: str, exit_code: int = 0) -> None:
        """Simulation hook: complete a running pod."""
        with self._lock:
            pod = self._pods.get(name)
            if pod is None or pod.phase not in ("Running", "Pending"):
                return
            pod.phase = "Succeeded" if exit_code == 0 else "Failed"
            pod.exit_code = exit_code
            self._emit("pod", "MODIFIED", pod)

    def lose_node(self, name: str) -> None:
        """Simulation hook: node disappears; its pods fail."""
        with self._lock:
            self.delete_node(name)
            for pod in list(self._pods.values()):
                if pod.node_name == name and pod.phase in ("Pending", "Running"):
                    pod.phase = "Failed"
                    pod.reason = "NodeLost"
                    self._emit("pod", "MODIFIED", pod)
