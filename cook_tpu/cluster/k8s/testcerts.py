"""Self-signed PKI for TLS tests: a CA, a server cert for 127.0.0.1, and
a client cert signed by the same CA.

Test support for the k8s wire (like :mod:`mock_apiserver`): the
reference's client stack is TLS everywhere
(scheduler/project.clj:152-156 pins an okhttp TLS client;
kubernetes/api.clj:372-475 builds it from kubeconfig/service-account
material), so the suite must execute real handshakes — server
verification against a CA, mTLS client identity, and wrong-CA rejection
— not just plaintext HTTP.
"""

from __future__ import annotations

import subprocess
from dataclasses import dataclass
from pathlib import Path


@dataclass
class TestPKI:
    ca_cert: str
    ca_key: str
    server_cert: str
    server_key: str
    client_cert: str
    client_key: str
    # a SECOND, unrelated CA: a client trusting this one must reject the
    # server's handshake
    wrong_ca_cert: str


def _run(args, cwd):
    subprocess.run(args, cwd=cwd, check=True, capture_output=True,
                   timeout=60)


def generate_pki(directory: str) -> TestPKI:
    """Generate the whole PKI under ``directory`` with the openssl CLI."""
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    ext = d / "san.ext"
    ext.write_text("subjectAltName=IP:127.0.0.1,DNS:localhost\n")

    _run(["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
          "-keyout", "ca.key", "-out", "ca.crt", "-days", "2",
          "-subj", "/CN=cook-test-ca"], d)
    _run(["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
          "-keyout", "wrong-ca.key", "-out", "wrong-ca.crt", "-days", "2",
          "-subj", "/CN=cook-wrong-ca"], d)

    for name, cn, use_ext in (("server", "127.0.0.1", True),
                              ("client", "cook-client", False)):
        _run(["openssl", "req", "-newkey", "rsa:2048", "-nodes",
              "-keyout", f"{name}.key", "-out", f"{name}.csr",
              "-subj", f"/CN={cn}"], d)
        cmd = ["openssl", "x509", "-req", "-in", f"{name}.csr",
               "-CA", "ca.crt", "-CAkey", "ca.key", "-CAcreateserial",
               "-out", f"{name}.crt", "-days", "2"]
        if use_ext:
            cmd += ["-extfile", str(ext)]
        _run(cmd, d)

    return TestPKI(ca_cert=str(d / "ca.crt"), ca_key=str(d / "ca.key"),
                   server_cert=str(d / "server.crt"),
                   server_key=str(d / "server.key"),
                   client_cert=str(d / "client.crt"),
                   client_key=str(d / "client.key"),
                   wrong_ca_cert=str(d / "wrong-ca.crt"))
