"""Pod-spec compiler: Job -> rich pod specification.

The analog of the reference's ``task-metadata->pod`` (reference:
scheduler/src/cook/kubernetes/api.clj:1370-1813) and its checkpointing
injection (api.clj:1173-1267): the job's container image/volumes, env,
checkpoint volumes + env + init container (with incremental-config-driven
image selection), tolerations, priority class, GPU/disk node selectors, and
the shm volume are compiled into a plain dict carried on the pod object.

The dict IS the contract: the fake API stores it verbatim; a real client
adapter translates it to V1Pod fields 1:1.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from ...state.schema import Checkpoint, Job

# well-known labels (shared with sched/constraints.py)
GPU_MODEL_LABEL = "gpu-model"
DISK_TYPE_LABEL = "disk-type"

COOK_WORKDIR = "/mnt/sandbox"
CHECKPOINT_VOLUME = "cook-checkpoint"
CHECKPOINT_MOUNT = "/mnt/checkpoint"
DEFAULT_CHECKPOINT_INIT_IMAGE = "cook/checkpoint-init:stable"
DEFAULT_FETCH_INIT_IMAGE = "cook/fetch-init:stable"
DEFAULT_SIDECAR_IMAGE = "cook/sidecar:stable"
SIDECAR_PORT = 28101
SIDECAR_HEALTH_PATH = "/readiness-probe"
SIDECAR_WORKDIR = "/mnt/sidecar"
# the file server is infrastructure, not user workload: its requests ride
# outside the job's resources (reference: sidecar resource-requirements
# from config, api.clj:1666-1696)
SIDECAR_CPUS = 0.1
SIDECAR_MEM_MB = 32.0
DEFAULT_SHM_MB = 64


def _resolve_image(incremental: Optional[Any], key: str, default: str,
                   job_uuid: str) -> str:
    """Incremental-config image rollout (reference resolves images per
    job-uuid hash portion, api.clj:1226 + config_incremental.clj)."""
    if incremental is not None:
        resolved = incremental.resolve(key, job_uuid)
        if resolved:
            return resolved
    return default


def build_pod_spec(job: Job, pool: str,
                   incremental: Optional[Any] = None,
                   sidecar: bool = True,
                   task_id: Optional[str] = None,
                   rest_url: str = "",
                   disallowed_container_paths: Optional[set] = None,
                   disallowed_var_names: Optional[set] = None
                   ) -> Dict[str, Any]:
    """Compile one job's pod specification.

    ``incremental`` is a policy.incremental.IncrementalConfig used for
    gradual image rollouts (the reference resolves the checkpoint init
    image per job-uuid hash, api.clj:1226 + config_incremental.clj).
    ``task_id``/``rest_url`` feed the task-identity metadata environment
    (reference: mesos/task.clj:114-135 + kubernetes/api.clj:1440
    COOK_SCHEDULER_REST_URL).
    """
    container = job.container or {}
    image = container.get("image", "cook/default-runtime:stable")

    env = [{"name": "HOST_IP",  # fieldRef, resolved by the kubelet
            # (reference: hostIpEnvVar kubernetes/api.clj:1102-1114)
            "value_from": {"field_ref": {"field_path": "status.hostIP"}}},
           {"name": "COOK_JOB_UUID", "value": job.uuid},
           {"name": "COOK_JOB_USER", "value": job.user},
           {"name": "COOK_WORKDIR", "value": COOK_WORKDIR},
           {"name": "COOK_POOL", "value": pool},
           {"name": "COOK_JOB_CPUS", "value": str(job.resources.cpus)},
           {"name": "COOK_JOB_MEM_MB", "value": str(job.resources.mem)}]
    if task_id:
        env.append({"name": "COOK_INSTANCE_UUID", "value": task_id})
        # count of PRIOR attempts (the launching task is already recorded
        # on the job; the reference counts the pre-transaction snapshot)
        env.append({"name": "COOK_INSTANCE_NUM",
                    "value": str(max(0, len(job.instances) - 1))})
    if job.resources.gpus:
        env.append({"name": "COOK_JOB_GPUS",
                    "value": str(job.resources.gpus)})
    if job.group:
        env.append({"name": "COOK_JOB_GROUP_UUID", "value": job.group})
    if rest_url:
        env.append({"name": "COOK_SCHEDULER_REST_URL", "value": rest_url})
    # scheduler-owned identity vars win over user env (the reference assocs
    # them ON TOP of job-ent->env, mesos/task.clj:127-131; k8s env lists are
    # last-entry-wins, so drop user collisions instead)
    reserved = {e["name"] for e in env}
    # operator-filtered var names (reference: make-filtered-env-vars,
    # kubernetes/api.clj:1117-1126 — REMOVED, not rejected: another
    # cluster component owns those names)
    blocked_vars = disallowed_var_names or set()
    env.extend({"name": k, "value": v} for k, v in sorted(job.env.items())
               if k not in reserved and k not in blocked_vars)

    volumes = [{"name": "cook-workdir", "empty_dir": {}}]
    mounts = [{"name": "cook-workdir", "mount_path": COOK_WORKDIR}]
    blocked_paths = disallowed_container_paths or set()
    for vol in container.get("volumes", []):
        # user volumes: {"host-path": ..., "container-path": ..., "mode":
        # ...} or the compact "host:container" string form
        if isinstance(vol, str):
            bits = vol.split(":")  # host[:container[:mode]]
            vol = {"host-path": bits[0],
                   "container-path": bits[1] if len(bits) > 1 and bits[1]
                   else bits[0],
                   "mode": ("RO" if len(bits) > 2
                            and bits[2].lower() == "ro" else "RW")}
        # paths another cluster component mounts (admission controller)
        # are dropped, not rejected (make-volumes, kubernetes/api.clj:995)
        target = vol.get("container-path") or vol.get("host-path")
        if target in blocked_paths:
            continue
        name = f"uservol-{len(volumes)}"
        volumes.append({"name": name,
                        "host_path": vol.get("host-path", "")})
        mounts.append({"name": name,
                       "mount_path": vol.get("container-path",
                                             vol.get("host-path", "")),
                       "read_only": vol.get("mode", "RW") == "RO"})

    # shm volume (api.clj shm handling): jobs can ask for a bigger /dev/shm
    shm_mb = int(job.labels.get("shm-size-mb", 0) or 0)
    if shm_mb:
        volumes.append({"name": "shm",
                        "empty_dir": {"medium": "Memory",
                                      "size_limit_mb": shm_mb}})
        mounts.append({"name": "shm", "mount_path": "/dev/shm"})

    init_containers = []
    tolerations = [
        # cook nodes are tainted so only cook pods land on them
        {"key": "cook-pool", "operator": "Equal", "value": pool,
         "effect": "NoSchedule"},
    ]
    node_selector: Dict[str, str] = {}

    # GPU jobs: node selector on gpu model + toleration
    if job.resources.gpus > 0:
        model = job.labels.get(GPU_MODEL_LABEL)
        if model:
            node_selector[GPU_MODEL_LABEL] = model
        tolerations.append({"key": "nvidia.com/gpu", "operator": "Exists",
                            "effect": "NoSchedule"})
    disk_type = job.labels.get(DISK_TYPE_LABEL)
    if disk_type:
        node_selector[DISK_TYPE_LABEL] = disk_type

    # checkpointing (api.clj:1173-1267): volume + env + init container whose
    # image can roll out gradually via incremental config
    checkpoint: Optional[Checkpoint] = job.checkpoint
    if checkpoint is not None:
        volumes.append({"name": CHECKPOINT_VOLUME, "empty_dir": {}})
        mounts.append({"name": CHECKPOINT_VOLUME,
                       "mount_path": CHECKPOINT_MOUNT})
        env.append({"name": "COOK_CHECKPOINT_MODE",
                    "value": checkpoint.mode.value})
        env.append({"name": "COOK_CHECKPOINT_PATH",
                    "value": CHECKPOINT_MOUNT})
        if checkpoint.period_sec:
            env.append({"name": "COOK_CHECKPOINT_PERIOD_SEC",
                        "value": str(checkpoint.period_sec)})
        init_image = _resolve_image(incremental, "checkpoint-init-image",
                                    DEFAULT_CHECKPOINT_INIT_IMAGE, job.uuid)
        init_containers.append({
            "name": "checkpoint-init",
            "image": init_image,
            "volume_mounts": [{"name": CHECKPOINT_VOLUME,
                               "mount_path": CHECKPOINT_MOUNT}],
            "env": [{"name": "COOK_JOB_UUID", "value": job.uuid}],
        })
        for extra in checkpoint.volume_mounts:
            mounts.append({"name": CHECKPOINT_VOLUME, "mount_path": extra,
                           "sub_path": extra.strip("/")})

    # URI artifacts: fetched into the shared workdir by an init container
    # before the job container starts — the k8s analog of the mesos
    # fetcher, with its full per-uri mode set (executable/extract/cache;
    # reference: :job/uri semantics, mesos fetcher task.clj:114-160)
    if job.uris:
        fetch_spec = [{"value": u.get("value", ""),
                       "executable": bool(u.get("executable", False)),
                       "extract": bool(u.get("extract", False)),
                       "cache": bool(u.get("cache", False))}
                      for u in job.uris]
        init_containers.append({
            "name": "cook-fetch",
            "image": DEFAULT_FETCH_INIT_IMAGE,
            "env": [
                # structured fetch list: modes survive the wire
                {"name": "COOK_URIS_JSON",
                 "value": json.dumps(fetch_spec, sort_keys=True)},
                # legacy flat form (paths only) kept for older fetchers
                {"name": "COOK_URIS",
                 "value": ";".join(u["value"] for u in fetch_spec)},
            ],
            "volume_mounts": [{"name": "cook-workdir",
                               "mount_path": COOK_WORKDIR}],
            "working_dir": COOK_WORKDIR,
        })

    # requested host-port count (mesos/task.clj:209-237's slot).  Dynamic
    # host-port assignment is the native transport's feature; kubernetes
    # has no offer-side port ranges, so the request is surfaced as
    # COOK_PORT_COUNT + spec metadata for a runtime webhook/CNI to fulfill
    # rather than fabricated containerPorts the apiserver would reject.
    if job.ports:
        env.append({"name": "COOK_PORT_COUNT", "value": str(job.ports)})

    # docker parameters that translate to pod fields (reference: the k8s
    # path honors workdir/env parameters, kubernetes/api.clj:1370-1813;
    # the rest are docker-runtime flags with no pod equivalent)
    workdir = COOK_WORKDIR
    for p in container.get("parameters", []) or []:
        key, value = p.get("key"), p.get("value", "")
        if key == "workdir" and value:
            workdir = value
        elif key == "env" and "=" in value:
            name, _, val = value.partition("=")
            # the SAME filters as job.env: scheduler-owned identity vars
            # and operator-owned names must not be injectable through a
            # docker parameter either (k8s env is last-entry-wins)
            if name not in reserved and name not in blocked_vars:
                env.append({"name": name, "value": val})

    # duplicate mountPaths are rejected by the apiserver; when a USER
    # volume collides with any system mount (sandbox, /dev/shm,
    # checkpoint) or an earlier user volume, the user one is dropped so
    # the job still runs (reference: test_workdir_volume_overlap)
    claimed: Dict[str, Dict] = {}
    for m in mounts:
        if not m["name"].startswith("uservol-"):
            claimed.setdefault(m["mount_path"], m)
    for m in mounts:
        if m["name"].startswith("uservol-"):
            claimed.setdefault(m["mount_path"], m)
    dropped_user = {m["name"] for m in mounts
                    if claimed.get(m["mount_path"]) is not m
                    and m["name"].startswith("uservol-")}
    mounts = [m for m in mounts if claimed.get(m["mount_path"]) is m]
    volumes = [v for v in volumes if v["name"] not in dropped_user]

    containers = [{
        "name": "cook-job",
        "image": image,
        "command": ["/bin/sh", "-c", job.command],
        "env": env,
        "volume_mounts": mounts,
        "resources": {
            "requests": {"cpu": job.resources.cpus,
                         "memory_mb": job.resources.mem,
                         "gpu": job.resources.gpus},
            "limits": {"memory_mb": job.resources.mem,
                       "gpu": job.resources.gpus},
        },
        "working_dir": workdir,
    }]
    if sidecar:
        # progress tracker + sandbox file server (the reference's sidecar,
        # api.clj:1664-1698; our agent/file_server.py is the server):
        # fixed port + command wiring, HTTP readiness probe on the health
        # endpoint, own (non-job) resource requests, read-only sandbox
        # mount, and incremental-config image rollout
        sidecar_image = _resolve_image(incremental, "sidecar-image",
                                       DEFAULT_SIDECAR_IMAGE, job.uuid)
        volumes.append({"name": "cook-sidecar-workdir", "empty_dir": {}})
        containers.append({
            "name": "cook-sidecar",
            "image": sidecar_image,
            "command": ["cook-sidecar", str(SIDECAR_PORT)],
            "ports": [SIDECAR_PORT],
            "env": [{"name": "COOK_JOB_UUID", "value": job.uuid},
                    {"name": "COOK_SANDBOX", "value": COOK_WORKDIR},
                    # DEPRECATED alias of COOK_SANDBOX (reference keeps it
                    # one release for older sidecars, api.clj:1680)
                    {"name": "COOK_WORKDIR", "value": COOK_WORKDIR},
                    {"name": "COOK_FILE_SERVER_PORT",
                     "value": str(SIDECAR_PORT)}],
            "readiness_probe": {"http_get": {"port": SIDECAR_PORT,
                                             "path": SIDECAR_HEALTH_PATH}},
            "resources": {"requests": {"cpu": SIDECAR_CPUS,
                                       "memory_mb": SIDECAR_MEM_MB},
                          "limits": {"memory_mb": SIDECAR_MEM_MB}},
            "volume_mounts": [{"name": "cook-workdir",
                               "mount_path": COOK_WORKDIR,
                               "read_only": True},
                              {"name": "cook-sidecar-workdir",
                               "mount_path": SIDECAR_WORKDIR}],
            "working_dir": SIDECAR_WORKDIR,
        })

    # priority class from the pool (synthetic pods ride a lower class so
    # real pods preempt them; api.clj priority-class handling)
    priority_class = job.labels.get("priority-class",
                                    f"cook-pool-{pool}")

    return {
        "containers": containers,
        "init_containers": init_containers,
        "port_count": job.ports,
        "volumes": volumes,
        "tolerations": tolerations,
        "node_selector": node_selector,
        "priority_class": priority_class,
        "restart_policy": "Never",
        "labels": dict(job.labels),
    }
