"""Real Kubernetes client adapter over stdlib HTTP.

Implements the same surface as :class:`fake_api.FakeKubernetesApi`
(nodes/pods/pod/create_pod/delete_pod/watch/unwatch/resource_version +
coordination/v1 leases) by speaking the Kubernetes REST API directly —
list/create/delete as JSON requests, watches as chunked ``?watch=1``
streams with resourceVersion resume and 410-Gone relist, leases with
resourceVersion compare-and-swap (reference: the okhttp watch +
client-java layer, scheduler/src/cook/kubernetes/api.clj:372-734; watch
bootstrap/resume :372-475).

No ``kubernetes`` package dependency: the wire protocol is small and a
stdlib client is exercisable in-repo against
:class:`mock_apiserver.MockApiServer` over real sockets
(tests/test_k8s_real_api.py), which is how every method here is tested.
"""

from __future__ import annotations

import datetime
import json
import socket
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from .fake_api import FakeNode, FakePod, WatchEvent
from .types import Lease

COOK_NS = "cook"


# --------------------------------------------------------------- quantities
def parse_qty(v, default: float = 0.0, kind: str = "count") -> float:
    """Kubernetes quantity -> float in cook units (cpus/gpus as counts,
    memory as MiB via ``kind="mem"``).

    "2" -> 2.0 cpus; "1500m" -> 1.5; "512Mi" -> 512; "1Gi" -> 1024;
    "524288Ki" -> 512; "2G" -> ~1907Mi.  A suffixless or
    exponent-form memory quantity ("16423059456", "16e9") is BYTES on
    the wire (canonical k8s form) and converts to MiB; suffixless
    cpu/gpu counts stay counts.
    """
    if v is None:
        return default
    s = str(v)
    try:
        if s.endswith("Ki"):
            return float(s[:-2]) / 1024.0
        if s.endswith("Mi"):
            return float(s[:-2])
        if s.endswith("Gi"):
            return float(s[:-2]) * 1024.0
        if s.endswith("Ti"):
            return float(s[:-2]) * 1024.0 * 1024.0
        if s.endswith("k"):
            return float(s[:-1]) * 1000.0 / (1024.0 * 1024.0)
        if s.endswith("M"):
            return float(s[:-1]) * 1e6 / (1024.0 * 1024.0)
        if s.endswith("G"):
            return float(s[:-1]) * 1e9 / (1024.0 * 1024.0)
        if s.endswith("m"):
            return float(s[:-1]) / 1000.0
        n = float(s)
        if kind == "mem":
            return n / (1024.0 * 1024.0)  # bytes -> MiB
        return n
    except ValueError:
        return default


def _ts_ms(rfc3339: Optional[str]) -> Optional[int]:
    if not rfc3339:
        return None
    try:
        dt = datetime.datetime.fromisoformat(rfc3339.replace("Z", "+00:00"))
        return int(dt.timestamp() * 1000)
    except ValueError:
        return None


def rfc3339(ts_s: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts_s, tz=datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def _resources_to_k8s(res: Dict) -> Dict:
    """pod_spec's internal resource dicts ({"cpu": float, "memory_mb":
    float, "gpu": float}) -> Kubernetes resource names/quantities (a real
    apiserver rejects unknown names like memory_mb)."""
    out: Dict = {}
    for section in ("requests", "limits"):
        vals = res.get(section)
        if not vals:
            continue
        k8s_vals: Dict = {}
        for k, v in vals.items():
            if k in ("memory_mb", "mem", "memory"):
                k8s_vals["memory"] = f"{int(float(v))}Mi"
            elif k in ("gpu", "gpus", "nvidia.com/gpu"):
                if float(v):
                    k8s_vals["nvidia.com/gpu"] = str(int(float(v)))
            else:
                k8s_vals["cpu" if k == "cpu" else k] = str(v)
        out[section] = k8s_vals
    return out


class ApiError(RuntimeError):
    def __init__(self, status: int, body: str = ""):
        super().__init__(f"apiserver HTTP {status}: {body[:200]}")
        self.status = status


class RealKubernetesApi:
    """Live-apiserver twin of FakeKubernetesApi over stdlib HTTP.

    ``base_url`` points at the apiserver (e.g. ``http://127.0.0.1:6443``
    or the MockApiServer's address); ``kubeconfig`` extracts server/token
    from a kubeconfig file instead.  Objects are translated into the same
    Fake* dataclasses the controller consumes, so
    :class:`compute_cluster.KubernetesCluster` and
    :class:`controller.PodController` run unchanged against a live
    cluster.
    """

    def __init__(self, namespace: str = COOK_NS,
                 kubeconfig: Optional[str] = None,
                 base_url: Optional[str] = None,
                 token: Optional[str] = None,
                 verify_tls: bool = True,
                 watch_timeout_s: float = 60.0):
        ctx: Optional[ssl.SSLContext] = None
        if kubeconfig and not base_url:
            base_url, token, ctx = self._from_kubeconfig(kubeconfig)
        self._token_path: Optional[str] = None
        self._token_checked = 0.0
        if not base_url and token is None:
            # in-cluster fallback: the pod's service account (the env
            # override exists so tests can execute this branch against a
            # mock apiserver — in a pod the default path is projected)
            import os
            sa = os.environ.get(
                "COOK_K8S_SA_DIR",
                "/var/run/secrets/kubernetes.io/serviceaccount")
            if os.path.exists(f"{sa}/token"):
                with open(f"{sa}/token", encoding="utf-8") as f:
                    token = f.read().strip()
                # bound service-account tokens ROTATE (the kubelet
                # refreshes the projected file); remember the path so
                # long-lived schedulers keep authenticating (reference:
                # TokenRefreshingAuthenticator.java + the bearer-token
                # refresh thread, kubernetes/compute_cluster.clj:756-792)
                self._token_path = f"{sa}/token"
                host = os.environ.get("KUBERNETES_SERVICE_HOST")
                port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
                if host:
                    base_url = f"https://{host}:{port}"
                if os.path.exists(f"{sa}/ca.crt"):
                    ctx = ssl.create_default_context(
                        cafile=f"{sa}/ca.crt")
        if not base_url:
            raise ValueError(
                "RealKubernetesApi needs base_url, kubeconfig, or an "
                "in-cluster service account")
        self.base_url = base_url.rstrip("/")
        self.namespace = namespace
        self.token = token
        self.watch_timeout_s = watch_timeout_s
        self._ctx = ctx
        if self.base_url.startswith("https") and not verify_tls:
            if self._ctx is None:
                self._ctx = ssl.create_default_context()
            # downgrade IN PLACE: rebuilding would drop a kubeconfig's
            # client-certificate (mTLS) identity
            self._ctx.check_hostname = False
            self._ctx.verify_mode = ssl.CERT_NONE
        self._rv = 0
        self._watchers: List[Callable[[WatchEvent], None]] = []
        self._lock = threading.RLock()
        # per-generation stop event: unwatch() must only stop the threads
        # of ITS generation — a later watch() spawns fresh threads with a
        # fresh event, so a slow old thread can never double-deliver
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # observability: watch reconnects / 410 relists (the reference
        # tracks watch gaps as metrics, api.clj:440-470)
        self.watch_reconnects = 0
        self.watch_gap_relists = 0

    @staticmethod
    def _from_kubeconfig(path: str) -> Tuple[str, Optional[str],
                                             Optional[ssl.SSLContext]]:
        """Resolve server/credentials honoring current-context, bearer
        tokens, client certificates, and CA bundles (inline *-data fields
        are written to temp files for the ssl module)."""
        import base64
        import tempfile

        import yaml
        with open(path, encoding="utf-8") as f:
            cfg = yaml.safe_load(f) or {}

        def by_name(items, name):
            for it in items or []:
                if it.get("name") == name:
                    return it
            return (items or [{}])[0]

        ctx_name = cfg.get("current-context")
        context = (by_name(cfg.get("contexts"), ctx_name)
                   .get("context") or {})
        cluster = (by_name(cfg.get("clusters"),
                           context.get("cluster")).get("cluster") or {})
        user = (by_name(cfg.get("users"),
                        context.get("user")).get("user") or {})
        server = cluster.get("server")
        if not server:
            raise ValueError(f"kubeconfig {path}: no cluster server")

        def materialize(data_key, file_key, src):
            if src.get(file_key):
                return src[file_key]
            if src.get(data_key):
                f = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
                f.write(base64.b64decode(src[data_key]))
                f.close()
                return f.name
            return None

        cafile = materialize("certificate-authority-data",
                             "certificate-authority", cluster)
        certfile = materialize("client-certificate-data",
                               "client-certificate", user)
        keyfile = materialize("client-key-data", "client-key", user)
        ctx = None
        if server.startswith("https") and (
                cafile or certfile
                or cluster.get("insecure-skip-tls-verify")):
            # skip-verify alone still needs a context: the default one
            # would reject the very self-signed server the operator just
            # told us to trust
            ctx = ssl.create_default_context(cafile=cafile)
            if cluster.get("insecure-skip-tls-verify"):
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if certfile:
                ctx.load_cert_chain(certfile, keyfile)
        return server, user.get("token"), ctx

    # ------------------------------------------------------------------ http
    def _bearer(self) -> Optional[str]:
        """The current bearer token, re-read from the projected
        service-account file at most once per minute (bound tokens
        rotate; a stale one starts getting 401s after expiry)."""
        if self._token_path is not None:
            now = time.time()
            if now - self._token_checked > 60.0:
                self._token_checked = now
                try:
                    with open(self._token_path, encoding="utf-8") as f:
                        fresh = f.read().strip()
                    if fresh:
                        self.token = fresh
                except OSError:
                    pass  # keep the last good token
        return self.token

    def _request(self, method: str, path: str, body=None,
                 timeout: float = 10.0):
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        token = self._bearer()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req, timeout=timeout,
                                        context=self._ctx) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else None
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, e.read().decode("utf-8", "replace")) \
                from None

    # ------------------------------------------------------------ translate
    @staticmethod
    def _node_from_json(n: Dict) -> FakeNode:
        meta = n.get("metadata") or {}
        spec = n.get("spec") or {}
        alloc = (n.get("status") or {}).get("allocatable") or {}
        labels = meta.get("labels") or {}
        return FakeNode(
            name=meta.get("name", ""),
            cpus=parse_qty(alloc.get("cpu")),
            mem=parse_qty(alloc.get("memory"), kind="mem"),
            gpus=parse_qty(alloc.get("nvidia.com/gpu")),
            pool=labels.get("cook-pool", "default"),
            labels=dict(labels),
            taints=[t.get("key", "") for t in (spec.get("taints") or [])],
            unschedulable=bool(spec.get("unschedulable")),
            gpu_model=labels.get("gpu-model", ""))

    @staticmethod
    def _pod_from_json(p: Dict) -> FakePod:
        meta = p.get("metadata") or {}
        spec = p.get("spec") or {}
        status = p.get("status") or {}
        labels = meta.get("labels") or {}
        exit_code = None
        reason = status.get("reason") or ""
        unschedulable = ""
        for cond in (status.get("conditions") or []):
            if cond.get("type") == "PodScheduled" \
                    and cond.get("status") == "False":
                unschedulable = (cond.get("message") or cond.get("reason")
                                 or "Unschedulable")
        for cs in (status.get("containerStatuses") or []):
            term = (cs.get("state") or {}).get("terminated")
            if term is not None and cs.get("name") == "cook-job":
                exit_code = term.get("exitCode")
                reason = reason or (term.get("reason") or "")
        req = {}
        containers = spec.get("containers") or []
        if containers:
            req = (containers[0].get("resources") or {}).get("requests") or {}
        deleted_at = _ts_ms(meta.get("deletionTimestamp"))
        return FakePod(
            name=meta.get("name", ""),
            node_name=spec.get("nodeName"),
            phase=status.get("phase") or "Pending",
            cpus=parse_qty(req.get("cpu")),
            mem=parse_qty(req.get("memory"), kind="mem"),
            gpus=parse_qty(req.get("nvidia.com/gpu")),
            labels=dict(labels),
            annotations=dict(meta.get("annotations") or {}),
            deleted=deleted_at is not None,
            deletion_ms=deleted_at,
            creation_ms=_ts_ms(meta.get("creationTimestamp")) or 0,
            exit_code=exit_code,
            reason=reason,
            unschedulable_reason=unschedulable,
            synthetic=labels.get("cook/synthetic") == "true",
            resource_version=int(meta.get("resourceVersion") or 0))

    def _pod_to_json(self, pod: FakePod) -> Dict:
        spec = pod.spec or {}

        def container(c):
            out = {"name": c["name"], "image": c["image"]}
            if c.get("command"):
                out["command"] = c["command"]
            if c.get("env"):
                def env_entry(e):
                    if "value_from" in e:  # fieldRef vars (HOST_IP)
                        fr = e["value_from"]["field_ref"]
                        return {"name": e["name"],
                                "valueFrom": {"fieldRef": {
                                    "fieldPath": fr["field_path"]}}}
                    return {"name": e["name"], "value": e["value"]}
                out["env"] = [env_entry(e) for e in c["env"]]
            if c.get("working_dir"):
                out["workingDir"] = c["working_dir"]
            if c.get("volume_mounts"):
                out["volumeMounts"] = [
                    {"name": m["name"], "mountPath": m["mount_path"],
                     **({"readOnly": True} if m.get("read_only") else {}),
                     **({"subPath": m["sub_path"]}
                        if m.get("sub_path") else {})}
                    for m in c["volume_mounts"]]
            if c.get("ports"):
                out["ports"] = [{"containerPort": int(p)}
                                for p in c["ports"]]

            def probe(p):
                # pod_spec carries {"http_get": {"port", "path"}}; the
                # wire form is camelCase httpGet
                if "http_get" in p:
                    hg = p["http_get"]
                    return {"httpGet": {"port": int(hg["port"]),
                                        "path": hg.get("path", "/")}}
                return p
            if c.get("liveness_probe"):
                out["livenessProbe"] = probe(c["liveness_probe"])
            if c.get("readiness_probe"):
                out["readinessProbe"] = probe(c["readiness_probe"])
            out["resources"] = {"requests": {
                "cpu": str(pod.cpus), "memory": f"{int(pod.mem)}Mi",
                **({"nvidia.com/gpu": str(int(pod.gpus))}
                   if pod.gpus else {})}}
            res = c.get("resources")
            if res:  # per-container override (sidecar/init containers)
                out["resources"] = _resources_to_k8s(res)
            return out

        def volume(v):
            if "host_path" in v:
                return {"name": v["name"],
                        "hostPath": {"path": v["host_path"]}}
            ed = v.get("empty_dir", {})
            out = {}
            if ed.get("medium"):
                out["medium"] = ed["medium"]
            if "size_limit_mb" in ed:
                out["sizeLimit"] = f"{ed['size_limit_mb']}Mi"
            return {"name": v["name"], "emptyDir": out}

        body = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": pod.name, "namespace": self.namespace,
                         "labels": dict(pod.labels),
                         "annotations": dict(pod.annotations)},
            "spec": {
                "restartPolicy": spec.get("restart_policy", "Never"),
                "containers": [container(c)
                               for c in spec.get("containers", [])] or
                [container({"name": "cook-job",
                            "image": "cook/default-runtime:stable"})],
            },
        }
        ps = body["spec"]
        if pod.node_name:
            ps["nodeName"] = pod.node_name
        if spec.get("init_containers"):
            ps["initContainers"] = [container(c)
                                    for c in spec["init_containers"]]
        if spec.get("volumes"):
            ps["volumes"] = [volume(v) for v in spec["volumes"]]
        if spec.get("tolerations"):
            ps["tolerations"] = [
                {k.replace("_seconds", "Seconds"): v for k, v in t.items()}
                for t in spec["tolerations"]]
        if spec.get("node_selector"):
            ps["nodeSelector"] = spec["node_selector"]
        if spec.get("priority_class"):
            ps["priorityClassName"] = spec["priority_class"]
        if spec.get("shm_size_mb"):
            body["metadata"]["annotations"]["cook/shm-size-mb"] = \
                str(spec["shm_size_mb"])
        return body

    # -------------------------------------------------------------- surface
    def nodes(self) -> List[FakeNode]:
        out = self._request("GET", "/api/v1/nodes")
        return [self._node_from_json(n) for n in out.get("items", [])]

    def pods(self) -> List[FakePod]:
        out = self._request(
            "GET", f"/api/v1/namespaces/{self.namespace}/pods")
        return [self._pod_from_json(p) for p in out.get("items", [])]

    def pod(self, name: str) -> Optional[FakePod]:
        try:
            out = self._request(
                "GET", f"/api/v1/namespaces/{self.namespace}/pods/{name}")
            return self._pod_from_json(out)
        except ApiError as e:
            if e.status == 404:
                return None
            raise

    def create_pod(self, pod: FakePod) -> None:
        try:
            self._request(
                "POST", f"/api/v1/namespaces/{self.namespace}/pods",
                body=self._pod_to_json(pod))
        except ApiError as e:
            if e.status == 409:
                raise ValueError(f"pod {pod.name} already exists") from e
            raise

    def delete_pod(self, name: str, grace_period_s: Optional[float] = None,
                   now_ms: int = 0) -> None:
        q = ""
        if grace_period_s is not None:
            q = f"?gracePeriodSeconds={int(grace_period_s)}"
        try:
            self._request(
                "DELETE",
                f"/api/v1/namespaces/{self.namespace}/pods/{name}{q}")
        except ApiError as e:
            if e.status != 404:
                raise

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._rv

    # --------------------------------------------------------------- watches
    def watch(self, callback: Callable[[WatchEvent], None],
              resource_version: int = 0) -> None:
        """Start pod+node watch threads with resourceVersion resume
        (reference: watch bootstrap + gap handling, api.clj:372-475): a
        dropped connection resumes from the last seen resourceVersion; a
        410 Gone relists and emits the fresh objects before re-watching."""
        with self._lock:
            self._watchers.append(callback)
            if self._threads:
                return
            stop = self._stop = threading.Event()
            for kind in ("pod", "node"):
                t = threading.Thread(
                    target=self._watch_loop,
                    args=(kind, resource_version, stop),
                    daemon=True, name=f"k8s-watch-{kind}")
                t.start()
                self._threads.append(t)

    def unwatch(self, callback: Callable[[WatchEvent], None]) -> None:
        with self._lock:
            if callback in self._watchers:
                self._watchers.remove(callback)
            if not self._watchers:
                # stop THIS generation only; a later watch() gets a fresh
                # event + threads, and lingering old threads are muted by
                # their generation's stop flag in _emit
                self._stop.set()
                self._threads = []

    def _emit(self, kind: str, type_: str, obj, rv: int,
              stop: Optional[threading.Event] = None) -> None:
        if stop is not None and stop.is_set():
            return  # a stale generation's thread must not double-deliver
        with self._lock:
            self._rv = max(self._rv, rv)
            watchers = list(self._watchers)
        event = WatchEvent(kind, type_, obj, rv)
        for cb in watchers:
            cb(event)

    def _list_path(self, kind: str) -> str:
        return (f"/api/v1/namespaces/{self.namespace}/pods"
                if kind == "pod" else "/api/v1/nodes")

    def _relist(self, kind: str, known: Dict[str, object],
                stop: threading.Event) -> int:
        """Watch-gap recovery: list everything, emit the live objects as
        MODIFIED (the controller's handlers are reconciling, so replayed
        state is safe) and synthesize DELETED for objects that vanished
        during the gap — a pod garbage-collected while the watch was down
        must not stay RUNNING in the store forever.  Returns the
        collection resourceVersion to resume from."""
        out = self._request("GET", self._list_path(kind))
        rv = int((out.get("metadata") or {}).get("resourceVersion") or 0)
        seen = set()
        for item in out.get("items", []):
            obj = (self._pod_from_json(item) if kind == "pod"
                   else self._node_from_json(item))
            seen.add(obj.name)
            known[obj.name] = obj
            orv = getattr(obj, "resource_version", rv) or rv
            self._emit(kind, "MODIFIED", obj, int(orv), stop)
        for name in list(known):
            if name not in seen:
                self._emit(kind, "DELETED", known.pop(name), rv, stop)
        self.watch_gap_relists += 1
        return rv

    def _watch_loop(self, kind: str, start_rv: int,
                    stop: threading.Event) -> None:
        import logging

        from ...utils.faults import injector as _faults
        from ...utils.retry import Backoff
        log = logging.getLogger(__name__)
        rv: Optional[int] = start_rv
        known: Dict[str, object] = {}  # name -> last obj (for gap deletes)
        # ONE jittered-exponential policy for every retry branch below
        # (ERROR events, HTTP errors, dropped streams, parse errors):
        # full jitter so a fleet of watchers reconnecting after one
        # apiserver restart cannot synchronize into a relist storm
        backoff = Backoff(base_s=0.1, cap_s=5.0)
        delay = 0.0
        while not stop.is_set():
            try:
                _faults.fire(
                    "k8s.watch.disconnect",
                    lambda: ConnectionError("injected watch disconnect"))
                if _faults.should_fire("k8s.watch.gone"):
                    rv = None  # injected 410: force the relist path
                if rv is None:
                    rv = self._relist(kind, known, stop)
                q = urllib.parse.urlencode(
                    {"watch": "1", "resourceVersion": str(rv),
                     "timeoutSeconds": str(int(self.watch_timeout_s))})
                url = f"{self.base_url}{self._list_path(kind)}?{q}"
                req = urllib.request.Request(url)
                token = self._bearer()
                if token:
                    req.add_header("Authorization", f"Bearer {token}")
                with urllib.request.urlopen(
                        req, timeout=self.watch_timeout_s + 5,
                        context=self._ctx) as resp:
                    for line in resp:
                        if stop.is_set():
                            return
                        line = line.strip()
                        if not line:
                            continue
                        evt = json.loads(line)
                        if evt.get("type") == "ERROR":
                            code = (evt.get("object") or {}).get("code")
                            if code == 410:  # watch gap: relist + resume
                                rv = None
                            else:
                                log.warning(
                                    "k8s %s watch ERROR event: %s",
                                    kind, evt.get("object"))
                                delay = backoff.next_delay()
                            break
                        raw = evt.get("object") or {}
                        obj = (self._pod_from_json(raw) if kind == "pod"
                               else self._node_from_json(raw))
                        orv = int((raw.get("metadata") or {})
                                  .get("resourceVersion") or 0)
                        rv = max(int(rv or 0), orv)
                        if evt.get("type") == "DELETED":
                            known.pop(obj.name, None)
                        else:
                            known[obj.name] = obj
                        self._emit(kind, evt.get("type", "MODIFIED"),
                                   obj, orv, stop)
                        backoff.reset()  # healthy stream
                        delay = 0.0
                self.watch_reconnects += 1
            except urllib.error.HTTPError as e:
                if e.code == 410:
                    rv = None
                    continue
                delay = backoff.next_delay()
                log.warning("k8s %s watch HTTP %s; retrying in %.1fs",
                            kind, e.code, delay)
            except (urllib.error.URLError, socket.timeout,
                    ConnectionError, OSError) as e:
                # dropped stream: reconnect and resume from last seen rv
                self.watch_reconnects += 1
                delay = backoff.next_delay()
                log.debug("k8s %s watch dropped (%s); resuming rv=%s",
                          kind, e, rv)
            except json.JSONDecodeError:
                delay = backoff.next_delay()
            if delay:
                stop.wait(delay)
                delay = 0.0

    # --------------------------------------------------------------- leases
    # (coordination.k8s.io/v1; the surface LeaseLeaderElector drives —
    # same contract as FakeKubernetesApi.try_acquire_lease.)
    def _lease_path(self, name: str = "") -> str:
        base = (f"/apis/coordination.k8s.io/v1/namespaces/"
                f"{self.namespace}/leases")
        return f"{base}/{name}" if name else base

    @staticmethod
    def _lease_from_json(name: str, obj: Dict) -> Lease:
        spec = obj.get("spec") or {}
        meta = obj.get("metadata") or {}
        renew = spec.get("renewTime")
        return Lease(
            name=name, holder=spec.get("holderIdentity") or "",
            holder_url=(meta.get("annotations") or {}).get(
                "cook/leader-url", ""),
            renew_time_s=(_ts_ms(renew) or 0) / 1000.0,
            duration_s=float(spec.get("leaseDurationSeconds") or 15),
            transitions=int(spec.get("leaseTransitions") or 0),
            annotations=dict(meta.get("annotations") or {}))

    def get_lease(self, name: str) -> Optional[Lease]:
        try:
            obj = self._request("GET", self._lease_path(name))
        except ApiError as e:
            if e.status == 404:
                return None
            raise
        return self._lease_from_json(name, obj)

    def try_acquire_lease(self, name: str, identity: str, now_s: float,
                          duration_s: float = 15.0,
                          holder_url: str = "") -> Optional[Lease]:
        """Apiserver-CAS acquire/renew: the object's resourceVersion makes
        the replace conditional, so two contenders cannot both win."""
        body = {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": {"name": name, "namespace": self.namespace,
                         "annotations": {"cook/leader-url": holder_url}},
            "spec": {"holderIdentity": identity,
                     "renewTime": rfc3339(now_s),
                     "leaseDurationSeconds": int(duration_s)},
        }
        try:
            cur = self._request("GET", self._lease_path(name))
        except ApiError as e:
            if e.status != 404:
                raise
            body["spec"]["leaseTransitions"] = 1
            try:
                self._request("POST", self._lease_path(), body=body)
            except ApiError as e2:
                if e2.status == 409:  # lost the create race
                    return None
                raise
            return Lease(name=name, holder=identity, holder_url=holder_url,
                         renew_time_s=now_s, duration_s=duration_s,
                         transitions=1)
        spec = cur.get("spec") or {}
        renew_s = (_ts_ms(spec.get("renewTime")) or 0) / 1000.0
        expired = now_s - renew_s > float(
            spec.get("leaseDurationSeconds") or duration_s)
        holder = spec.get("holderIdentity") or ""
        if holder and holder != identity and not expired:
            return None
        transitions = int(spec.get("leaseTransitions") or 0)
        if holder != identity:
            transitions += 1
        # preserve foreign annotations (candidate positions ride here) —
        # a renewal replacing the whole object must not wipe them
        body["metadata"]["annotations"] = {
            **((cur.get("metadata") or {}).get("annotations") or {}),
            "cook/leader-url": holder_url}
        body["metadata"]["resourceVersion"] = \
            (cur.get("metadata") or {}).get("resourceVersion")
        body["spec"]["leaseTransitions"] = transitions
        try:
            self._request("PUT", self._lease_path(name), body=body)
        except ApiError as e:
            if e.status == 409:  # CAS lost: someone renewed under us
                return None
            raise
        return Lease(name=name, holder=identity, holder_url=holder_url,
                     renew_time_s=now_s, duration_s=duration_s,
                     transitions=transitions)

    def annotate_lease(self, name: str,
                       annotations: Dict[str, Optional[str]]) -> None:
        """Merge annotations onto the lease (None deletes a key) — the
        candidate-position plane of coordinated promotion.  CAS via
        resourceVersion with a small retry budget: losing the race to a
        renewal just means re-reading and re-merging."""
        for _attempt in range(4):
            try:
                cur = self._request("GET", self._lease_path(name))
            except ApiError as e:
                if e.status != 404:
                    raise
                cur = {"apiVersion": "coordination.k8s.io/v1",
                       "kind": "Lease",
                       "metadata": {"name": name,
                                    "namespace": self.namespace},
                       "spec": {}}
            meta = cur.setdefault("metadata", {})
            merged = dict(meta.get("annotations") or {})
            for k, v in annotations.items():
                if v is None:
                    merged.pop(k, None)
                else:
                    merged[k] = str(v)
            meta["annotations"] = merged
            create = not meta.get("resourceVersion")
            try:
                if create:
                    self._request("POST", self._lease_path(), body=cur)
                else:
                    self._request("PUT", self._lease_path(name), body=cur)
                return
            except ApiError as e:
                if e.status != 409:  # CAS/create race: re-read, re-merge
                    raise
        # one-shot callers (clear_candidate, the promotion-time final
        # position) must not believe a dropped update was applied
        raise ApiError(409, f"lease {name} annotation update lost the "
                            "CAS race 4 times; retry")

    def release_lease(self, name: str, identity: str) -> None:
        """Explicit release on clean shutdown: clear holderIdentity so a
        standby acquires immediately instead of waiting out the TTL."""
        try:
            cur = self._request("GET", self._lease_path(name))
        except ApiError as e:
            if e.status == 404:
                return
            raise
        spec = cur.get("spec") or {}
        if (spec.get("holderIdentity") or "") != identity:
            return  # someone else holds it now; not ours to clear
        spec["holderIdentity"] = ""
        spec["renewTime"] = None
        meta = cur.setdefault("metadata", {})
        if meta.get("annotations"):
            meta["annotations"]["cook/leader-url"] = ""
        try:
            self._request("PUT", self._lease_path(name), body=cur)
        except ApiError as e:
            if e.status != 409:  # CAS lost: a competitor already took it
                raise
