"""Real Kubernetes client adapter.

Implements the same surface as :class:`fake_api.FakeKubernetesApi`
(nodes/pods/pod/create_pod/delete_pod/watch/unwatch/resource_version) on
top of the official ``kubernetes`` Python client, so
:class:`compute_cluster.KubernetesCluster` and :class:`controller.PodController`
run unchanged against a live cluster (reference: the okhttp watch +
client-java layer, scheduler/src/cook/kubernetes/api.clj:372-734, with
resourceVersion resume and watch-gap handling).

The ``kubernetes`` package is not part of this image, so the import is
gated: constructing the adapter without it raises a clear error, and
``tests/test_k8s.py`` asserts interface parity with the fake via
introspection instead of a live cluster.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from .fake_api import FakeNode, FakePod, WatchEvent

COOK_NS = "cook"


def _require_client():
    try:
        import kubernetes  # type: ignore
        return kubernetes
    except ImportError as e:  # pragma: no cover - package absent in image
        raise RuntimeError(
            "RealKubernetesApi needs the 'kubernetes' package; in this "
            "image use FakeKubernetesApi (same interface)") from e


class RealKubernetesApi:
    """Live-cluster twin of FakeKubernetesApi.

    Pods/nodes are translated into the same Fake* dataclasses the
    controller consumes; the rich ``spec`` dict produced by
    pod_spec.build_pod_spec is translated 1:1 into V1Pod fields.
    """

    def __init__(self, namespace: str = COOK_NS, kubeconfig: Optional[str] = None):
        k8s = _require_client()
        if kubeconfig:
            k8s.config.load_kube_config(config_file=kubeconfig)
        else:  # pragma: no cover
            k8s.config.load_incluster_config()
        self._k8s = k8s
        self._core = k8s.client.CoreV1Api()
        self.namespace = namespace
        self._rv = 0
        self._watchers: List[Callable[[WatchEvent], None]] = []
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------ translate
    @staticmethod
    def _node_from_v1(n) -> FakeNode:
        alloc = n.status.allocatable or {}

        def qty(key, default=0.0):
            v = alloc.get(key)
            if v is None:
                return default
            s = str(v)
            if s.endswith("Ki"):
                return float(s[:-2]) / 1024.0  # -> MiB
            if s.endswith("Mi"):
                return float(s[:-2])
            if s.endswith("m"):
                return float(s[:-1]) / 1000.0
            return float(s)

        labels = n.metadata.labels or {}
        return FakeNode(
            name=n.metadata.name,
            cpus=qty("cpu"), mem=qty("memory"),
            gpus=qty("nvidia.com/gpu"),
            pool=labels.get("cook-pool", "default"),
            labels=dict(labels),
            taints=[t.key for t in (n.spec.taints or [])],
            unschedulable=bool(n.spec.unschedulable),
            gpu_model=labels.get("gpu-model", ""))

    @staticmethod
    def _pod_from_v1(p) -> FakePod:
        labels = p.metadata.labels or {}
        status = p.status
        exit_code = None
        reason = status.reason or ""
        unschedulable = ""
        for cond in (status.conditions or []):
            if cond.type == "PodScheduled" and cond.status == "False":
                unschedulable = cond.message or cond.reason or "Unschedulable"
        for cs in (status.container_statuses or []):
            term = cs.state and cs.state.terminated
            if term is not None and cs.name == "cook-job":
                exit_code = term.exit_code
                reason = reason or (term.reason or "")
        req = {}
        if p.spec.containers:
            req = p.spec.containers[0].resources.requests or {}

        def qty(key):
            v = req.get(key)
            if v is None:
                return 0.0
            s = str(v)
            if s.endswith("Mi"):
                return float(s[:-2])
            if s.endswith("m"):
                return float(s[:-1]) / 1000.0
            return float(s)

        created = p.metadata.creation_timestamp
        deleted_at = p.metadata.deletion_timestamp
        return FakePod(
            name=p.metadata.name,
            node_name=p.spec.node_name,
            phase=status.phase or "Pending",
            cpus=qty("cpu"), mem=qty("memory"), gpus=qty("nvidia.com/gpu"),
            labels=dict(labels),
            annotations=dict(p.metadata.annotations or {}),
            deleted=deleted_at is not None,
            deletion_ms=int(deleted_at.timestamp() * 1000) if deleted_at else None,
            creation_ms=int(created.timestamp() * 1000) if created else 0,
            exit_code=exit_code,
            reason=reason,
            unschedulable_reason=unschedulable,
            synthetic=labels.get("cook/synthetic") == "true",
            resource_version=int(p.metadata.resource_version or 0))

    def _pod_to_v1(self, pod: FakePod):
        k8s = self._k8s
        spec = pod.spec or {}

        def container(c):
            return k8s.client.V1Container(
                name=c["name"], image=c["image"],
                command=c.get("command"),
                env=[k8s.client.V1EnvVar(name=e["name"], value=e["value"])
                     for e in c.get("env", [])],
                working_dir=c.get("working_dir"),
                volume_mounts=[k8s.client.V1VolumeMount(
                    name=m["name"], mount_path=m["mount_path"],
                    read_only=m.get("read_only", False),
                    sub_path=m.get("sub_path"))
                    for m in c.get("volume_mounts", [])],
                resources=k8s.client.V1ResourceRequirements(
                    requests={"cpu": str(pod.cpus),
                              "memory": f"{int(pod.mem)}Mi",
                              **({"nvidia.com/gpu": str(int(pod.gpus))}
                                 if pod.gpus else {})}))

        def volume(v):
            if "host_path" in v:
                return k8s.client.V1Volume(
                    name=v["name"],
                    host_path=k8s.client.V1HostPathVolumeSource(
                        path=v["host_path"]))
            ed = v.get("empty_dir", {})
            return k8s.client.V1Volume(
                name=v["name"],
                empty_dir=k8s.client.V1EmptyDirVolumeSource(
                    medium=ed.get("medium"),
                    size_limit=(f"{ed['size_limit_mb']}Mi"
                                if "size_limit_mb" in ed else None)))

        return k8s.client.V1Pod(
            metadata=k8s.client.V1ObjectMeta(
                name=pod.name, namespace=self.namespace,
                labels=pod.labels, annotations=pod.annotations),
            spec=k8s.client.V1PodSpec(
                restart_policy=spec.get("restart_policy", "Never"),
                node_name=pod.node_name,
                containers=[container(c)
                            for c in spec.get("containers", [])] or
                [container({"name": "cook-job",
                            "image": "cook/default-runtime:stable"})],
                init_containers=[container(c)
                                 for c in spec.get("init_containers", [])],
                volumes=[volume(v) for v in spec.get("volumes", [])],
                tolerations=[k8s.client.V1Toleration(**t)
                             for t in spec.get("tolerations", [])],
                node_selector=spec.get("node_selector") or None,
                priority_class_name=spec.get("priority_class")))

    # -------------------------------------------------------------- surface
    def nodes(self) -> List[FakeNode]:
        return [self._node_from_v1(n)
                for n in self._core.list_node().items]

    def pods(self) -> List[FakePod]:
        return [self._pod_from_v1(p) for p in
                self._core.list_namespaced_pod(self.namespace).items]

    def pod(self, name: str) -> Optional[FakePod]:
        try:
            return self._pod_from_v1(
                self._core.read_namespaced_pod(name, self.namespace))
        except self._k8s.client.exceptions.ApiException as e:
            if e.status == 404:
                return None
            raise

    def create_pod(self, pod: FakePod) -> None:
        try:
            self._core.create_namespaced_pod(self.namespace,
                                             self._pod_to_v1(pod))
        except self._k8s.client.exceptions.ApiException as e:
            if e.status == 409:
                raise ValueError(f"pod {pod.name} already exists") from e
            raise

    def delete_pod(self, name: str, grace_period_s: Optional[float] = None,
                   now_ms: int = 0) -> None:
        try:
            self._core.delete_namespaced_pod(
                name, self.namespace,
                grace_period_seconds=(int(grace_period_s)
                                      if grace_period_s is not None else None))
        except self._k8s.client.exceptions.ApiException as e:
            if e.status != 404:
                raise

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._rv

    # --------------------------------------------------------------- watches
    def watch(self, callback: Callable[[WatchEvent], None],
              resource_version: int = 0) -> None:
        """Start pod+node watch threads with resourceVersion resume
        (reference: the watch bootstrap + gap handling,
        kubernetes/api.clj:372-475). 410 Gone restarts from a fresh list."""
        with self._lock:
            self._watchers.append(callback)
            if self._threads:
                return
            for kind in ("pod", "node"):
                t = threading.Thread(target=self._watch_loop, args=(kind,),
                                     daemon=True, name=f"k8s-watch-{kind}")
                t.start()
                self._threads.append(t)

    def unwatch(self, callback: Callable[[WatchEvent], None]) -> None:
        with self._lock:
            if callback in self._watchers:
                self._watchers.remove(callback)
            if not self._watchers:
                self._stop.set()

    def _watch_loop(self, kind: str) -> None:  # pragma: no cover - live only
        k8s = self._k8s
        w = k8s.watch.Watch()
        rv = None
        while not self._stop.is_set():
            try:
                if kind == "pod":
                    stream = w.stream(self._core.list_namespaced_pod,
                                      self.namespace, resource_version=rv,
                                      timeout_seconds=60)
                else:
                    stream = w.stream(self._core.list_node,
                                      resource_version=rv,
                                      timeout_seconds=60)
                for raw in stream:
                    if self._stop.is_set():
                        return
                    obj = (self._pod_from_v1(raw["object"]) if kind == "pod"
                           else self._node_from_v1(raw["object"]))
                    rv = raw["object"].metadata.resource_version
                    with self._lock:
                        self._rv = max(self._rv, int(rv or 0))
                        watchers = list(self._watchers)
                    event = WatchEvent(kind, raw["type"], obj,
                                       int(rv or 0))
                    for cb in watchers:
                        cb(event)
            except k8s.client.exceptions.ApiException as e:
                if e.status == 410:  # watch gap: resync from a fresh list
                    rv = None
                    continue
                raise

    # --------------------------------------------------------------- leases
    # (coordination.k8s.io/v1; the lease surface LeaseLeaderElector drives.
    # Same contract as FakeKubernetesApi.try_acquire_lease.)
    def get_lease(self, name: str):  # pragma: no cover - live only
        from .types import Lease
        k8s = self._k8s
        coord = k8s.client.CoordinationV1Api()
        try:
            lease = coord.read_namespaced_lease(name, self.namespace)
        except k8s.client.exceptions.ApiException as e:
            if e.status == 404:
                return None
            raise
        spec = lease.spec
        renew = spec.renew_time.timestamp() if spec.renew_time else 0.0
        return Lease(
            name=name, holder=spec.holder_identity or "",
            holder_url=(lease.metadata.annotations or {}).get(
                "cook/leader-url", ""),
            renew_time_s=renew,
            duration_s=float(spec.lease_duration_seconds or 15),
            transitions=int(spec.lease_transitions or 0))

    def try_acquire_lease(self, name: str, identity: str, now_s: float,
                          duration_s: float = 15.0, holder_url: str = ""
                          ):  # pragma: no cover - live only
        """Apiserver-CAS acquire/renew: the object's resourceVersion makes
        the replace conditional, so two contenders cannot both win."""
        import datetime

        from .types import Lease
        k8s = self._k8s
        coord = k8s.client.CoordinationV1Api()
        now = datetime.datetime.now(datetime.timezone.utc)
        body = k8s.client.V1Lease(
            metadata=k8s.client.V1ObjectMeta(
                name=name, namespace=self.namespace,
                annotations={"cook/leader-url": holder_url}),
            spec=k8s.client.V1LeaseSpec(
                holder_identity=identity, renew_time=now,
                lease_duration_seconds=int(duration_s)))
        try:
            cur = coord.read_namespaced_lease(name, self.namespace)
        except k8s.client.exceptions.ApiException as e:
            if e.status != 404:
                raise
            try:
                body.spec.lease_transitions = 1
                coord.create_namespaced_lease(self.namespace, body)
                return Lease(name=name, holder=identity,
                                 holder_url=holder_url,
                                 renew_time_s=now.timestamp(),
                                 duration_s=duration_s, transitions=1)
            except k8s.client.exceptions.ApiException as e2:
                if e2.status == 409:  # lost the create race
                    return None
                raise
        spec = cur.spec
        renew = spec.renew_time.timestamp() if spec.renew_time else 0.0
        expired = now.timestamp() - renew > float(
            spec.lease_duration_seconds or duration_s)
        if (spec.holder_identity and spec.holder_identity != identity
                and not expired):
            return None
        transitions = int(spec.lease_transitions or 0)
        if spec.holder_identity != identity:
            transitions += 1
        body.metadata.resource_version = cur.metadata.resource_version
        body.spec.lease_transitions = transitions
        try:
            coord.replace_namespaced_lease(name, self.namespace, body)
        except k8s.client.exceptions.ApiException as e:
            if e.status == 409:  # CAS lost: someone renewed under us
                return None
            raise
        return Lease(name=name, holder=identity, holder_url=holder_url,
                         renew_time_s=now.timestamp(),
                         duration_s=duration_s, transitions=transitions)

    def release_lease(self, name: str, identity: str
                      ) -> None:  # pragma: no cover - live only
        """Explicit release on clean shutdown: clear holderIdentity so a
        standby acquires immediately instead of waiting out the TTL."""
        k8s = self._k8s
        coord = k8s.client.CoordinationV1Api()
        try:
            cur = coord.read_namespaced_lease(name, self.namespace)
        except k8s.client.exceptions.ApiException as e:
            if e.status == 404:
                return
            raise
        if (cur.spec.holder_identity or "") != identity:
            return  # someone else holds it now; not ours to clear
        cur.spec.holder_identity = ""
        cur.spec.renew_time = None
        if cur.metadata.annotations:
            cur.metadata.annotations["cook/leader-url"] = ""
        try:
            coord.replace_namespaced_lease(name, self.namespace, cur)
        except k8s.client.exceptions.ApiException as e:
            if e.status != 409:  # CAS lost: a competitor already took it
                raise
