"""Kubernetes-style compute cluster backend.

Mirrors the reference's KubernetesComputeCluster (reference:
scheduler/src/cook/kubernetes/compute_cluster.clj:410-741):

 - offers are *synthesized* from watch state: per node, capacity minus the
   consumption of live pods (generate-offers :68-174, get-capacity/
   get-consumption api.clj:874-927);
 - launch builds a pod and feeds the controller (launch-task! :319-347);
 - startup reconstructs expected state from the store union live pods
   (determine-cook-expected-state-on-startup :253-288);
 - autoscaling launches placeholder "synthetic pods" sized like unmatched
   jobs so a cluster autoscaler provisions nodes (autoscale! :590-715);
 - max_launchable gives direct-mode backpressure from node/pod headroom
   (:555-588).

Works against any object with the FakeKubernetesApi surface; a real
kubernetes client adapter can implement the same interface.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ...state.schema import InstanceStatus, Job, Resources
from ...state.store import Store
from ..base import ComputeCluster, LaunchSpec, Offer
from .controller import CookExpected, PodController, synthesize_pod_state
from .fake_api import FakeKubernetesApi, FakeNode, FakePod

SYNTHETIC_PREFIX = "synthetic-"


class KubernetesCluster(ComputeCluster):
    def __init__(self, name: str, api: Optional[FakeKubernetesApi] = None,
                 store: Optional[Store] = None,
                 max_total_pods: int = 10_000,
                 max_pods_per_node: int = 32,
                 synthetic_pod_ttl_ms: int = 120_000,
                 stuck_pod_timeout_ms: int = 300_000,
                 node_blocklist_labels: Optional[List[str]] = None,
                 incremental=None,
                 rest_url: str = "",
                 disallowed_container_paths: Optional[List[str]] = None,
                 disallowed_var_names: Optional[List[str]] = None):
        super().__init__(name)
        self.api = api or FakeKubernetesApi()
        self.store = store
        self.max_total_pods = max_total_pods
        self.max_pods_per_node = max_pods_per_node
        self.stuck_pod_timeout_ms = stuck_pod_timeout_ms
        # nodes carrying any of these label KEYS take no cook work
        # (reference: node-blocklist-labels in node-schedulable?,
        # kubernetes/api.clj:782)
        self.node_blocklist_labels = list(node_blocklist_labels or [])
        self.incremental = incremental
        # advertised to tasks as COOK_SCHEDULER_REST_URL
        # (reference: kubernetes/api.clj:1440)
        self.rest_url = rest_url
        # volumes/env another cluster component owns, dropped at pod
        # compile (reference: config :kubernetes
        # :disallowed-container-paths / :disallowed-var-names)
        self.disallowed_container_paths = set(
            disallowed_container_paths or [])
        self.disallowed_var_names = set(disallowed_var_names or [])
        self._watch_registered = False
        clock = (lambda: store.clock()) if store is not None else (lambda: 0)
        self.controller = PodController(
            self.api,
            on_pod_started=self._pod_started,
            on_pod_completed=self._pod_completed,
            on_pod_killed=self._pod_killed,
            on_pod_preempted=self._pod_preempted,
            managed_filter=lambda pod: self._cook_managed(pod),
            clock=clock)

    # ------------------------------------------------------------- lifecycle
    def initialize(self, status_callback) -> None:
        super().initialize(status_callback)
        if self.store is not None:
            self._reconcile_startup()
        if not self._watch_registered:
            self.api.watch(self._on_watch_event)
            self._watch_registered = True

    def shutdown(self) -> None:
        """Detach from the api (leader handoff: the dying leader must stop
        reacting before the new one adopts the pods)."""
        if self._watch_registered:
            self.api.unwatch(self._on_watch_event)
            self._watch_registered = False

    def _reconcile_startup(self) -> None:
        """Expected state = store's live instances for this cluster, union
        live pods (reference: compute_cluster.clj:253-288)."""
        expected_live = set()
        for _job, inst in self.store.running_instances():
            if inst.compute_cluster == self.name:
                expected_live.add(inst.task_id)
                self.controller.set_expected(
                    inst.task_id,
                    CookExpected.STARTING
                    if inst.status is InstanceStatus.UNKNOWN
                    else CookExpected.RUNNING)
        for pod in self.api.pods():
            if not self._cook_managed(pod):
                continue
            if pod.name not in expected_live:
                # live pod with no live instance: the controller's
                # (MISSING, live) arm will clean it up
                self.controller.set_expected(pod.name, CookExpected.MISSING)
        self.controller.scan_all()

    @staticmethod
    def _cook_managed(pod: FakePod) -> bool:
        """Only pods we launched are controller-managed; foreign pods on
        shared nodes consume capacity but are never touched (the reference
        scopes by namespace/naming, kubernetes/api.clj pod<->job naming)."""
        return (not pod.synthetic) and "cook/job" in pod.labels

    def _on_watch_event(self, event) -> None:
        if event.kind == "pod" and self._cook_managed(event.obj):
            if event.type == "DELETED":
                self.controller.pod_deleted(event.obj.name)
            else:
                self.controller.pod_update(event.obj.name)

    # ------------------------------------------------------------ writebacks
    def _pod_started(self, pod_name: str) -> None:
        pod = self.api.pod(pod_name)
        if self._status_callback:
            self._status_callback(pod_name, InstanceStatus.RUNNING, None,
                                  hostname=pod.node_name if pod else None)

    def _pod_completed(self, pod_name: str, exit_code: Optional[int],
                       reason_code: Optional[int]) -> None:
        ok = (exit_code or 0) == 0 and reason_code is None
        if self._status_callback:
            self._status_callback(
                pod_name,
                InstanceStatus.SUCCESS if ok else InstanceStatus.FAILED,
                reason_code, exit_code=exit_code)

    def _pod_killed(self, pod_name: str, reason_code: int) -> None:
        if self._status_callback:
            from ...state.schema import Reasons
            preempted = reason_code == Reasons.PREEMPTED_BY_REBALANCER.code
            self._status_callback(pod_name, InstanceStatus.FAILED,
                                  reason_code, preempted=preempted)

    def _pod_preempted(self, pod_name: str) -> None:
        """Pod regressed running->waiting (node preemption): mea-culpa
        failure so the retry is free (reference: handle-pod-preemption,
        controller.clj)."""
        if self._status_callback:
            from ...state.schema import Reasons
            self._status_callback(pod_name, InstanceStatus.FAILED,
                                  Reasons.PREEMPTED_BY_POOL.code,
                                  preempted=True)

    # --------------------------------------------------------------- offers
    def pending_offers(self, pool: str) -> List[Offer]:
        consumption: Dict[str, List[float]] = {}
        counts: Dict[str, int] = {}
        for pod in self.api.pods():
            if pod.node_name and pod.phase in ("Pending", "Running"):
                u = consumption.setdefault(pod.node_name, [0.0, 0.0, 0.0])
                u[0] += pod.cpus
                u[1] += pod.mem
                u[2] += pod.gpus
                counts[pod.node_name] = counts.get(pod.node_name, 0) + 1
        offers = []
        for node in self.api.nodes():
            if node.pool != pool or node.unschedulable or node.taints:
                continue
            if any(k in node.labels for k in self.node_blocklist_labels):
                continue
            used = consumption.get(node.name, [0.0, 0.0, 0.0])
            avail = Resources(cpus=max(0.0, node.cpus - used[0]),
                              mem=max(0.0, node.mem - used[1]),
                              gpus=max(0.0, node.gpus - used[2]))
            offers.append(Offer(
                id=f"{self.name}/{node.name}/{self.api.resource_version}",
                hostname=node.name, slave_id=node.name, pool=pool,
                cluster=self.name,
                available=avail,
                capacity=Resources(cpus=node.cpus, mem=node.mem,
                                   gpus=node.gpus),
                attributes=dict(node.labels),
                task_count=counts.get(node.name, 0),
                gpu_model=node.gpu_model))
        return offers

    def hosts(self, pool: str) -> List[Offer]:
        return self.pending_offers(pool)

    # --------------------------------------------------------------- launch
    def launch_tasks(self, pool: str, specs: List[LaunchSpec]) -> None:
        from ...state.schema import Reasons
        from .pod_spec import build_pod_spec
        for spec in specs:
            job = self.store.job(spec.job_uuid) if self.store else None
            pod = FakePod(
                name=spec.task_id,
                node_name=spec.hostname or None,  # direct mode: unscheduled
                cpus=spec.resources.cpus, mem=spec.resources.mem,
                gpus=spec.resources.gpus,
                creation_ms=(self.store.clock() if self.store else 0),
                labels={"cook/job": spec.job_uuid, "cook/pool": pool},
                spec=(build_pod_spec(
                    job, pool, incremental=self.incremental,
                    task_id=spec.task_id, rest_url=self.rest_url,
                    disallowed_container_paths=(
                        self.disallowed_container_paths),
                    disallowed_var_names=self.disallowed_var_names)
                      if job is not None else {}))
            if not self.controller.launch_pod(pod):
                if self._status_callback:
                    self._status_callback(
                        spec.task_id, InstanceStatus.FAILED,
                        Reasons.REASON_POD_SUBMISSION_FAILED.code)

    def kill_task(self, task_id: str) -> None:
        self.controller.kill_pod(task_id)

    # ---------------------------------------------------- direct-mode limits
    def max_launchable(self, pool: str) -> int:
        """Headroom = min(total pod cap, per-node pod slots) (reference:
        kubernetes/compute_cluster.clj:555-588)."""
        pods = [p for p in self.api.pods() if not p.synthetic]
        total_headroom = self.max_total_pods - len(pods)
        node_headroom = 0
        per_node: Dict[str, int] = {}
        for p in pods:
            if p.node_name:
                per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
        for node in self.api.nodes():
            if node.pool != pool or node.unschedulable:
                continue
            if any(k in node.labels for k in self.node_blocklist_labels):
                continue  # consistent with pending_offers: no offers ->
                # no launchable headroom either
            node_headroom += max(
                0, self.max_pods_per_node - per_node.get(node.name, 0))
        return max(0, min(total_headroom, node_headroom))

    # ------------------------------------------------------------ autoscaling
    def autoscale(self, pool: str, unmatched_jobs: List[Job],
                  now_ms: int = 0,
                  gangs: Optional[Dict[str, Dict]] = None) -> int:
        """Launch placeholder synthetic pods sized like unmatched jobs so a
        cluster autoscaler sees unsatisfied demand and provisions nodes
        (reference: autoscale! kubernetes/compute_cluster.clj:590-715,
        trigger-autoscaling! scheduler.clj:1178). Returns pods created.

        ``gangs`` (group uuid -> {"size", "topology"}) sizes gang demand
        as whole-slice pod SETS: the gang's placeholders are created
        all-or-none within the pod budget and carry a co-location
        affinity label/annotation so the cluster autoscaler provisions a
        contiguous slice instead of scattered singles (docs/GANG.md)."""
        gangs = gangs or {}
        budget = max(0, self.max_total_pods - len(self.api.pods()))
        created = 0
        # gang members grouped so a set never splits across the budget
        units: List[List[Job]] = []
        cohorts: Dict[str, List[Job]] = {}
        for job in unmatched_jobs:
            if job.group and job.group in gangs:
                cohort = cohorts.get(job.group)
                if cohort is None:
                    cohort = cohorts[job.group] = []
                    units.append(cohort)
                cohort.append(job)
            else:
                units.append([job])
        for unit in units:
            if budget <= 0:
                # nothing more can be created — skip the per-job pod
                # lookups (real API reads) the missing-filter would do
                break
            # budget the MISSING placeholders only: members whose pods
            # survived a previous cycle are free, and counting them
            # would wrongly skip a nearly-provisioned gang at the cap
            missing = [job for job in unit
                       if self.api.pod(f"{SYNTHETIC_PREFIX}{job.uuid}")
                       is None]
            if not missing or len(missing) > budget:
                continue  # a split gang set would under-provision the slice
            guuid = unit[0].group if unit[0].group in gangs else None
            made: List[str] = []
            for job in missing:
                name = f"{SYNTHETIC_PREFIX}{job.uuid}"
                labels = {"cook/synthetic": "true", "cook/job": job.uuid}
                annotations = {"cook/created-ms": str(now_ms)}
                if guuid:
                    labels["cook/gang"] = guuid
                    annotations["cook/gang-size"] = \
                        str(gangs[guuid].get("size") or len(unit))
                    topo = gangs[guuid].get("topology")
                    if topo:
                        # co-location affinity hint for the autoscaler /
                        # kube-scheduler: members want one topology domain
                        annotations["cook/gang-affinity"] = topo
                try:
                    self.api.create_pod(FakePod(
                        name=name, cpus=job.resources.cpus,
                        mem=job.resources.mem, gpus=job.resources.gpus,
                        synthetic=True,
                        labels=labels, annotations=annotations))
                    made.append(name)
                    created += 1
                    budget -= 1
                except ValueError:
                    if guuid:
                        # the set is all-or-none: roll back this gang's
                        # fresh placeholders rather than leave a partial
                        # slice signal for the autoscaler
                        for n in made:
                            try:
                                self.api.delete_pod(n)
                            except Exception:
                                pass
                        created -= len(made)
                        budget += len(made)
                        break
                    continue
        return created

    def synthetic_pods_for(self, job_uuids: List[str]) -> List[str]:
        """Which of these jobs already have a live placeholder here.
        The scheduler's autoscale routing uses this to tell "at the pod
        cap" (fall through with the uncovered jobs) apart from "already
        provisioned" (stay put) when autoscale() creates nothing —
        autoscale()'s own missing-filter reads the same pods, so this
        is the established per-cycle read pattern, not a new one."""
        return [u for u in job_uuids
                if self.api.pod(f"{SYNTHETIC_PREFIX}{u}") is not None]

    def detect_stuck_pods(self, now_ms: Optional[int] = None) -> List[str]:
        """Stuck/unschedulable pod detection (reference:
        kubernetes/api.clj:1820-1846): a cook-managed pod Pending past the
        timeout, or one the kube-scheduler marked unschedulable, is killed
        with a mea-culpa POD_STUCK failure (free retry elsewhere)."""
        from ...state.schema import Reasons
        if now_ms is None:
            now_ms = self.store.clock() if self.store else 0
        stuck: List[str] = []
        for pod in self.api.pods():
            if not self._cook_managed(pod) or pod.deleted:
                continue
            if pod.phase != "Pending":
                continue
            unschedulable = bool(pod.unschedulable_reason)
            timed_out = (now_ms - pod.creation_ms) > self.stuck_pod_timeout_ms
            if not (unschedulable or timed_out):
                continue
            stuck.append(pod.name)
            why = (f"unschedulable: {pod.unschedulable_reason}"
                   if unschedulable else
                   f"pending for {now_ms - pod.creation_ms}ms")
            # writeback first, then the kubernetes delete (restart safety)
            if self._status_callback:
                self._status_callback(pod.name, InstanceStatus.FAILED,
                                      Reasons.POD_STUCK.code)
            self.controller.set_expected(pod.name, CookExpected.COMPLETED)
            self.api.delete_pod(pod.name)
            self.controller.pod_update(pod.name)
            import logging
            logging.getLogger(__name__).warning(
                "reaped stuck pod %s (%s)", pod.name, why)
        return stuck

    def reap_synthetic_pods(self, launched_job_uuids: List[str]) -> int:
        """Delete placeholders whose jobs launched for real."""
        reaped = 0
        launched = set(launched_job_uuids)
        for pod in self.api.pods():
            if pod.synthetic and pod.labels.get("cook/job") in launched:
                self.api.delete_pod(pod.name)
                reaped += 1
        return reaped


def factory(store=None, name: str = "k8s", api_url: str = "",
            **kwargs) -> KubernetesCluster:
    """Config-file / dynamic-creation entry point (the analog of
    fake.factory / remote.factory; reference: the factory-fn template,
    compute_cluster.clj:483-497).  ``api_url`` selects the stdlib-HTTP
    RealKubernetesApi; empty keeps the in-process fake (tests,
    simulation)."""
    api = None
    if api_url:
        from .real_api import RealKubernetesApi
        api = RealKubernetesApi(base_url=api_url)
    return KubernetesCluster(name, api, store=store, **kwargs)
