"""Dual-state-machine pod controller.

The heart of the k8s backend (reference:
scheduler/src/cook/kubernetes/controller.clj:482-711): reconciles the cross
product of

  cook-expected-state in {STARTING, RUNNING, COMPLETED, KILLED, MISSING}
  pod-synthesized-state in {WAITING, RUNNING, SUCCEEDED, FAILED, UNKNOWN, MISSING}

preserving the reference's invariants:
  * store writeback happens FIRST, then kubernetes actions (restart safety);
  * pods are deleted from kubernetes only in terminal pod states;
  * a live pod in an unexpected ("weird") state is killed by deleting it and
    the failure is marked mea-culpa;
  * per-pod processing is serialized through sharded locks
    (controller.clj:22-51 — here the sharded ordered executor).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from ...state.schema import InstanceStatus, Reasons
from .fake_api import FakePod


class CookExpected(enum.Enum):
    STARTING = "starting"
    RUNNING = "running"
    COMPLETED = "completed"
    KILLED = "killed"
    MISSING = "missing"


class PodState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    UNKNOWN = "unknown"
    MISSING = "missing"


TERMINAL_POD_STATES = (PodState.SUCCEEDED, PodState.FAILED,
                       PodState.UNKNOWN, PodState.MISSING)


def synthesize_pod_state(pod: Optional[FakePod]) -> PodState:
    """pod object -> synthesized state (reference:
    pod->synthesized-pod-state kubernetes/api.clj:1916)."""
    if pod is None:
        return PodState.MISSING
    if pod.phase == "Pending":
        return PodState.WAITING
    if pod.phase == "Running":
        return PodState.RUNNING
    if pod.phase == "Succeeded":
        return PodState.SUCCEEDED
    if pod.phase == "Failed":
        return PodState.FAILED
    return PodState.UNKNOWN


@dataclass
class ExpectedStateEntry:
    state: CookExpected
    # why a kill happened / weird-state provenance, for passport/debug
    reason: str = ""


class PodController:
    """Reconciler over (expected, actual) per pod name.

    Writebacks to the store go through the callbacks; kubernetes actions go
    through the api client (create/delete pod).
    """

    def __init__(self, api, *,
                 on_pod_started: Callable[[str], None],
                 on_pod_completed: Callable[[str, Optional[int], Optional[int]], None],
                 on_pod_killed: Callable[[str, int], None],
                 managed_filter: Optional[Callable] = None,
                 logger=None):
        self.api = api
        self.managed_filter = managed_filter or (lambda pod: True)
        self.expected: Dict[str, ExpectedStateEntry] = {}
        self._lock = threading.RLock()
        self.on_pod_started = on_pod_started
        self.on_pod_completed = on_pod_completed
        self.on_pod_killed = on_pod_killed
        import logging
        self.log = logger or logging.getLogger(__name__)

    # ------------------------------------------------------------ lifecycle
    def launch_pod(self, pod: FakePod) -> bool:
        """Expected -> STARTING and create in kubernetes."""
        with self._lock:
            self.expected[pod.name] = ExpectedStateEntry(CookExpected.STARTING)
            try:
                self.api.create_pod(pod)
                return True
            except ValueError:
                # name collision: treat as submission failure
                self.expected.pop(pod.name, None)
                return False

    def kill_pod(self, pod_name: str, reason: str = "killed") -> None:
        """Cook-level kill (user kill / preemption): expected -> KILLED, then
        reconcile (which deletes the pod)."""
        with self._lock:
            entry = self.expected.get(pod_name)
            if entry is None or entry.state in (CookExpected.COMPLETED,
                                                CookExpected.MISSING):
                return
            self.expected[pod_name] = ExpectedStateEntry(
                CookExpected.KILLED, reason)
        self.process(pod_name)

    def set_expected(self, pod_name: str, state: CookExpected) -> None:
        """Startup reconciliation hook."""
        with self._lock:
            self.expected[pod_name] = ExpectedStateEntry(state)

    # ---------------------------------------------------------------- events
    def pod_update(self, pod_name: str) -> None:
        self.process(pod_name)

    def pod_deleted(self, pod_name: str) -> None:
        self.process(pod_name)

    def scan_all(self) -> None:
        """Periodic full reconciliation (reference: scan-process
        controller.clj:815): every tracked or live pod gets visited."""
        with self._lock:
            names = set(self.expected.keys())
        names.update(p.name for p in self.api.pods()
                     if self.managed_filter(p))
        for name in names:
            self.process(name)

    # ------------------------------------------------------------------ core
    def process(self, pod_name: str) -> None:
        """One reconciliation visit (reference: process controller.clj:482).
        Runs under the per-pod lock; loops until the state is stable."""
        with self._lock:
            for _ in range(4):  # states converge in <= a few hops
                entry = self.expected.get(pod_name)
                expected = entry.state if entry else CookExpected.MISSING
                pod = self.api.pod(pod_name)
                actual = synthesize_pod_state(pod)
                new_expected = self._step(pod_name, expected, actual, pod,
                                          entry)
                if new_expected is None:
                    self.expected.pop(pod_name, None)
                    if expected is CookExpected.MISSING:
                        return
                elif new_expected is not expected:
                    self.expected[pod_name] = ExpectedStateEntry(
                        new_expected, entry.reason if entry else "")
                else:
                    return  # stable

    # The 30-state table. Returns the new expected state (None = forget).
    def _step(self, pod_name: str, expected: CookExpected, actual: PodState,
              pod: Optional[FakePod], entry: Optional[ExpectedStateEntry]
              ) -> Optional[CookExpected]:
        E, A = CookExpected, PodState

        if expected is E.STARTING:
            if actual in (A.WAITING, A.MISSING):
                return E.STARTING  # pod creation/scheduling in progress
            if actual is A.RUNNING:
                self.on_pod_started(pod_name)
                return E.RUNNING
            if actual is A.SUCCEEDED:
                self.on_pod_started(pod_name)  # never observed running
                self.on_pod_completed(pod_name, pod.exit_code, None)
                return E.COMPLETED
            if actual in (A.FAILED, A.UNKNOWN):
                self.on_pod_completed(
                    pod_name, pod.exit_code if pod else None,
                    self._failure_reason(pod))
                return E.COMPLETED

        elif expected is E.RUNNING:
            if actual is A.RUNNING:
                return E.RUNNING
            if actual is A.SUCCEEDED:
                self.on_pod_completed(pod_name, pod.exit_code, None)
                return E.COMPLETED
            if actual in (A.FAILED, A.UNKNOWN):
                self.on_pod_completed(
                    pod_name, pod.exit_code if pod else None,
                    self._failure_reason(pod))
                return E.COMPLETED
            if actual is A.WAITING:
                # a running pod regressing to waiting is a weird state:
                # kill it; the failure is the cluster's fault (mea culpa)
                self._kill_weird(pod_name, "pod regressed to waiting")
                return E.RUNNING
            if actual is A.MISSING:
                # pod vanished under us (node reclaim, external delete)
                self.on_pod_killed(pod_name, Reasons.NODE_LOST.code)
                return E.COMPLETED

        elif expected is E.KILLED:
            if actual in (A.WAITING, A.RUNNING):
                # store writeback first, then delete from kubernetes
                self.on_pod_killed(pod_name, Reasons.KILLED_BY_USER.code)
                self.api.delete_pod(pod_name)
                return E.COMPLETED
            if actual in (A.SUCCEEDED,):
                # it finished before the kill landed
                self.on_pod_completed(pod_name, pod.exit_code, None)
                self.api.delete_pod(pod_name)
                return E.COMPLETED
            if actual in (A.FAILED, A.UNKNOWN):
                self.on_pod_killed(pod_name, Reasons.KILLED_BY_USER.code)
                self.api.delete_pod(pod_name)
                return E.COMPLETED
            if actual is A.MISSING:
                # kill-before-watch race: the pod never materialized
                # (reference: explicit (killed, missing) state,
                # controller.clj:572-598)
                self.on_pod_killed(pod_name, Reasons.KILLED_BY_USER.code)
                return E.COMPLETED

        elif expected is E.COMPLETED:
            if actual in (A.SUCCEEDED, A.FAILED, A.UNKNOWN):
                self.api.delete_pod(pod_name)  # writeback already happened
                return E.COMPLETED if self.api.pod(pod_name) else None
            if actual in (A.RUNNING, A.WAITING):
                # who resurrected this pod? two leaders? kill it
                self._kill_weird(pod_name, "live pod for completed instance")
                return E.COMPLETED
            if actual is A.MISSING:
                return None  # final state: forget

        elif expected is E.MISSING:
            # only reached for cook-managed pods (the watch layer filters
            # foreign and synthetic pods before the controller sees them)
            if actual in (A.SUCCEEDED, A.FAILED, A.UNKNOWN):
                self.api.delete_pod(pod_name)
                return None
            if actual in (A.RUNNING, A.WAITING):
                self._kill_weird(pod_name, "untracked live cook pod")
                return None
            return None

        return expected

    def _kill_weird(self, pod_name: str, why: str) -> None:
        self.log.warning("killing pod %s in weird state: %s", pod_name, why)
        self.api.delete_pod(pod_name)

    @staticmethod
    def _failure_reason(pod: Optional[FakePod]) -> Optional[int]:
        if pod is None:
            return Reasons.UNKNOWN.code
        if pod.reason == "NodeLost":
            return Reasons.NODE_LOST.code
        if pod.reason == "Deleted":
            return Reasons.KILLED_BY_USER.code
        return Reasons.NON_ZERO_EXIT.code if pod.exit_code else \
            Reasons.UNKNOWN.code
