"""Dual-state-machine pod controller.

The heart of the k8s backend (reference:
scheduler/src/cook/kubernetes/controller.clj:482-711): reconciles the cross
product of

  cook-expected-state in {STARTING, RUNNING, COMPLETED, KILLED, MISSING}
  pod-synthesized-state in {WAITING, RUNNING, SUCCEEDED, FAILED, UNKNOWN,
                            DELETING, MISSING}

— the reference's "30-state table" plus its DELETING arms — preserving the
reference's invariants:
  * store writeback happens FIRST, then kubernetes actions (restart safety);
  * pods are deleted from kubernetes only in terminal pod states
    (UNKNOWN counts as terminal, forced retry at the cook level);
  * a live pod in an unexpected ("weird") state is killed by deleting it and
    the failure is marked mea-culpa;
  * (RUNNING, WAITING) — a pod regressing to waiting means the node
    preempted/moved it (GKE preemptible semantics): kill the pod AND write
    a mea-culpa preemption so the retry is free (controller.clj
    handle-pod-preemption);
  * (KILLED, MISSING) — the kill-races-the-watch case: opportunistically
    kill using the launch-time pod object saved in the expected-state entry
    (controller.clj :launch-pod);
  * (MISSING, DELETING) with an old deletion timestamp — escalate to a
    grace-0 hard kill (controller.clj kill-pod-hard);
  * per-pod processing is serialized through sharded locks
    (controller.clj:22-51 — here the sharded ordered executor).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ...state.schema import Reasons
from .fake_api import FakePod


class CookExpected(enum.Enum):
    STARTING = "starting"
    RUNNING = "running"
    COMPLETED = "completed"
    KILLED = "killed"
    MISSING = "missing"


class PodState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    UNKNOWN = "unknown"
    DELETING = "deleting"
    MISSING = "missing"


TERMINAL_POD_STATES = (PodState.SUCCEEDED, PodState.FAILED,
                       PodState.UNKNOWN, PodState.MISSING)

# how long a DELETING pod may linger before the hard kill
OLD_DELETION_MS = 60_000


def synthesize_pod_state(pod: Optional[FakePod]) -> PodState:
    """pod object -> synthesized state (reference:
    pod->synthesized-pod-state kubernetes/api.clj:1916)."""
    if pod is None:
        return PodState.MISSING
    if pod.deleted and pod.phase in ("Pending", "Running"):
        return PodState.DELETING
    if pod.phase == "Pending":
        return PodState.WAITING
    if pod.phase == "Running":
        return PodState.RUNNING
    if pod.phase == "Succeeded":
        return PodState.SUCCEEDED
    if pod.phase == "Failed":
        return PodState.FAILED
    return PodState.UNKNOWN


@dataclass
class ExpectedStateEntry:
    state: CookExpected
    # why a kill happened / weird-state provenance, for passport/debug
    reason: str = ""
    # the pod object we asked kubernetes to create, kept so a kill that
    # races ahead of the watch can still name its target
    # (reference: :launch-pod in the cook-expected-state dict)
    launch_pod: Optional[FakePod] = None


class PodController:
    """Reconciler over (expected, actual) per pod name.

    Writebacks to the store go through the callbacks; kubernetes actions go
    through the api client (create/delete pod).
    """

    def __init__(self, api, *,
                 on_pod_started: Callable[[str], None],
                 on_pod_completed: Callable[[str, Optional[int], Optional[int]], None],
                 on_pod_killed: Callable[[str, int], None],
                 on_pod_preempted: Optional[Callable[[str], None]] = None,
                 managed_filter: Optional[Callable] = None,
                 clock: Callable[[], int] = lambda: 0,
                 logger=None):
        self.api = api
        self.managed_filter = managed_filter or (lambda pod: True)
        self.expected: Dict[str, ExpectedStateEntry] = {}
        self._lock = threading.RLock()
        self.on_pod_started = on_pod_started
        self.on_pod_completed = on_pod_completed
        self.on_pod_killed = on_pod_killed
        self.on_pod_preempted = on_pod_preempted or (
            lambda pod_name: on_pod_killed(
                pod_name, Reasons.PREEMPTED_BY_POOL.code))
        self.clock = clock
        import logging
        self.log = logger or logging.getLogger(__name__)

    # ------------------------------------------------------------ lifecycle
    def launch_pod(self, pod: FakePod) -> bool:
        """Expected -> STARTING and create in kubernetes."""
        with self._lock:
            self.expected[pod.name] = ExpectedStateEntry(
                CookExpected.STARTING, launch_pod=pod)
            try:
                self.api.create_pod(pod)
                return True
            except ValueError:
                # name collision: treat as submission failure
                self.expected.pop(pod.name, None)
                return False

    def kill_pod(self, pod_name: str, reason: str = "killed") -> None:
        """Cook-level kill (user kill / preemption): expected -> KILLED, then
        reconcile (which deletes the pod)."""
        with self._lock:
            entry = self.expected.get(pod_name)
            if entry is None or entry.state in (CookExpected.COMPLETED,
                                                CookExpected.MISSING):
                return
            self.expected[pod_name] = ExpectedStateEntry(
                CookExpected.KILLED, reason,
                launch_pod=entry.launch_pod)
        self.process(pod_name)

    def set_expected(self, pod_name: str, state: CookExpected) -> None:
        """Startup reconciliation hook."""
        with self._lock:
            self.expected[pod_name] = ExpectedStateEntry(state)

    # ---------------------------------------------------------------- events
    def pod_update(self, pod_name: str) -> None:
        self.process(pod_name)

    def pod_deleted(self, pod_name: str) -> None:
        self.process(pod_name)

    def scan_all(self) -> None:
        """Periodic full reconciliation (reference: scan-process
        controller.clj:815): every tracked or live pod gets visited."""
        with self._lock:
            names = set(self.expected.keys())
        names.update(p.name for p in self.api.pods()
                     if self.managed_filter(p))
        for name in names:
            self.process(name)

    # ------------------------------------------------------------------ core
    def process(self, pod_name: str) -> None:
        """One reconciliation visit (reference: process controller.clj:482).
        Runs under the per-pod lock; loops until the state is stable."""
        with self._lock:
            for _ in range(4):  # states converge in <= a few hops
                entry = self.expected.get(pod_name)
                expected = entry.state if entry else CookExpected.MISSING
                pod = self.api.pod(pod_name)
                actual = synthesize_pod_state(pod)
                new_expected = self._step(pod_name, expected, actual, pod,
                                          entry)
                if new_expected is None:
                    self.expected.pop(pod_name, None)
                    if expected is CookExpected.MISSING:
                        return
                elif new_expected is not expected:
                    self.expected[pod_name] = ExpectedStateEntry(
                        new_expected, entry.reason if entry else "",
                        launch_pod=entry.launch_pod if entry else None)
                else:
                    return  # stable

    # The full transition table. Returns the new expected state
    # (None = forget the entry).
    def _step(self, pod_name: str, expected: CookExpected, actual: PodState,
              pod: Optional[FakePod], entry: Optional[ExpectedStateEntry]
              ) -> Optional[CookExpected]:
        E, A = CookExpected, PodState

        if expected is E.STARTING:
            if actual in (A.WAITING, A.MISSING):
                return E.STARTING  # pod creation/scheduling in progress
            if actual is A.RUNNING:
                self.on_pod_started(pod_name)
                return E.RUNNING
            if actual is A.SUCCEEDED:
                self.on_pod_started(pod_name)  # never observed running
                self.on_pod_completed(pod_name, pod.exit_code, None)
                return E.COMPLETED
            if actual is A.FAILED:
                self.on_pod_completed(
                    pod_name, pod.exit_code, self._failure_reason(pod))
                return E.COMPLETED
            if actual is A.UNKNOWN:
                # terminal-as-far-as-we're-concerned + kill the weird pod;
                # mea-culpa so the retry is free
                self.on_pod_completed(pod_name, pod.exit_code if pod else None,
                                      Reasons.UNKNOWN_MEA_CULPA.code)
                self._kill_weird(pod_name, "unknown pod phase while starting")
                return E.COMPLETED
            if actual is A.DELETING:
                # deleted before it ever ran: something external killed it
                self.on_pod_killed(pod_name, Reasons.NODE_LOST.code)
                return E.COMPLETED

        elif expected is E.RUNNING:
            if actual is A.RUNNING:
                return E.RUNNING
            if actual is A.SUCCEEDED:
                self.on_pod_completed(pod_name, pod.exit_code, None)
                return E.COMPLETED
            if actual is A.FAILED:
                self.on_pod_completed(
                    pod_name, pod.exit_code, self._failure_reason(pod))
                return E.COMPLETED
            if actual is A.UNKNOWN:
                self.on_pod_completed(pod_name, pod.exit_code if pod else None,
                                      Reasons.UNKNOWN_MEA_CULPA.code)
                self._kill_weird(pod_name, "unknown pod phase while running")
                return E.COMPLETED
            if actual is A.WAITING:
                # a running pod regressing to waiting means the node
                # preempted/moved it (GKE preemptible): kill the pod and
                # write a mea-culpa PREEMPTION so the retry is free
                # (reference: handle-pod-preemption, controller.clj)
                self.log.info("pod %s regressed running->waiting: preempted",
                              pod_name)
                self.api.delete_pod(pod_name)
                self.on_pod_preempted(pod_name)
                return E.COMPLETED
            if actual in (A.MISSING, A.DELETING):
                # pod vanished under us (node reclaim, external delete)
                self.on_pod_killed(pod_name, Reasons.NODE_LOST.code)
                return E.COMPLETED

        elif expected is E.KILLED:
            if actual in (A.WAITING, A.RUNNING):
                # store writeback first, then delete from kubernetes
                self.on_pod_killed(pod_name, Reasons.KILLED_BY_USER.code)
                self.api.delete_pod(pod_name)
                return E.COMPLETED
            if actual is A.SUCCEEDED:
                # it finished before the kill landed
                self.on_pod_completed(pod_name, pod.exit_code, None)
                self.api.delete_pod(pod_name)
                return E.COMPLETED
            if actual is A.FAILED:
                self.on_pod_killed(pod_name, Reasons.KILLED_BY_USER.code)
                self.api.delete_pod(pod_name)
                return E.COMPLETED
            if actual is A.UNKNOWN:
                self.on_pod_completed(pod_name, pod.exit_code if pod else None,
                                      Reasons.UNKNOWN_MEA_CULPA.code)
                self._kill_weird(pod_name, "unknown pod phase while killed")
                return E.COMPLETED
            if actual is A.DELETING:
                # expected step of the deletion path
                self.on_pod_killed(pod_name, Reasons.KILLED_BY_USER.code)
                return E.COMPLETED
            if actual is A.MISSING:
                # kill raced ahead of the watch: the pod may exist even
                # though our watch state says missing — opportunistically
                # kill the launch-time pod object (controller.clj
                # :launch-pod) so it cannot leak, then write back
                if entry is not None and entry.launch_pod is not None:
                    self.log.info(
                        "opportunistic kill of %s (kill raced the watch)",
                        pod_name)
                    self.api.delete_pod(pod_name)
                self.on_pod_killed(pod_name, Reasons.KILLED_BY_USER.code)
                return E.COMPLETED

        elif expected is E.COMPLETED:
            if actual in (A.SUCCEEDED, A.FAILED):
                self.api.delete_pod(pod_name)  # writeback already happened
                return E.COMPLETED if self.api.pod(pod_name) else None
            if actual is A.UNKNOWN:
                self._kill_weird(pod_name, "unknown pod phase after complete")
                return E.COMPLETED if self.api.pod(pod_name) else None
            if actual in (A.RUNNING, A.WAITING):
                # who resurrected this pod? two leaders? kill it
                self._kill_weird(pod_name, "live pod for completed instance")
                return E.COMPLETED
            if actual is A.DELETING:
                return None  # deletion in progress; nothing left to do
            if actual is A.MISSING:
                return None  # final state: forget

        elif expected is E.MISSING:
            # only reached for cook-managed pods (the watch layer filters
            # foreign and synthetic pods before the controller sees them)
            if actual in (A.SUCCEEDED, A.FAILED, A.UNKNOWN):
                self._kill_weird(pod_name, "terminal pod with no record")
                return None
            if actual in (A.RUNNING, A.WAITING):
                self._kill_weird(pod_name, "untracked live cook pod")
                return None
            if actual is A.DELETING:
                # stuck deletion: past the deadline, escalate to a grace-0
                # hard kill (reference: kill-pod-hard for old deletion
                # timestamps)
                if pod is not None and pod.deletion_ms is not None and \
                        self.clock() - pod.deletion_ms > OLD_DELETION_MS:
                    self.log.warning("hard-killing pod %s stuck deleting",
                                     pod_name)
                    self.api.delete_pod(pod_name, grace_period_s=0)
                return None
            return None

        return expected

    def _kill_weird(self, pod_name: str, why: str) -> None:
        self.log.warning("killing pod %s in weird state: %s", pod_name, why)
        self.api.delete_pod(pod_name)

    @staticmethod
    def _failure_reason(pod: Optional[FakePod]) -> Optional[int]:
        if pod is None:
            return Reasons.UNKNOWN.code
        if pod.reason == "NodeLost":
            return Reasons.NODE_LOST.code
        if pod.reason == "Deleted":
            return Reasons.KILLED_BY_USER.code
        return Reasons.NON_ZERO_EXIT.code if pod.exit_code else \
            Reasons.UNKNOWN.code
