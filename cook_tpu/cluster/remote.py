"""Remote compute cluster over the native C++ transport.

The framework's equivalent of the reference's Mesos backend: the scheduler
binds a *native* driver (libcooktransport.so, built from
``native/transport.cpp``) the way the reference binds the C++
MesosSchedulerDriver through JNI (reference: mesos_compute_cluster.clj:
206-238, project.clj:207 twosigma/mesomatic), and on-node ``cook_agentd``
daemons play the role of the Mesos agent + custom executor pair
(reference: executor/cook/executor.py): they run task commands in their own
process groups under per-task sandboxes and stream status updates back.

Semantics mirrored from the reference backend:
  - offers synthesized as capacity minus tracked consumption per host
    (the k8s-style model, kubernetes/compute_cluster.clj:68-174);
  - status updates delivered through the scheduler's callback exactly like
    mesos status-update -> write-status-to-datomic (scheduler.clj:217);
  - reconciliation on (re)connect (scheduler.clj:1828-1878): the agent's
    REGISTERED frame carries its live task ids, and RECONCILE replays the
    authoritative per-task state; tasks the store considers live but the
    agent no longer knows become NODE_LOST (mea-culpa);
  - sandbox directory writeback (mesos/sandbox.clj:222-353) via the STATUS
    frame's sandbox field.
"""

from __future__ import annotations

import ctypes
import logging
from collections import OrderedDict
import subprocess
import threading
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from ..state.schema import InstanceStatus, Reasons, Resources
from ..utils import tracing
from .base import ComputeCluster, LaunchSpec, Offer

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_SRC = _REPO_ROOT / "native" / "transport.cpp"
_BUILD_DIR = _REPO_ROOT / "native" / "build"
_LIB = _BUILD_DIR / "libcooktransport.so"
_AGENTD = _BUILD_DIR / "cook_agentd"

_SEP = "\x1f"
_BUF_CAP = 1 << 20


def compile_fetch_prelude(uris) -> str:
    """Shell prelude fetching each job URI into the sandbox before the
    command runs (reference: the mesos fetcher's copy/download + extract +
    executable bits, driven from :job/uri at mesos/task.clj:114-160).
    Local paths / file:// are copied; http(s) downloads via curl; a failed
    fetch fails the task (exit before the user command)."""
    import shlex
    lines = []
    for uri in uris or []:
        value = (uri.get("value") or "").strip()
        if not value:
            continue
        src = value[7:] if value.startswith("file://") else value
        base = shlex.quote(src.rsplit("/", 1)[-1])
        if value.startswith(("http://", "https://")):
            lines.append(f"curl -sSfL -o {base} {shlex.quote(value)}")
        else:
            lines.append(f"cp {shlex.quote(src)} {base}")
        if uri.get("executable"):
            lines.append(f"chmod +x {base}")
        if uri.get("extract"):
            lines.append(f"tar -xf {base}")
    if not lines:
        return ""
    return "set -e\n" + "\n".join(lines) + "\nset +e\n"


def _build(target: Path, extra: List[str]) -> Optional[Path]:
    from ..native.build import build_if_stale
    return build_if_stale([_SRC, _SRC.parent / "framing.h"], target, extra)


def build_agentd() -> Optional[Path]:
    return _build(_AGENTD, ["-DCOOK_AGENT_MAIN"])


_lib_handle = None
_lib_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib_handle, _lib_tried
    if _lib_tried:
        return _lib_handle
    _lib_tried = True
    path = _build(_LIB, ["-shared", "-fPIC"])
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    lib.ctd_connect.restype = ctypes.c_void_p
    lib.ctd_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.ctd_agent_info.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
    lib.ctd_launch.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_char_p, ctypes.c_double,
                               ctypes.c_double]
    lib.ctd_launch2.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_double,
                                ctypes.c_double, ctypes.c_char_p,
                                ctypes.c_int, ctypes.c_char_p,
                                ctypes.c_char_p]
    lib.ctd_launch3.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_double,
                                ctypes.c_double, ctypes.c_char_p,
                                ctypes.c_int, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_char_p]
    lib.ctd_kill.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.ctd_reconcile.argtypes = [ctypes.c_void_p]
    lib.ctd_ping.argtypes = [ctypes.c_void_p]
    lib.ctd_poll.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                             ctypes.c_int]
    lib.ctd_connected.argtypes = [ctypes.c_void_p]
    lib.ctd_close.argtypes = [ctypes.c_void_p]
    _lib_handle = lib
    return lib


def native_available() -> bool:
    return _load() is not None and build_agentd() is not None


class AgentConnection:
    """One driver connection to one cook_agentd (ctypes over the C API)."""

    def __init__(self, host: str, port: int, timeout_ms: int = 5000):
        lib = _load()
        if lib is None:
            raise RuntimeError("native transport unavailable")
        self._lib = lib
        self._handle = lib.ctd_connect(host.encode(), port, timeout_ms)
        if not self._handle:
            raise ConnectionError(f"agent {host}:{port} unreachable")
        self._buf = ctypes.create_string_buffer(_BUF_CAP)
        self._lock = threading.Lock()  # guards handle lifetime vs close
        info = self._call_str(lib.ctd_agent_info)
        (self.agent_id, self.hostname, cpus, mem, gpus, disk,
         running_csv) = info.split(_SEP)
        self.capacity = Resources(cpus=float(cpus), mem=float(mem),
                                  gpus=float(gpus), disk=float(disk))
        self.running_at_connect = ([t for t in running_csv.split(",") if t]
                                   if running_csv else [])

    def _call_str(self, fn) -> str:
        n = fn(self._handle, self._buf, _BUF_CAP)
        if n < 0:
            raise RuntimeError("transport call failed")
        return self._buf.value.decode()

    def launch(self, task_id: str, command: str, cpus: float,
               mem: float, env: Optional[Dict[str, str]] = None,
               port_count: int = 0, image: str = "",
               volumes: Optional[List[str]] = None,
               params: Optional[List[Dict[str, str]]] = None) -> bool:
        env_pairs = [f"{k}={v}" for k, v in (env or {}).items()]
        vol_items = list(volumes or [])
        # docker parameters [{"key": k, "value": v}] -> "--k v" runtime
        # flags agent-side (reference: mesos/task.clj docker parameters)
        par_items = [f"{p['key']}={p.get('value', '')}"
                     for p in (params or [])
                     if isinstance(p, dict) and p.get("key")]
        # The agent splits each of these channels on \x1e (an embedded one
        # in any untrusted value injects extra entries — e.g. a runtime
        # flag like ``--privileged`` past the REST allowlist), and every
        # channel crosses ctypes as a C string, which a NUL byte silently
        # truncates (dropping e.g. the executor env merged after user
        # env).  REST validation rejects both bytes at submission; this
        # layer refuses regardless of the caller, failing the launch.
        wire_fields = (env_pairs + vol_items + par_items
                       + [task_id, command, image])
        if any("\x1e" in s or "\x00" in s for s in wire_fields):
            logging.getLogger(__name__).warning(
                "refusing launch of %s: field embeds a NUL or the \\x1e "
                "wire delimiter", task_id)
            return False
        env_s = "\x1e".join(env_pairs)
        vol_s = "\x1e".join(vol_items)
        par_s = "\x1e".join(par_items)
        with self._lock:
            if not self._handle:
                return False
            return self._lib.ctd_launch3(
                self._handle, task_id.encode(), command.encode(), cpus, mem,
                env_s.encode(), int(port_count), image.encode(),
                vol_s.encode(), par_s.encode()) == 0

    def kill(self, task_id: str, grace_ms: int = 3000) -> bool:
        with self._lock:
            if not self._handle:
                return False
            return self._lib.ctd_kill(self._handle, task_id.encode(),
                                      grace_ms) == 0

    def reconcile(self) -> bool:
        with self._lock:
            if not self._handle:
                return False
            return self._lib.ctd_reconcile(self._handle) == 0

    def poll(self, timeout_ms: int = 100) -> Optional[List[str]]:
        """Next event's fields; None on timeout; raises on closed.

        Only the pump thread calls poll, and close() is only invoked from
        the pump thread itself or after its join (see
        RemoteComputeCluster.shutdown), so the blocking C call needs no
        lock.  rc -2 = event larger than the buffer: grow and retry (the
        event stays queued agent-side) instead of misreading a big frame
        as connection loss and NODE_LOSTing every task."""
        if not self._handle:
            raise ConnectionError("closed")
        while True:
            n = self._lib.ctd_poll(self._handle, self._buf,
                                   ctypes.sizeof(self._buf), timeout_ms)
            if n == 0:
                return None
            if n == -2:
                self._buf = ctypes.create_string_buffer(
                    ctypes.sizeof(self._buf) * 4)
                continue
            if n < 0:
                raise ConnectionError("agent connection closed")
            return self._buf.value.decode().split(_SEP)

    @property
    def connected(self) -> bool:
        with self._lock:  # vs concurrent close(): no use-after-free reads
            return bool(self._handle) and \
                self._lib.ctd_connected(self._handle) == 1

    def close(self) -> None:
        with self._lock:
            if self._handle:
                self._lib.ctd_close(self._handle)
                self._handle = None


class LocalAgentProcess:
    """Spawn a cook_agentd on this machine (tests/single-node deployments)."""

    def __init__(self, hostname: str, cpus: float = 4.0, mem: float = 4096.0,
                 gpus: float = 0.0, disk: float = 0.0,
                 workdir: str = "/tmp/cook-agentd",
                 ports_begin: int = 0, ports_end: int = 0,
                 container_runtime: str = ""):
        agentd = build_agentd()
        if agentd is None:
            raise RuntimeError("cook_agentd unavailable (no C++ toolchain?)")
        Path(workdir).mkdir(parents=True, exist_ok=True)
        self.hostname = hostname
        argv = [str(agentd), "--port", "0", "--hostname", hostname,
                "--cpus", str(cpus), "--mem", str(mem), "--gpus", str(gpus),
                "--disk", str(disk), "--workdir", workdir,
                "--ports-begin", str(ports_begin),
                "--ports-end", str(ports_end)]
        if container_runtime:
            argv += ["--container-runtime", container_runtime]
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, text=True)
        line = self.proc.stdout.readline()
        if not line.startswith("PORT "):
            self.proc.kill()
            raise RuntimeError(f"agentd failed to start: {line!r}")
        self.port = int(line.split()[1])

    def stop(self) -> None:
        self.proc.terminate()
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:  # pragma: no cover
            self.proc.kill()


class RemoteComputeCluster(ComputeCluster):
    """ComputeCluster backed by cook_agentd daemons over the native driver."""

    def __init__(self, name: str, endpoints: List[Tuple[str, int]],
                 pool: str = "default", store=None,
                 kill_grace_ms: int = 3000,
                 progress_url: str = "",
                 executor_python: str = "",
                 executor_pythonpath: str = ""):
        super().__init__(name)
        self.pool = pool
        self.store = store  # optional: sandbox writeback target
        self.kill_grace_ms = kill_grace_ms
        # scheduler REST base URL; jobs running under the "cook" executor
        # POST progress frames here (reference: progress plumbing)
        self.progress_url = progress_url
        # AGENT-side interpreter + cook_tpu location for the "cook"
        # executor wrapper; the defaults (this process's interpreter and
        # repo) are only right when agents share the scheduler's filesystem
        # — multi-node deployments configure the agent-side paths here
        # (the reference ships its executor to agents as a mesos URI).
        import sys as _sys
        self.executor_python = executor_python or _sys.executable
        self.executor_pythonpath = executor_pythonpath or str(_REPO_ROOT)
        self._endpoints = endpoints
        self._agents: Dict[str, AgentConnection] = {}  # hostname -> conn
        # endpoints that failed to connect at initialize: while any
        # remain, this backend cannot POSITIVELY enumerate its tasks
        # (running_task_ids returns None), so the launch-intent sweep
        # defers instead of refunding a task that may be running on the
        # unreachable agent
        self._failed_endpoints: set = set()
        self._lock = threading.RLock()
        # task_id -> (hostname, resources); consumption tracking for offers
        self._tasks: Dict[str, Tuple[str, Resources]] = {}
        # (pump thread, its connection): shutdown() may only close a
        # connection whose pump has actually joined (use-after-free guard)
        self._pumps: List[Tuple[threading.Thread, "AgentConnection"]] = []
        self._stopping = threading.Event()
        # task ids already seen terminal: a late replayed "running" frame
        # must not re-adopt them into consumption tracking
        self._terminal_seen: "OrderedDict[str, None]" = OrderedDict()

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, status_callback: Callable) -> None:
        super().initialize(status_callback)
        for host, port in self._endpoints:
            # one dead node must not prevent scheduling on healthy ones
            try:
                self._connect_agent(host, port)
            except (ConnectionError, RuntimeError) as e:
                with self._lock:
                    self._failed_endpoints.add((host, port))
                logging.getLogger(__name__).warning(
                    "agent %s:%s unreachable at startup: %s", host, port, e)
        self._reconcile_store_tasks()

    def _connect_agent(self, host: str, port: int) -> AgentConnection:
        conn = AgentConnection(host, port)
        with self._lock:
            self._failed_endpoints.discard((host, port))
            self._agents[conn.hostname] = conn
            # Adopt tasks already running on the agent (reconnect after a
            # scheduler restart) so offers subtract their consumption.
            for task_id in conn.running_at_connect:
                if task_id not in self._tasks:
                    self._tasks[task_id] = (
                        conn.hostname, self._task_resources(task_id))
        # Reconciliation (scheduler.clj:1828-1878): replay authoritative
        # state for every task the agent knows about.
        conn.reconcile()
        pump = threading.Thread(target=self._pump, args=(conn,), daemon=True,
                                name=f"agent-pump-{conn.hostname}")
        pump.start()
        self._pumps.append((pump, conn))
        return conn

    def _task_resources(self, task_id: str) -> Resources:
        """Best-effort resource lookup for an adopted task."""
        if self.store is not None:
            inst = self.store.instance(task_id)
            if inst is not None:
                job = self.store.job(inst.job_uuid)
                if job is not None:
                    return job.resources
        return Resources()

    def _reconcile_store_tasks(self) -> None:
        """Tasks the store believes are live on this cluster but no agent
        knows about are NODE_LOST, mea-culpa (the reference's task
        reconciliation on (re)register, scheduler.clj:1828-1878)."""
        if self.store is None:
            return
        cb = self._status_callback
        with self._lock:
            known = set(self._tasks)
        for job, inst in self.store.running_instances():
            if inst.compute_cluster != self.name:
                continue
            if inst.task_id not in known and cb is not None:
                cb(inst.task_id, InstanceStatus.FAILED,
                   Reasons.NODE_LOST.code, hostname=inst.hostname)

    def add_agent(self, host: str, port: int) -> None:
        """Dynamic agent registration (elastic capacity)."""
        self._connect_agent(host, port)

    # -- status pump --------------------------------------------------------
    def _pump(self, conn: AgentConnection) -> None:
        while not self._stopping.is_set():
            try:
                ev = conn.poll(timeout_ms=200)
            except ConnectionError:
                if not self._stopping.is_set():
                    self._on_agent_lost(conn)
                return
            if ev is None or not ev:
                continue
            if ev[0] == "STATUS" and len(ev) >= 5:
                ports = ([int(p) for p in ev[5].split(",") if p]
                         if len(ev) >= 6 and ev[5] else [])
                self._on_status(conn, task_id=ev[1], state=ev[2],
                                exit_code=int(ev[3] or 0), sandbox=ev[4],
                                ports=ports)

    def _on_status(self, conn: AgentConnection, task_id: str, state: str,
                   exit_code: int, sandbox: str,
                   ports: Optional[List[int]] = None) -> None:
        if self.store is not None and sandbox:
            try:
                self.store.update_instance_sandbox(
                    task_id, sandbox_directory=sandbox)
            except Exception:
                pass
        if self.store is not None and ports:
            # assigned host-port writeback (mesos/task.clj:209-237 ->
            # :instance/ports)
            try:
                self.store.update_instance_ports(task_id, ports)
            except Exception:
                pass
        cb = self._status_callback
        if state == "running":
            with self._lock:
                if task_id in self._terminal_seen:
                    # out-of-order/replayed "running" after a terminal
                    # status: adopting it would leak tracked consumption
                    # on that host's offers forever
                    return
                # replayed running status after reconnect: adopt the task
                if task_id not in self._tasks:
                    self._tasks[task_id] = (
                        conn.hostname, self._task_resources(task_id))
            if cb:
                cb(task_id, InstanceStatus.RUNNING, None,
                   hostname=conn.hostname)
            return
        # terminal: release tracked consumption; remember the terminal so a
        # late "running" replay is dropped (bounded memory)
        with self._lock:
            self._tasks.pop(task_id, None)
            self._terminal_seen[task_id] = None
            while len(self._terminal_seen) > 4096:
                self._terminal_seen.popitem(last=False)
        if cb is None:
            return
        if state == "finished":
            cb(task_id, InstanceStatus.SUCCESS, None, exit_code=exit_code,
               hostname=conn.hostname)
        elif state == "killed":
            cb(task_id, InstanceStatus.FAILED, Reasons.KILLED_BY_USER.code,
               exit_code=exit_code, hostname=conn.hostname)
        elif state == "memlimit":
            # the agent's memory watchdog hard-killed the task tree
            # (reference: "Container memory limit exceeded")
            cb(task_id, InstanceStatus.FAILED,
               Reasons.MEMORY_LIMIT_EXCEEDED.code,
               exit_code=exit_code, hostname=conn.hostname)
        else:  # failed
            cb(task_id, InstanceStatus.FAILED, Reasons.NON_ZERO_EXIT.code,
               exit_code=exit_code, hostname=conn.hostname)

    def _on_agent_lost(self, conn: AgentConnection) -> None:
        """Connection dropped: its tasks are NODE_LOST (mea-culpa), exactly
        the reference's slave-lost semantics.  Deliberately NOT a
        circuit-breaker failure: agent loss is a capacity event, and
        counting it would let routine node churn black out launches on
        the cluster's remaining healthy agents."""
        with self._lock:
            if self._agents.get(conn.hostname) is conn:
                del self._agents[conn.hostname]
            lost = [t for t, (h, _) in self._tasks.items()
                    if h == conn.hostname]
            for t in lost:
                del self._tasks[t]
        cb = self._status_callback
        if cb:
            for t in lost:
                cb(t, InstanceStatus.FAILED, Reasons.NODE_LOST.code,
                   hostname=conn.hostname)
        conn.close()  # release the fd/driver; reader thread already exited

    # -- scheduling ---------------------------------------------------------
    def pending_offers(self, pool: str) -> List[Offer]:
        if pool != self.pool:
            return []
        offers = []
        with self._lock:
            consumption: Dict[str, Resources] = {}
            counts: Dict[str, int] = {}
            for h, res in self._tasks.values():
                consumption[h] = consumption.get(h, Resources()) + res
                counts[h] = counts.get(h, 0) + 1
            for hostname, conn in self._agents.items():
                used = consumption.get(hostname, Resources())
                avail = conn.capacity - used
                if not avail.non_negative():
                    avail = Resources()
                offers.append(Offer(
                    id=f"{self.name}/{hostname}",
                    hostname=hostname, slave_id=conn.agent_id, pool=pool,
                    available=avail, capacity=conn.capacity,
                    cluster=self.name,
                    task_count=counts.get(hostname, 0)))
        return offers

    def launch_tasks(self, pool: str, specs: List[LaunchSpec]) -> None:
        from ..utils.faults import injector as _faults
        from ..utils.retry import breakers as _breakers
        breaker = _breakers.get(self.name)
        for spec in specs:
            with self._lock:
                conn = self._agents.get(spec.hostname)
                if conn is not None:
                    self._tasks[spec.task_id] = (spec.hostname, spec.resources)
            if conn is None:
                cb = self._status_callback
                if cb:
                    cb(spec.task_id, InstanceStatus.FAILED,
                       Reasons.CONTAINER_LAUNCH_FAILED.code,
                       hostname=spec.hostname)
                continue
            command, extra_env = self._task_command(spec)
            if command is None:
                # job vanished between match and launch, or has no command:
                # running a placeholder would report SUCCESS for work that
                # never happened
                with self._lock:
                    self._tasks.pop(spec.task_id, None)
                cb = self._status_callback
                if cb:
                    cb(spec.task_id, InstanceStatus.FAILED,
                       Reasons.CONTAINER_LAUNCH_FAILED.code,
                       hostname=spec.hostname)
                continue
            container = spec.container or {}
            with tracing.span("remote.launch", cluster=self.name,
                              hostname=spec.hostname):
                if _faults.should_fire("remote.rpc"):
                    ok = False  # injected transport fault: RPC never lands
                else:
                    ok = conn.launch(
                        spec.task_id, command,
                        spec.resources.cpus, spec.resources.mem,
                        env={**spec.env, **extra_env},
                        port_count=spec.port_count,
                        image=container.get("image", ""),
                        volumes=[v if isinstance(v, str)
                                 else f"{v['host-path']}:"
                                      f"{v['container-path']}"
                                 for v in container.get("volumes", [])],
                        params=container.get("parameters") or [])
            if ok:
                breaker.record_success()
            else:
                breaker.record_failure()
            if not ok:
                with self._lock:
                    self._tasks.pop(spec.task_id, None)
                cb = self._status_callback
                if cb:
                    cb(spec.task_id, InstanceStatus.FAILED,
                       Reasons.CONTAINER_LAUNCH_FAILED.code,
                       hostname=spec.hostname)

    def _task_command(self, spec: LaunchSpec
                      ) -> Tuple[Optional[str], Dict[str, str]]:
        """(command, extra env), command None when it cannot be determined
        (which must fail the launch, not silently succeed). Without a store
        this backend is a pure transport under test; 'true' keeps it
        driveable.

        Task compilation (the reference's mesos/task.clj:114-294 role):
        URI artifacts become a fetch prelude ahead of the user command, and
        :job/executor "cook" wraps the command in the progress-tracking
        executor (python -m cook_tpu.agent.executor) with its configuration
        in the environment."""
        if self.store is None:
            return "true", {}
        job = self.store.job(spec.job_uuid)
        if job is None or not job.command:
            return None, {}
        prelude = compile_fetch_prelude(job.uris)
        command = prelude + job.command if prelude else job.command
        # the reference's task environment (mesos/task.clj:114-135): every
        # task learns its own identity and resource grant from COOK_* vars
        extra: Dict[str, str] = {
            "COOK_JOB_UUID": job.uuid,
            "COOK_INSTANCE_UUID": spec.task_id,
            # count of PRIOR attempts (the launching task is already in
            # job.instances here; the reference counts from the
            # pre-transaction snapshot, so attempt 1 sees 0)
            "COOK_INSTANCE_NUM": str(max(0, len(job.instances) - 1)),
            "COOK_JOB_CPUS": str(job.resources.cpus),
            "COOK_JOB_MEM_MB": str(job.resources.mem),
        }
        if job.resources.gpus:
            extra["COOK_JOB_GPUS"] = str(job.resources.gpus)
        if job.group:
            extra["COOK_JOB_GROUP_UUID"] = job.group
        if job.executor == "cook":
            import shlex
            # prepend (not clobber) any PYTHONPATH the job itself set
            job_pp = job.env.get("PYTHONPATH", "")
            extra["PYTHONPATH"] = (self.executor_pythonpath
                                   + (":" + job_pp if job_pp else ""))
            if self.progress_url:
                extra["COOK_PROGRESS_URL"] = self.progress_url
            if job.progress_regex_string:
                extra["COOK_PROGRESS_REGEX"] = job.progress_regex_string
            if job.progress_output_file:
                extra["COOK_PROGRESS_FILE"] = job.progress_output_file
            command = (f"exec {shlex.quote(self.executor_python)} -m "
                       f"cook_tpu.agent.executor {shlex.quote(command)}")
        return command, extra

    def running_task_ids(self) -> Optional[List[str]]:
        """Task ids this backend is tracking (launched here or adopted
        from agent reconnects) — the launch-intent sweep's positive
        does-the-cluster-know-it check.  None while any configured
        endpoint never connected: the enumeration is incomplete, so a
        task's absence proves nothing (refunding it could double-run
        work still executing on the unreachable agent)."""
        with self._lock:
            if self._failed_endpoints:
                return None
            return list(self._tasks)

    def kill_task(self, task_id: str) -> None:
        with self._lock:
            entry = self._tasks.get(task_id)
            conn = self._agents.get(entry[0]) if entry else None
        if conn is not None:
            conn.kill(task_id, self.kill_grace_ms)

    # -- teardown -----------------------------------------------------------
    def shutdown(self) -> None:
        self._stopping.set()
        closable = []
        for pump, conn in self._pumps:
            pump.join(timeout=2)
            if pump.is_alive():
                # the pump may still be inside ctd_poll; closing now would
                # delete the C driver under it (use-after-free). Leak the
                # handle instead — the daemon thread dies with the process.
                logging.getLogger(__name__).warning(
                    "agent pump for %s did not exit; leaking its handle",
                    conn.hostname)
            else:
                closable.append(conn)
        with self._lock:
            self._agents.clear()
        for conn in closable:
            conn.close()


def factory(store=None, name: str = "native", endpoints=None,
            pool: str = "default", kill_grace_ms: int = 3000,
            progress_url: str = "") -> "RemoteComputeCluster":
    """Config-driven construction for the daemon: ``endpoints`` is a list of
    [host, port] pairs of running cook_agentd daemons."""
    eps = [(h, int(p)) for h, p in (endpoints or [])]
    return RemoteComputeCluster(name, eps, pool=pool, store=store,
                                kill_grace_ms=kill_grace_ms,
                                progress_url=progress_url)
