from .base import ComputeCluster, LaunchSpec, Offer, ReadWriteLock  # noqa: F401
from .fake import FakeCluster, FakeHost  # noqa: F401
