from .base import ComputeCluster, LaunchSpec, Offer, ReadWriteLock  # noqa: F401
from .fake import FakeCluster, FakeHost  # noqa: F401
from .remote import (  # noqa: F401
    AgentConnection,
    LocalAgentProcess,
    RemoteComputeCluster,
)
