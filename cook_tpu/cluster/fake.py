"""In-process fake compute cluster with a virtual clock.

The port of the reference's test/simulation backends: the fake compute
cluster registered by unit tests (reference: testutil.clj:76-122) fused with
the offer-fabricating in-JVM Mesos master used by the faster-than-real-time
simulator (reference: scheduler/src/cook/mesos/mesos_mock.clj:88-184).

Hosts are declared with capacities/attributes; offers are synthesized as
capacity minus consumption (the k8s-style offer model); launched tasks
complete after a configurable virtual duration when :meth:`advance_to` moves
the clock, delivering status updates through the scheduler's callback.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..state.schema import InstanceStatus, Reasons, Resources
from .base import ComputeCluster, LaunchSpec, Offer


@dataclass
class FakeHost:
    hostname: str
    capacity: Resources
    pool: str = "default"
    attributes: Dict[str, str] = field(default_factory=dict)
    gpu_model: str = ""
    disk_type: str = ""


@dataclass
class _RunningTask:
    spec: LaunchSpec
    started_at_ms: int
    duration_ms: Optional[int]   # None = runs until killed
    exit_code: int = 0


class FakeCluster(ComputeCluster):
    """Deterministic fake backend for tests, the simulator, and benchmarks."""

    def __init__(self, name: str, hosts: List[FakeHost],
                 default_task_duration_ms: Optional[int] = None,
                 auto_advance: bool = False):
        """``auto_advance``: follow the wall clock on a background ticker
        — for daemon deployments where no simulator drives advance_to, so
        tasks with durations actually complete.  A ticker (not an
        advance-on-offers hook) because a DRAINING cluster gets no offer
        calls yet must still finish its tasks for drain-then-delete."""
        super().__init__(name)
        self._hosts: Dict[str, FakeHost] = {h.hostname: h for h in hosts}
        self._tasks: Dict[str, _RunningTask] = {}
        self._lock = threading.RLock()
        self._now_ms = 0
        self._default_duration_ms = default_task_duration_ms
        # task_id -> duration override, set by tests/simulator before launch
        self.task_durations_ms: Dict[str, int] = {}
        # job uuid -> duration fallback (the simulator keys by job, since
        # task ids are only minted at launch)
        self.job_durations_ms: Dict[str, int] = {}
        self.task_exit_codes: Dict[str, int] = {}
        self.launched_order: List[str] = []
        # task_id -> advisory notify_task events delivered while running
        # (the elastic resize plane's checkpoint warnings, docs/GANG.md)
        self.notifications: Dict[str, List[Dict]] = {}
        # per-host consumption/counts maintained incrementally on
        # launch/complete/kill: recomputing from _tasks and re-running the
        # generator-based Resources arithmetic for every host cost 25-50 ms
        # per cycle at the 5k-host bench point
        self._consumption: Dict[str, List[float]] = {}
        self._counts: Dict[str, int] = {}
        # per-host Offer cache: rebuilding 5k Offer objects per cycle cost
        # ~35 ms at the bench point while only the ~launched hosts change;
        # entries are invalidated by _consume and host add/remove
        self._offer_cache: Dict[str, Offer] = {}
        self._auto_advance = auto_advance
        self._ticker_stop = threading.Event()
        if auto_advance:
            import time as _time

            def tick():
                while not self._ticker_stop.wait(0.1):
                    self.advance_to(int(_time.time() * 1000))
            threading.Thread(target=tick, daemon=True,
                             name=f"fake-clock-{name}").start()

    def shutdown(self) -> None:
        self._ticker_stop.set()

    def _consume(self, hostname: str, r: Resources, sign: float) -> None:
        c = self._consumption.get(hostname)
        if c is None:
            c = self._consumption[hostname] = [0.0, 0.0, 0.0, 0.0]
        c[0] += sign * r.cpus
        c[1] += sign * r.mem
        c[2] += sign * r.gpus
        c[3] += sign * r.disk
        self._counts[hostname] = self._counts.get(hostname, 0) + (
            1 if sign > 0 else -1)
        self._offer_cache.pop(hostname, None)

    def _pop_task(self, task_id: str) -> Optional[_RunningTask]:
        """Remove a task and release its consumption (caller holds _lock)."""
        task = self._tasks.pop(task_id, None)
        if task is not None:
            self._consume(task.spec.hostname, task.spec.resources, -1.0)
        return task

    # ------------------------------------------------------------- protocol
    def pending_offers(self, pool: str) -> List[Offer]:
        with self._lock:
            offers = []
            zeros = (0.0, 0.0, 0.0, 0.0)
            cache = self._offer_cache
            for h in self._hosts.values():
                if h.pool != pool:
                    continue
                offer = cache.get(h.hostname)
                if offer is not None and offer.pool == pool:
                    offers.append(offer)
                    continue
                cap = h.capacity
                used = self._consumption.get(h.hostname, zeros)
                avail = Resources(cap.cpus - used[0], cap.mem - used[1],
                                  cap.gpus - used[2], cap.disk - used[3])
                if not avail.non_negative():
                    avail = Resources()
                offer = Offer(
                    id=f"{self.name}/{h.hostname}/{self._now_ms}",
                    hostname=h.hostname, slave_id=h.hostname, pool=pool,
                    cluster=self.name,
                    available=avail, capacity=cap,
                    attributes=dict(h.attributes),
                    task_count=self._counts.get(h.hostname, 0),
                    gpu_model=h.gpu_model, disk_type=h.disk_type)
                cache[h.hostname] = offer
                offers.append(offer)
            return offers

    def launch_tasks(self, pool: str, specs: List[LaunchSpec]) -> None:
        from ..utils.faults import injector as _faults
        from ..utils.retry import breakers as _breakers
        breaker = _breakers.get(self.name)
        rejected: List[str] = []
        with self._lock:
            for spec in specs:
                if _faults.should_fire("cluster.launch"):
                    # injected backend/RPC fault: the launch is rejected
                    # (mea-culpa, pod-submission-failed) and the failure
                    # counts against this cluster's circuit breaker
                    rejected.append(spec.task_id)
                    breaker.record_failure()
                    continue
                if not spec.hostname:
                    # direct (Kenzo) mode: the backend's own scheduler places
                    # the task — first-fit stand-in for kube-scheduler
                    chosen = self._first_fit(pool, spec.resources)
                    if chosen is None:
                        rejected.append(spec.task_id)
                        continue
                    spec.hostname = chosen
                    spec.slave_id = chosen
                duration = self.task_durations_ms.get(
                    spec.task_id,
                    self.job_durations_ms.get(spec.job_uuid,
                                              self._default_duration_ms))
                # out-of-process drivers (daemon integration tests) can't
                # reach the dicts above; a job env hint carries the same
                # override through the REST surface
                env_hint = (spec.env or {}).get("COOK_FAKE_DURATION_MS")
                if env_hint is not None and \
                        spec.task_id not in self.task_durations_ms and \
                        spec.job_uuid not in self.job_durations_ms:
                    try:
                        duration = int(env_hint)
                    except ValueError:
                        pass
                exit_hint = (spec.env or {}).get("COOK_FAKE_EXIT_CODE")
                if exit_hint is not None and \
                        spec.task_id not in self.task_exit_codes:
                    try:
                        self.task_exit_codes[spec.task_id] = int(exit_hint)
                    except ValueError:
                        pass
                # relaunch of a live task_id (retry/replay): release the
                # overwritten entry's consumption or the host stays
                # permanently inflated
                self._pop_task(spec.task_id)
                self._tasks[spec.task_id] = _RunningTask(
                    spec=spec, started_at_ms=self._now_ms, duration_ms=duration,
                    exit_code=self.task_exit_codes.get(spec.task_id, 0))
                self._consume(spec.hostname, spec.resources, 1.0)
                self.launched_order.append(spec.task_id)
                breaker.record_success()
        for spec in specs:
            if spec.task_id not in rejected:
                self._emit(spec.task_id, InstanceStatus.RUNNING, None,
                           hostname=spec.hostname)
        for tid in rejected:
            self._emit(tid, InstanceStatus.FAILED,
                       Reasons.REASON_POD_SUBMISSION_FAILED.code)

    def _first_fit(self, pool: str, need: Resources) -> Optional[str]:
        zeros = (0.0, 0.0, 0.0, 0.0)
        for h in self._hosts.values():
            if h.pool != pool:
                continue
            cap, used = h.capacity, self._consumption.get(h.hostname, zeros)
            avail = Resources(cap.cpus - used[0], cap.mem - used[1],
                              cap.gpus - used[2], cap.disk - used[3])
            if need.fits_in(avail):
                return h.hostname
        return None

    def kill_task(self, task_id: str) -> None:
        with self._lock:
            task = self._pop_task(task_id)
        if task is not None:
            self._emit(task_id, InstanceStatus.FAILED, Reasons.KILLED_BY_USER.code)

    def notify_task(self, task_id: str, event: Dict) -> None:
        """Record resize notifications per task so tests/sim can assert
        the checkpoint warning reached a still-running member (the fake
        analog of the agent's SIGUSR1 + resize-file relay)."""
        with self._lock:
            if task_id in self._tasks:
                self.notifications.setdefault(task_id, []).append(
                    dict(event))

    # ---------------------------------------------------------- virtual time
    def advance_to(self, now_ms: int) -> List[str]:
        """Move the virtual clock; complete tasks whose duration elapsed.
        Returns completed task ids (in completion-time order)."""
        finished: List[tuple] = []
        with self._lock:
            self._now_ms = max(self._now_ms, now_ms)
            for tid, t in list(self._tasks.items()):
                if t.duration_ms is None:
                    continue
                done_at = t.started_at_ms + t.duration_ms
                if done_at <= self._now_ms:
                    finished.append((done_at, tid, t.exit_code))
                    self._pop_task(tid)
        finished.sort()
        out = []
        for _done_at, tid, exit_code in finished:
            ok = exit_code == 0
            self._emit(tid,
                       InstanceStatus.SUCCESS if ok else InstanceStatus.FAILED,
                       None if ok else Reasons.NON_ZERO_EXIT.code,
                       exit_code=exit_code)
            out.append(tid)
        return out

    @property
    def now_ms(self) -> int:
        return self._now_ms

    def running_task_ids(self) -> List[str]:
        with self._lock:
            return list(self._tasks.keys())

    def complete_task(self, task_id: str, exit_code: int = 0) -> None:
        """Test/simulator hook: finish a running task immediately."""
        with self._lock:
            task = self._pop_task(task_id)
        if task is not None:
            ok = exit_code == 0
            self._emit(task_id,
                       InstanceStatus.SUCCESS if ok else InstanceStatus.FAILED,
                       None if ok else Reasons.NON_ZERO_EXIT.code,
                       exit_code=exit_code)

    def fail_task(self, task_id: str, reason_code: int,
                  preempted: bool = False) -> None:
        """Test/chaos hook: fail a running task with a given reason."""
        with self._lock:
            task = self._pop_task(task_id)
        if task is not None:
            self._emit(task_id, InstanceStatus.FAILED, reason_code,
                       preempted=preempted)

    def _emit(self, task_id: str, status: InstanceStatus,
              reason_code: Optional[int], exit_code: Optional[int] = None,
              preempted: bool = False, hostname: Optional[str] = None) -> None:
        if self._status_callback is not None:
            self._status_callback(task_id, status, reason_code,
                                  exit_code=exit_code, preempted=preempted,
                                  hostname=hostname)


def factory(store=None, name: str = "fake", n_hosts: int = 4,
            cpus: float = 8.0, mem: float = 8192.0, gpus: float = 0.0,
            pool: str = "default", attributes=None,
            default_task_duration_ms=None,
            auto_advance: bool = False) -> "FakeCluster":
    """Config-driven construction for the daemon (the analog of the
    reference's compute-cluster factory-fn, compute_cluster.clj:483-497).
    In a daemon there is no simulator calling advance_to, so pass
    ``auto_advance`` (with a duration) when fake tasks should complete in
    wall time."""
    hosts = [FakeHost(hostname=f"{name}-h{i}", pool=pool,
                      capacity=Resources(cpus=cpus, mem=mem, gpus=gpus),
                      attributes=dict(attributes or {}))
             for i in range(n_hosts)]
    return FakeCluster(name, hosts,
                       default_task_duration_ms=default_task_duration_ms,
                       auto_advance=auto_advance)
