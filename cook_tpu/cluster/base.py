"""Compute-cluster abstraction — the framework's "device layer".

Mirrors the reference's ComputeCluster protocol (reference:
scheduler/src/cook/compute_cluster.clj:27-112) with the subset of methods the
scheduler core needs, plus the per-cluster launch/kill ReadWriteLock ordering
discipline (compute_cluster.clj:86-130): kills take the write lock, launches
the read lock, so a kill issued while a launch is in flight cannot be
reordered before it.
"""

from __future__ import annotations

import abc
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..state.schema import Resources


@dataclass
class Offer:
    """A host's spare capacity offered to the matcher (reference: mesos
    offers / k8s synthesized offers, kubernetes/compute_cluster.clj:68-174)."""

    id: str
    hostname: str
    slave_id: str
    pool: str
    available: Resources
    capacity: Resources
    cluster: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)
    # running task count, for max-tasks-per-host constraints
    task_count: int = 0
    # gpu/disk models present on the host (constraints.clj:122-216)
    gpu_model: str = ""
    disk_type: str = ""


@dataclass
class LaunchSpec:
    """One matched task to launch.

    Carries the full task compilation the reference builds in
    mesos/task.clj:114-294: command environment, requested host-port count,
    and the container spec ({"image": ..., "volumes": ["host:cont", ...]}).
    """

    task_id: str
    job_uuid: str
    hostname: str
    slave_id: str
    resources: Resources
    env: Dict[str, str] = field(default_factory=dict)
    port_count: int = 0
    container: Optional[Dict] = None


class ReadWriteLock:
    """Writer-preferring RW lock (equivalent of the reference's
    ReentrantReadWriteLock kill-lock, compute_cluster.clj:86-112)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0
        self._local = threading.local()

    def holds_read(self) -> bool:
        """True when the calling thread holds the read side — acquiring the
        write side from such a thread would self-deadlock."""
        return getattr(self._local, "read_count", 0) > 0

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
            self._local.read_count = getattr(self._local, "read_count", 0) + 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            self._local.read_count = getattr(self._local, "read_count", 1) - 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class ComputeCluster(abc.ABC):
    """Pluggable cluster backend (reference: compute_cluster.clj protocol).

    Status updates flow back through ``status_callback(task_id, status,
    reason_code)`` registered at initialization — the moral equivalent of the
    mesos scheduler callbacks / k8s watch feed.
    """

    def __init__(self, name: str):
        self.name = name
        self.kill_lock = ReadWriteLock()
        self.state = "running"  # running -> draining -> deleted
        self._status_callback: Optional[Callable] = None

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, status_callback: Callable) -> None:
        """Connect and begin delivering status updates."""
        self._status_callback = status_callback

    # -- scheduling ---------------------------------------------------------
    @abc.abstractmethod
    def pending_offers(self, pool: str) -> List[Offer]:
        """Current spare capacity per host for a pool."""

    def hosts(self, pool: str) -> List[Offer]:
        """ALL schedulable hosts for a pool with true capacity/attributes,
        including fully-utilized ones (which pending_offers may omit).  The
        rebalancer needs this for constraint evaluation on preemption
        targets — exactly the busy hosts.  Default assumes pending_offers is
        already exhaustive."""
        return self.pending_offers(pool)

    @abc.abstractmethod
    def launch_tasks(self, pool: str, specs: List[LaunchSpec]) -> None:
        """Start tasks. Caller holds kill_lock (the read side), so an
        in-flight launch always lands before a safe_kill_task."""

    @abc.abstractmethod
    def kill_task(self, task_id: str) -> None:
        """Kill one task. Implementations must be idempotent."""

    def safe_kill_task(self, task_id: str) -> None:
        """Kill under the write lock so in-flight launches land first
        (reference: compute_cluster.clj:116-130)."""
        self.kill_lock.acquire_write()
        try:
            self.kill_task(task_id)
        finally:
            self.kill_lock.release_write()

    def notify_task(self, task_id: str, event: Dict) -> None:
        """Best-effort advisory delivery to a RUNNING task — the elastic
        resize plane's checkpoint warning (docs/GANG.md elasticity: the
        agent relays SIGUSR1 + a ``COOK_GANG_RESIZE_FILE`` event so the
        workload can checkpoint inside the grace window).  Never
        load-bearing: a lost notification only costs the workload its
        checkpoint opportunity, the shrink itself executes through the
        ordinary kill path at the grace deadline.  Default: drop."""

    # -- capacity (Kenzo-style direct mode backpressure) --------------------
    def max_launchable(self, pool: str) -> int:
        """Headroom for direct-mode submission (reference:
        kubernetes/compute_cluster.clj:555-588)."""
        return len(self.pending_offers(pool))

    def accepts_pool(self, pool: str) -> bool:
        return self.state == "running"
