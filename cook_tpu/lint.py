"""``python -m cook_tpu.lint`` — the repo-native static analysis CLI.

Exit contract (wired into tier-1 via tests/test_analysis.py's self-lint
golden): **0** when the tree has zero unsuppressed findings, **1** when
any pass raises a new finding, a file fails to parse, or a baseline
entry has gone stale — the same verdict the tier-1 golden renders.
``cs lint`` is the same entry point through the main CLI.

Usage::

    python -m cook_tpu.lint [--json] [--root DIR] [--docs DIR]
                            [--baseline FILE] [--show-suppressed]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from .analysis import run_lint


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cs lint",
        description="repo-native static analysis: lock discipline, "
                    "JIT hygiene, docs-registry completeness "
                    "(docs/ANALYSIS.md)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable result document")
    p.add_argument("--root", default=None,
                   help="package root to scan (default: the cook_tpu "
                        "package)")
    p.add_argument("--docs", default=None,
                   help="docs directory for the registry pass (default: "
                        "<root>/../docs when present)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: "
                        "cook_tpu/analysis/baseline.json)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also list baselined/pragma-suppressed findings")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    result = run_lint(
        package_root=Path(args.root) if args.root else None,
        docs_root=Path(args.docs) if args.docs else None,
        baseline=Path(args.baseline) if args.baseline else None)
    if args.as_json:
        print(json.dumps(result.to_doc(), indent=2))
        return 0 if result.ok else 1
    for err in result.errors:
        print(f"ERROR {err}")
    for f in result.findings:
        print(f"{f.path}:{f.line}: [{f.check}] {f.message}")
        print(f"    fingerprint: {f.fingerprint}")
    if args.show_suppressed:
        for f in result.suppressed:
            print(f"suppressed ({f.suppressed_by}) {f.path}:{f.line}: "
                  f"[{f.check}] {f.detail}")
    for fp in result.stale_baseline:
        print(f"stale baseline entry (matches nothing — remove it): {fp}")
    n, s = len(result.findings), len(result.suppressed)
    print(f"{result.files_scanned} files scanned: {n} finding(s), "
          f"{s} suppressed, {len(result.stale_baseline)} stale "
          "baseline entr(ies)")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
