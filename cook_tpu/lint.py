"""``python -m cook_tpu.lint`` — the repo-native static analysis CLI.

Exit contract (wired into tier-1 via tests/test_analysis.py's self-lint
golden; documented in docs/ANALYSIS.md): **0** when the tree has zero
unsuppressed findings, **1** when any pass raises a new finding, a file
fails to parse, or a baseline entry has gone stale — the same verdict
the tier-1 golden renders.  In ``--changed`` mode, findings are
restricted to files modified vs a git base (default ``HEAD``) and the
stale-baseline check is skipped (entries for unchanged files are not
stale just because they were filtered out): **0** = nothing new in
YOUR files, while the full-repo pass remains the tier-1 gate.
``cs lint`` is the same entry point through the main CLI.

Usage::

    python -m cook_tpu.lint [--json] [--root DIR] [--docs DIR]
                            [--baseline FILE] [--show-suppressed]
                            [--changed [BASE]] [--lock-coverage]
                            [--observed FILE]
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import Iterable, List, Optional, Set

from .analysis import run_lint
from .analysis.engine import LintResult


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cs lint",
        description="repo-native static analysis: lock discipline + "
                    "interprocedural effect summaries, JIT hygiene, "
                    "docs-registry + journal-record completeness "
                    "(docs/ANALYSIS.md)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable result document (schema "
                        "version + summary counts)")
    p.add_argument("--root", default=None,
                   help="package root to scan (default: the cook_tpu "
                        "package)")
    p.add_argument("--docs", default=None,
                   help="docs directory for the registry pass (default: "
                        "<root>/../docs when present)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: "
                        "cook_tpu/analysis/baseline.json)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also list baselined/pragma-suppressed findings")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="BASE",
                   help="restrict findings to files modified vs a git "
                        "base (default HEAD) — the sub-second inner "
                        "loop; the full-repo pass stays the tier-1 "
                        "gate")
    p.add_argument("--lock-coverage", action="store_true",
                   dest="lock_coverage",
                   help="print the static-vs-observed lock-edge "
                        "coverage diff (statically possible orderings "
                        "the dynamic sanitizer never exercised, and "
                        "vice versa)")
    p.add_argument("--observed", default=None, metavar="FILE",
                   help="observed edge set for --lock-coverage: a "
                        "/debug/health JSON document (or just its "
                        "locks block, or a bare list of 'a->b' "
                        "strings); default: this process's own "
                        "lock monitor")
    return p


def changed_files(base: str, repo_root: Path,
                  package_name: str) -> Set[str]:
    """Finding-path set for files modified vs ``base``: package files
    as package-relative paths (``state/store.py``), everything else
    (docs) repo-relative — the two path shapes findings carry."""
    names: List[str] = []
    for cmd in (["git", "diff", "--name-only", base, "--"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            out = subprocess.run(
                cmd, cwd=str(repo_root), capture_output=True,
                text=True, timeout=30, check=True).stdout
        except (OSError, subprocess.SubprocessError) as e:
            raise SystemExit(
                f"cs lint --changed: git failed ({e}); run inside the "
                "repository or drop --changed")
        names.extend(line.strip() for line in out.splitlines()
                     if line.strip())
    out_set: Set[str] = set()
    prefix = package_name.rstrip("/") + "/"
    for name in names:
        out_set.add(name)
        if name.startswith(prefix):
            out_set.add(name[len(prefix):])
    return out_set


def _observed_edges(path: Optional[str]) -> List[str]:
    """The observed (dynamic) edge set for the coverage diff."""
    if path is None:
        from .utils.locks import monitor
        return monitor.observed_edges()
    doc = json.loads(Path(path).read_text(encoding="utf-8"))
    if isinstance(doc, list):
        return [str(e) for e in doc]
    locks = doc.get("locks", doc)
    edges = locks.get("observed_edges")
    if edges is None:
        # fall back to the raw edge list shape — family-normalize the
        # sibling-suffixed names (store[p0] -> store) so the diff
        # compares like with like, exactly as observed_edges() does
        from .utils.locks import family
        edges = sorted({f"{family(e['from'])}->{family(e['to'])}"
                        for e in locks.get("edges", [])})
    return [str(e) for e in edges]


def print_lock_coverage(result: LintResult,
                        observed: Iterable[str]) -> None:
    static = {f"{e['from']}->{e['to']}": e for e in result.lock_edges}
    obs = set(observed)
    exercised = sorted(set(static) & obs)
    unexercised = sorted(set(static) - obs)
    unstatic = sorted(obs - set(static))
    print("lock-order coverage (static analysis vs dynamic sanitizer):")
    print(f"  static edges:   {len(static)} "
          f"({sum(1 for e in static.values() if e['kind'] == 'resolved')}"
          f" resolved, "
          f"{sum(1 for e in static.values() if e['kind'] == 'dynamic')}"
          " via dynamic-dispatch over-approximation)")
    print(f"  observed edges: {len(obs)}")
    print(f"  exercised:      {len(exercised)}")
    for e in exercised:
        print(f"    [ok]         {e}")
    for e in unexercised:
        info = static[e]
        print(f"    [unexercised] {e}  ({info['kind']}; via "
              f"{info['via']}; {info['site']})")
    for e in unstatic:
        print(f"    [OBSERVED-ONLY] {e}  — the dynamic sanitizer saw "
              "an ordering the static analysis missed (resolution "
              "gap: report it)")


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    package_root = Path(args.root) if args.root else \
        Path(__file__).resolve().parent
    changed: Optional[Set[str]] = None
    if args.changed is not None:
        changed = changed_files(args.changed, package_root.parent,
                                package_root.name)
    result = run_lint(
        package_root=package_root,
        docs_root=Path(args.docs) if args.docs else None,
        baseline=Path(args.baseline) if args.baseline else None,
        changed=changed)
    if args.as_json:
        print(json.dumps(result.to_doc(), indent=2))
        return 0 if result.ok else 1
    for err in result.errors:
        print(f"ERROR {err}")
    for f in result.findings:
        print(f"{f.path}:{f.line}: [{f.check}] {f.message}")
        print(f"    fingerprint: {f.fingerprint}")
    if args.show_suppressed:
        for f in result.suppressed:
            print(f"suppressed ({f.suppressed_by}) {f.path}:{f.line}: "
                  f"[{f.check}] {f.detail}")
    for fp in result.stale_baseline:
        print(f"stale baseline entry (matches nothing — remove it): {fp}")
    if args.lock_coverage:
        print_lock_coverage(result, _observed_edges(args.observed))
    n, s = len(result.findings), len(result.suppressed)
    mode = f" (changed vs {args.changed})" if changed is not None else ""
    cg = result.callgraph or {}
    cov = cg.get("resolution_coverage")
    cov_txt = (f", call resolution {cov:.0%} "
               f"({cg.get('calls_unresolved', 0)} unresolved)"
               if cov is not None else "")
    print(f"{result.files_scanned} files scanned{mode}: {n} "
          f"finding(s), {s} suppressed, {len(result.stale_baseline)} "
          f"stale baseline entr(ies){cov_txt}")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
