"""``cs`` command-line interface.

Parity with the reference's CLI subcommands (reference: cli/cook/subcommands/
— submit, show, wait, jobs, kill, usage, cat, tail, ls, ssh, plus admin
queue/limits).  Sandbox access (cat/tail/ls) goes through the instance's
``output_url`` file server, the analog of the Mesos agent / sidecar files
API (reference: cli/cook/mesos.py; sidecar file_server.py).  Cluster
selection via --url or the COOK_URL environment variable / ~/.cs.json
config federation list (reference: cli/cook/querying.py multi-cluster
federation, deduped by uuid).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.parse
import urllib.request
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from ..client import JobClient, JobClientError

CONFIG_PATH = Path.home() / ".cs.json"


def load_cs_config() -> Optional[Dict]:
    """Parsed ~/.cs.json; {} when absent, None when present but CORRUPT
    (callers that WRITE must refuse on None — falling back to {} and
    rewriting would destroy the user's whole config)."""
    if not CONFIG_PATH.exists():
        return {}
    try:
        return json.loads(CONFIG_PATH.read_text())
    except (OSError, json.JSONDecodeError):
        return None


def load_urls(args) -> List[str]:
    # clusters named by entity refs on this invocation come first
    refs = list(getattr(args, "ref_urls", []) or [])
    if args.url:
        return refs + [args.url]
    env = os.environ.get("COOK_URL")
    if env:
        return refs + env.split(",")
    cfg = load_cs_config()
    if cfg is None:
        # a corrupt config must not silently reroute work to localhost
        raise OSError(f"{CONFIG_PATH} exists but is not valid JSON; "
                      "fix or remove it (or pass --url)")
    if cfg:
        return refs + [c["url"] for c in cfg.get("clusters", [])]
    return refs or ["http://127.0.0.1:12321"]


def clients(args) -> List[JobClient]:
    user = args.user or os.environ.get("COOK_USER") \
        or os.environ.get("USER", "anonymous")
    return [JobClient(url, user=user) for url in load_urls(args)]


def resolve_refs(args, tokens: List[str],
                 allow_stdin: bool = True) -> Optional[List[str]]:
    """Entity refs -> uuids (reference: cli/cook/querying.py
    parse_entity_refs + the test_entity_refs_* integration scenarios).

    Accepts bare uuids, ``https://cluster/jobs/<uuid>`` refs (case-
    insensitive, optional trailing slash on the cluster part), and
    ``...?job=<uuid>`` query-string refs; a ref's cluster URL is added to
    this invocation's federation list.  With no tokens, refs are read
    from stdin (one per whitespace-separated word) so ``cs jobs | cs
    kill`` pipes compose.  Duplicate uuids are an error (the reference
    refuses them for show/wait/kill alike) -> None."""
    if not tokens and allow_stdin and not sys.stdin.isatty():
        tokens = sys.stdin.read().split()
    uuids: List[str] = []
    extra_urls: List[str] = []
    for tok in tokens:
        tok = tok.strip()
        if not tok:
            continue
        if tok.lower().startswith(("http://", "https://")):
            parsed = urllib.parse.urlparse(tok)
            qs = urllib.parse.parse_qs(parsed.query)
            if qs.get("job"):
                uuid = qs["job"][0]
            else:
                parts = [p for p in parsed.path.split("/") if p]
                uuid = parts[-1] if parts else ""
                if uuid in ("jobs", "rawscheduler", "instances", "group"):
                    uuid = ""  # a bare endpoint path carries no uuid
            if not uuid:
                print(f"error: malformed entity ref {tok}", file=sys.stderr)
                return None
            extra_urls.append(f"{parsed.scheme}://{parsed.netloc}")
            uuids.append(uuid.lower())
        else:
            uuids.append(tok.lower())
    if len(set(uuids)) != len(uuids):
        dupes = sorted({u for u in uuids if uuids.count(u) > 1})
        print(f"error: duplicate uuids {', '.join(dupes)}", file=sys.stderr)
        return None
    if not uuids:
        print("error: at least one uuid or entity ref is required",
              file=sys.stderr)
        return None
    if extra_urls:
        args.ref_urls = list(dict.fromkeys(extra_urls))
    return uuids


def federated_owners(args, uuids: List[str]
                     ) -> Tuple[List[Tuple[JobClient, List[str]]],
                                List[str]]:
    """Partition uuids by the federation cluster that owns them
    (reference: querying.py routes each entity to its cluster before
    acting on it).  Returns ([(client, owned_uuids)...], missing).

    A cluster that cannot be queried is reported on stderr when any uuid
    ends up unclaimed: an OUTAGE of the owning cluster must be
    distinguishable from a uuid no cluster has ever seen (the caller's
    "no cluster knows" message alone would misreport the former)."""
    unclaimed = list(uuids)
    owned: List[Tuple[JobClient, List[str]]] = []
    errors = []
    for client in clients(args):
        if not unclaimed:
            break
        try:
            found = {j["uuid"] for j in client.query(unclaimed,
                                                     partial=True)}
        except (JobClientError, OSError) as e:
            errors.append(f"{client.url}: {e}")
            continue
        mine = [u for u in unclaimed if u in found]
        if mine:
            owned.append((client, mine))
            unclaimed = [u for u in unclaimed if u not in found]
    if unclaimed and errors:
        print("warning: some clusters could not be queried (the uuids "
              "reported missing may live there):", file=sys.stderr)
        print("\n".join(errors), file=sys.stderr)
    return owned, unclaimed


def federated_query(args, uuids: List[str]) -> List[Dict]:
    """Query every configured cluster, dedupe by uuid (reference:
    cli/cook/querying.py)."""
    seen: Dict[str, Dict] = {}
    errors = []
    for client in clients(args):
        try:
            # partial: a cluster that owns only SOME of the uuids must
            # return that subset, not 404 the whole query
            for job in client.query(uuids, partial=True):
                seen.setdefault(job["uuid"], job)
        except (JobClientError, OSError) as e:
            errors.append(f"{client.url}: {e}")
    missing = [u for u in uuids if u not in seen]
    if missing and errors:
        print("\n".join(errors), file=sys.stderr)
    return [seen[u] for u in uuids if u in seen]


def out(payload) -> None:
    print(json.dumps(payload, indent=2, default=str))


def cmd_submit(args) -> int:
    """Submit job(s) (reference: cli/cook/subcommands/submit.py): the
    command comes from argv, or — when absent — from stdin, one job per
    non-empty line; ``--raw`` instead reads full JSON spec(s) (an object,
    a list, or a ``{"jobs": [...], "groups": [...]}`` body) from stdin
    and refuses argv commands."""
    groups = None
    if args.raw:
        if args.command:
            print("error: --raw reads specs from stdin; it cannot be "
                  "combined with a command argument", file=sys.stderr)
            return 1
        if (args.gang_size is not None or args.gang_topology
                or args.gang_policy or args.gang_min is not None
                or args.gang_max is not None):
            print("error: gang flags do not apply to --raw specs; "
                  'submit a full body {"jobs": [...], "groups": [{..., '
                  '"gang": {...}}]} instead', file=sys.stderr)
            return 1
        if args.command_prefix is not None:
            print("error: --command-prefix does not apply to --raw "
                  "specs", file=sys.stderr)
            return 1
        if sys.stdin.isatty():
            print("error: --raw expects JSON spec(s) on stdin",
                  file=sys.stderr)
            return 1
        try:
            raw = json.loads(sys.stdin.read())
        except json.JSONDecodeError as e:
            print(f"error: malformed --raw JSON: {e}", file=sys.stderr)
            return 1
        if isinstance(raw, dict) and "jobs" in raw:
            # full submit body {"jobs": [...], "groups": [...]} — the
            # raw form that can express group/gang membership
            specs = raw["jobs"]
            groups = raw.get("groups")
            if not isinstance(specs, list):
                print('error: --raw "jobs" must be a list of job '
                      "specs", file=sys.stderr)
                return 1
        else:
            specs = raw if isinstance(raw, list) else [raw]
    else:
        if args.command:
            commands = [" ".join(args.command)]
        elif sys.stdin.isatty():
            commands = []  # interactive with no command: error, not a hang
        else:
            commands = [line.strip() for line in sys.stdin.read().splitlines()
                        if line.strip()]
        if not commands:
            print("error: no command given (argv or stdin)",
                  file=sys.stderr)
            return 1
        # --command-prefix flag, falling back to the config file's
        # defaults.submit.command-prefix (reference: subcommands/submit.py
        # job-template command-prefix + test_submit_with_command_prefix)
        prefix = args.command_prefix
        if prefix is None:
            cfg = load_cs_config()
            if cfg is None:
                # a corrupt config must not silently drop the user's
                # configured command-prefix
                print(f"error: {CONFIG_PATH} exists but is not valid "
                      "JSON; fix or remove it (or pass "
                      "--command-prefix)", file=sys.stderr)
                return 1
            prefix = (cfg.get("defaults", {}).get("submit", {})
                      .get("command-prefix", ""))
        if prefix and not isinstance(prefix, str):
            print("error: defaults.submit.command-prefix must be a "
                  f"string, got {prefix!r}", file=sys.stderr)
            return 1
        if prefix:
            commands = [prefix + c for c in commands]
        base: Dict = {}
        for field in ("name", "pool"):
            value = getattr(args, field)
            if value:
                base[field] = value
        for field in ("cpus", "mem", "gpus", "priority", "max_retries",
                      "ports"):
            value = getattr(args, field)
            if value is not None:
                base[field] = value
        if args.env:
            base["env"] = dict(kv.split("=", 1) for kv in args.env)
        if args.label:
            base["labels"] = dict(kv.split("=", 1) for kv in args.label)
        if args.constraint:
            base["constraints"] = [c.split(":", 2) for c in args.constraint]
        if args.docker_image:
            base["container"] = {"image": args.docker_image,
                                 "volumes": list(args.volume or [])}
        if args.uri:
            base["uris"] = [{"value": u} for u in args.uri]
        if args.executor:
            base["executor"] = args.executor
        if args.application:
            name, _, version = args.application.partition(":")
            base["application"] = {"name": name, "version": version or "0"}
        specs = [{**base, "command": c} for c in commands]
        if args.gang_size is not None:
            # ONE command fans out into gang_size member jobs sharing a
            # gang group (all-or-nothing placement, docs/GANG.md)
            if args.gang_size < 1:
                print("error: --gang-size must be >= 1", file=sys.stderr)
                return 1
            if len(specs) != 1:
                print("error: --gang-size submits ONE command as N "
                      "member jobs; got multiple commands",
                      file=sys.stderr)
                return 1
            import uuid as uuidlib
            guuid = str(uuidlib.uuid4())
            specs = [{**specs[0], "group": guuid}
                     for _ in range(args.gang_size)]
            gang: Dict = {"size": args.gang_size}
            if args.gang_topology:
                gang["topology"] = args.gang_topology
            if args.gang_policy:
                gang["policy"] = args.gang_policy
            # elastic bounds (docs/GANG.md elasticity): the server
            # validates 1 <= min <= max <= size; pre-check the obvious
            # inversions here for a clearer error than a 400
            if args.gang_min is not None:
                if args.gang_min < 1 or args.gang_min > args.gang_size:
                    print("error: --gang-min must be in "
                          "[1, --gang-size]", file=sys.stderr)
                    return 1
                gang["min"] = args.gang_min
            if args.gang_max is not None:
                if args.gang_max > args.gang_size \
                        or args.gang_max < (args.gang_min or 1):
                    print("error: --gang-max must be in "
                          "[--gang-min, --gang-size]", file=sys.stderr)
                    return 1
                gang["max"] = args.gang_max
            groups = [{"uuid": guuid, "gang": gang}]
        elif args.gang_topology or args.gang_policy \
                or args.gang_min is not None or args.gang_max is not None:
            print("error: --gang-topology/--gang-policy/--gang-min/"
                  "--gang-max require --gang-size", file=sys.stderr)
            return 1
    client = clients(args)[0]
    uuids = client.submit(specs, groups=groups)
    for u in uuids:
        print(u)
    return 0


def cmd_show(args) -> int:
    uuids = resolve_refs(args, args.uuid)
    if uuids is None:
        return 1
    jobs = federated_query(args, uuids)
    if not jobs:
        print("no matching jobs", file=sys.stderr)
        return 1
    out(jobs)
    return 0


def cmd_jobs(args) -> int:
    client = clients(args)[0]
    states = args.state.split("+") if args.state else None
    jobs = client.jobs(user=args.for_user or client.user, states=states)
    if args.one_per_line:
        # uuid-per-line output feeds `cs show/wait/kill` pipes (reference:
        # subcommands/jobs.py --one-per-line + the piping scenarios)
        for j in jobs:
            print(j["uuid"])
    else:
        out(jobs)
    return 0


def cmd_wait(args) -> int:
    uuids = resolve_refs(args, args.uuid)
    if uuids is None:
        return 1
    owned, missing = federated_owners(args, uuids)
    if missing:
        print(f"error: no cluster knows {', '.join(missing)}",
              file=sys.stderr)
        return 1
    jobs: List[Dict] = []
    deadline = time.monotonic() + args.timeout
    for client, mine in owned:
        # one SHARED deadline across clusters — N owners must not
        # multiply the user's --timeout by N
        try:
            jobs.extend(client.wait(
                mine, timeout_s=max(0.0, deadline - time.monotonic())))
        except TimeoutError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    out(jobs)
    failed = [j for j in jobs
              if not any(i["status"] == "success"
                         for i in j.get("instances", []))]
    return 1 if failed else 0


def cmd_kill(args) -> int:
    uuids = resolve_refs(args, args.uuid)
    if uuids is None:
        return 1
    owned, missing = federated_owners(args, uuids)
    if missing:
        print(f"error: no cluster knows {', '.join(missing)}",
              file=sys.stderr)
        return 1
    # always a list, one entry per owning cluster — a stable shape no
    # matter how the uuids were distributed
    out([client.kill(mine) for client, mine in owned])
    return 0


def cmd_retry(args) -> int:
    """Raise retries on jobs and/or groups (reference: subcommands/
    retry.py over PUT /retry — multiple uuids, groups, retries or
    increment, failed-only)."""
    if (args.retries is None) == (args.increment is None):
        print("error: specify exactly one of --retries or --increment",
              file=sys.stderr)
        return 1
    uuids: List[str] = []
    if args.uuid:
        # entity refs, exactly like show/wait/kill
        resolved = resolve_refs(args, args.uuid, allow_stdin=False)
        if resolved is None:
            return 1
        uuids = resolved
    elif not args.group:
        # no positional refs and no groups: read uuids from a pipe
        # (`cs jobs -1 | cs retry --retries 3`)
        resolved = resolve_refs(args, [])
        if resolved is None:
            return 1
        uuids = resolved
    results = []
    if uuids:
        # route each uuid to its OWNING cluster (same federation
        # semantics as kill/wait)
        owned, missing = federated_owners(args, uuids)
        if missing:
            print(f"error: no cluster knows {', '.join(missing)}",
                  file=sys.stderr)
            return 1
        for client, mine in owned:
            results.append(client.retry(
                jobs=mine, retries=args.retries,
                increment=args.increment, failed_only=args.failed_only))
    for guuid in args.group or []:
        # group ownership isn't resolvable through the jobs query; try
        # each federation cluster, keeping the first that knows it
        last_err: Optional[Exception] = None
        for client in clients(args):
            try:
                results.append(client.retry(
                    groups=[guuid], retries=args.retries,
                    increment=args.increment,
                    failed_only=args.failed_only))
                break
            except (JobClientError, OSError) as e:
                last_err = e
        else:
            print(f"error: group {guuid}: {last_err}", file=sys.stderr)
            return 1
    out(results if len(results) != 1 else results[0])
    return 0


def cmd_usage(args) -> int:
    client = clients(args)[0]
    out(client.usage(args.for_user or client.user, pool=args.pool,
                     group_breakdown=args.group_breakdown))
    return 0


def cmd_unscheduled(args) -> int:
    uuids = resolve_refs(args, args.uuid)
    if uuids is None:
        return 1
    owned, missing = federated_owners(args, uuids)
    if missing:
        print(f"error: no cluster knows {', '.join(missing)}",
              file=sys.stderr)
        return 1
    merged: List[Dict] = []
    for client, mine in owned:
        merged.extend(client.unscheduled_jobs(mine))
    out(merged)
    return 0


def cmd_pools(args) -> int:
    out(clients(args)[0].pools())
    return 0


def cmd_admin(args) -> int:
    client = clients(args)[0]
    if args.admin_cmd == "queue":
        out(client.queue())
    elif args.admin_cmd == "share":
        if args.set:
            pools = {args.pool or "default":
                     dict((kv.split("=")[0], float(kv.split("=")[1]))
                          for kv in args.set)}
            out(client.set_share(args.for_user, pools))
        else:
            out(client.get_share(args.for_user or client.user))
    elif args.admin_cmd == "quota":
        if args.set:
            pools = {args.pool or "default":
                     dict((kv.split("=")[0], float(kv.split("=")[1]))
                          for kv in args.set)}
            out(client.set_quota(args.for_user, pools))
        else:
            out(client.get_quota(args.for_user or client.user))
    elif args.admin_cmd == "usage":
        # all-users report by default (admin-only server side);
        # --for-user scopes it like the other admin subcommands
        out(client.usage(args.for_user, pool=args.pool))
    elif args.admin_cmd == "stats":
        if any(v is not None for v in (args.status, args.start, args.end,
                                       args.name)):
            # forward everything given: the server's validation explains
            # what's missing rather than silently serving the wrong report
            out(client.stats(status=args.status, start=args.start,
                             end=args.end, name=args.name))
        else:
            out(client.stats())
    elif args.admin_cmd == "rebalancer":
        if args.set:
            body = {}
            for kv in args.set:
                k, eq, v = kv.partition("=")
                if not eq or not k:
                    raise JobClientError(
                        400, f"malformed --set {kv!r} (expected key=value)")
                if v.lower() in ("true", "false"):
                    body[k] = v.lower() == "true"
                else:
                    try:
                        # integral stays int so the server validates
                        # instead of silently truncating (max-preemption)
                        body[k] = int(v) if v.lstrip("-").isdigit() \
                            else float(v)
                    except ValueError:
                        raise JobClientError(
                            400, f"malformed --set value {kv!r}")
            out(client.set_rebalancer(body))
        else:
            out(client.settings().get("rebalancer", {}))
    return 0


def _fmt_ts(ms) -> str:
    import datetime
    try:
        return datetime.datetime.fromtimestamp(
            ms / 1000.0).strftime("%H:%M:%S")
    except (OverflowError, OSError, ValueError):
        return str(ms)


def cmd_why(args) -> int:
    """``cs why <uuid>`` — the whole lifecycle, human-readable: every
    audit event (submit, rank position + DRU, skip/defer reasons, launch
    intent/ack, instance transitions, preemption with the DRU delta,
    terminal), then — for a still-waiting job — the unscheduled
    explainer's live reasons.  The trail survives leader failover
    (journal-backed lane, docs/OBSERVABILITY.md), so this works for jobs
    scheduled by a previous leader too.  ``--json`` emits the raw
    timeline document; ``--perfetto FILE`` writes the newest cycle's
    trace with this job's events stitched in as a dedicated track."""
    uuids = resolve_refs(args, args.uuid)
    if uuids is None:
        return 1
    client = clients(args)[0]
    rc = 0
    shown = []
    for uuid in uuids:
        try:
            doc = client.job_timeline(uuid)
        except JobClientError as e:
            print(f"error: {uuid}: {e}", file=sys.stderr)
            rc = 1
            continue
        shown.append((uuid, doc.get("timeline", [])))
        if args.json:
            out(doc)
            continue
        head = f"job {uuid}"
        if doc.get("user"):
            head += f" (user={doc['user']}, pool={doc.get('pool')})"
        if doc.get("state"):
            head += f" — {doc['state']}"
        if doc.get("user_dru") is not None:
            head += f" [user DRU {doc['user_dru']:.3f}]"
        print(head)
        for ev in doc.get("timeline", []):
            data = dict(ev.get("data") or {})
            reason = data.pop("reason", None)
            label = ev["kind"] + (f":{reason}" if reason else "")
            extras = " ".join(f"{k}={v}" for k, v in data.items()
                              if v is not None and k != "pool")
            times = _fmt_ts(ev["ts"])
            if ev.get("count", 1) > 1:
                times += (f" (x{ev['count']}, last "
                          f"{_fmt_ts(ev.get('ts_last', ev['ts']))})")
            print(f"  {times}  {label}" + (f"  {extras}" if extras
                                           else ""))
        for r in doc.get("reasons", []):
            print(f"  why waiting: {r['reason']}")
    if args.perfetto and shown:
        # Prefer the server's stitched per-job export for the FIRST job:
        # the cycle that launched it, the submission request's span
        # track (http.request -> journal -> replication ack wait), and
        # its audit lane in one timeline (docs/OBSERVABILITY.md
        # "tracing one request").  Remaining jobs ride along as extra
        # tracks (ONE export — a per-uuid write would silently keep
        # only the last job).
        from ..utils.tracing import job_track_events
        trace = None
        extra = shown[1:]
        try:
            trace = client.debug_trace(job=shown[0][0])
        except (JobClientError, OSError):
            pass
        if trace is None:
            # no trace recorded for the job (old server / trace ring
            # rolled over): fall back to the newest cycle's flamegraph
            # with every job's audit track appended client-side
            cycles = client.debug_cycles(limit=1).get("cycles", [])
            if cycles and cycles[-1].get("trace_id"):
                trace = client.debug_trace(cycles[-1]["trace_id"])
                extra = shown
        if trace is not None:
            for i, (uuid, timeline) in enumerate(extra):
                trace["traceEvents"].extend(
                    job_track_events(uuid, timeline, tid=16 + i))
            with open(args.perfetto, "w") as f:
                json.dump(trace, f)
            print(f"wrote perfetto trace with {len(shown)} job "
                  f"track(s) to {args.perfetto}", file=sys.stderr)
        else:
            print("no cycle trace available for --perfetto",
                  file=sys.stderr)
    return rc


def cmd_debug(args) -> int:
    """Flight-recorder access: ``cs debug cycles`` lists recent per-cycle
    records; ``cs debug trace [TRACE_ID]`` exports one cycle's spans as
    Chrome trace-event JSON (default: the newest recorded cycle) for
    chrome://tracing / ui.perfetto.dev; ``cs debug faults`` dumps the
    degradation panel — armed fault points, per-cluster circuit-breaker
    states, and open launch intents (docs/ROBUSTNESS.md); ``cs debug
    replication`` dumps the failover panel — per-follower offsets,
    min_acked, synced set, the candidate positions published into
    the election medium, plus the node's SERVING role: a standby's
    read-fleet block (reads served, local apply offset vs mirrored
    head, staleness bytes/age) and a leader's group-commit batching
    counters (docs/DEPLOY.md read fleet).  On a PARTITIONED write
    plane the panel carries a ``partitions`` block — per-partition
    journal head, lease epoch, group-commit stage, declared pool
    groups — plus the cross-partition ``summary_exchange`` state
    (docs/DEPLOY.md partitioned write plane); ``cs debug health`` is the one-shot roll-up
    (SLO burn rates, breakers, replication lag, pipeline depth, repack
    counters, audit queue depth) replacing five /debug/* fetches;
    ``cs debug requests`` lists the serving plane's recent + slow
    captured requests with per-phase breakdowns
    (docs/OBSERVABILITY.md); ``cs debug optimizer`` dumps the goodput
    loop's decision panel — last per-pool decisions (grow budget,
    shrink pressure, preemption budget, autoscale target), cycle
    counts/errors, and the elastic resize plane's live state
    (docs/GANG.md elasticity); ``cs debug fleet`` dumps the federated
    fleet panel — every known member's health, role, last-scrape age,
    staleness, SLO burn, and saturation hot-spots, with unreachable
    members surfaced as rows (up=false), not gaps
    (docs/OBSERVABILITY.md debugging the fleet); ``cs debug storage``
    dumps the persistence-integrity panel — per-partition scrub
    progress, last verified offset, corruption/repair counters,
    checkpoint manifest status, and a follower's mirror poison state
    (docs/DEPLOY.md corrupted-journal runbook)."""
    client = clients(args)[0]
    if args.debug_cmd == "cycles":
        out(client.debug_cycles(limit=args.limit))
        return 0
    if args.debug_cmd == "faults":
        out(client.debug_faults())
        return 0
    if args.debug_cmd == "replication":
        out(client.debug_replication())
        return 0
    if args.debug_cmd == "health":
        out(client.debug_health())
        return 0
    if args.debug_cmd == "requests":
        out(client.debug_requests(limit=args.limit))
        return 0
    if args.debug_cmd == "optimizer":
        out(client.debug_optimizer())
        return 0
    if args.debug_cmd == "fleet":
        out(client.debug_fleet())
        return 0
    if args.debug_cmd == "storage":
        out(client.debug_storage())
        return 0
    trace_id = args.trace_id
    if not trace_id:
        cycles = client.debug_cycles(limit=1).get("cycles", [])
        if not cycles or not cycles[-1].get("trace_id"):
            print("error: no cycle records yet (is the scheduler "
                  "cycling?); pass an explicit TRACE_ID", file=sys.stderr)
            return 1
        trace_id = cycles[-1]["trace_id"]
    trace = client.debug_trace(trace_id)
    if args.out_file:
        with open(args.out_file, "w") as f:
            json.dump(trace, f)
        print(f"wrote {len(trace.get('traceEvents', []))} events to "
              f"{args.out_file} (open in chrome://tracing or "
              "https://ui.perfetto.dev)", file=sys.stderr)
    else:
        out(trace)
    return 0


def _resolve_instance(args, uuid: str) -> Tuple[Dict, Dict]:
    """uuid (job or instance) -> (job, instance) for sandbox access
    (reference: cli/cook/querying.py query_unique_and_run)."""
    jobs = federated_query(args, [uuid])
    if jobs:
        job = jobs[0]
        insts = job.get("instances", [])
        if not insts:
            raise JobClientError(404, f"job {uuid} has no instances")
        # prefer the running/latest attempt, as the reference does
        insts = sorted(insts, key=lambda i: (i["status"] == "running",
                                             i.get("start_time") or 0))
        return job, insts[-1]
    for client in clients(args):
        try:
            inst = client.instance(uuid)
            job = client.query([inst["job_uuid"]])[0]
            return job, inst
        except (JobClientError, OSError):
            continue
    raise JobClientError(404, f"no job or instance {uuid}")


def _files_get(inst: Dict, endpoint: str, params: Dict) -> bytes:
    base = inst.get("output_url")
    if not base:
        raise JobClientError(
            503, f"instance {inst['task_id']} has no sandbox file server "
                 "(output_url) yet")
    url = (base.rstrip("/") + "/files/" + endpoint + "?"
           + urllib.parse.urlencode(params))
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.read()


def cmd_cat(args) -> int:
    """Stream a sandbox file to stdout (reference: subcommands/cat.py)."""
    _job, inst = _resolve_instance(args, args.uuid[0])
    data = _files_get(inst, "download", {"path": args.path})
    sys.stdout.buffer.write(data)
    sys.stdout.buffer.flush()
    return 0


def cmd_tail(args) -> int:
    """Print the last N lines of a sandbox file (reference:
    subcommands/tail.py; reads backwards via the offset/length API)."""
    if args.lines <= 0:
        return 0
    _job, inst = _resolve_instance(args, args.uuid[0])
    from ..agent.file_server import MAX_READ_LENGTH
    probe = json.loads(_files_get(inst, "read", {"path": args.path}))
    size = probe.get("offset", 0)
    # clamp to the server's per-read cap: a larger request would be
    # silently shortened and leave holes between stitched chunks
    want = min(args.bytes if args.bytes else 64 * 1024, MAX_READ_LENGTH)
    chunk: bytes = b""
    offset = size
    while offset > 0 and chunk.count(b"\n") <= args.lines \
            and len(chunk) < 16 * want:
        step = min(want, offset)
        offset -= step
        got = json.loads(_files_get(inst, "read", {
            "path": args.path, "offset": offset, "length": step}))
        chunk = got["data"].encode("utf-8", "surrogateescape") + chunk
    lines = chunk.splitlines(keepends=True)[-args.lines:]
    sys.stdout.buffer.write(b"".join(lines))
    sys.stdout.buffer.flush()
    return 0


def cmd_ls(args) -> int:
    """List sandbox directory contents (reference: subcommands/ls.py)."""
    _job, inst = _resolve_instance(args, args.uuid[0])
    entries = json.loads(_files_get(inst, "browse",
                                    {"path": args.path or ""}))
    if args.json:
        out(entries)
        return 0
    for e in entries:
        print(f"{e.get('mode', '??????????')} {e.get('nlink', 1):>3} "
              f"{e.get('size', 0):>12} {e.get('path', '')}")
    return 0


def cmd_ssh(args) -> int:
    """exec ssh to the instance's host, landing in the sandbox directory
    (reference: subcommands/ssh.py execs ssh <host> -t cd <sandbox>)."""
    _job, inst = _resolve_instance(args, args.uuid[0])
    hostname = inst.get("hostname")
    if not hostname:
        print(f"instance {inst['task_id']} has no hostname", file=sys.stderr)
        return 1
    sandbox = inst.get("sandbox_directory") or "~"
    command = ["ssh", "-t", hostname, f"cd {sandbox} ; exec $SHELL -l"]
    if args.dry_run:
        print(" ".join(command))
        return 0
    os.execvp("ssh", command)  # pragma: no cover - replaces the process


def cmd_lint(args) -> int:
    """``cs lint [--json]`` — run the static analysis passes locally
    (no server round-trip; the lint reads source, not state) with the
    ``python -m cook_tpu.lint`` exit contract: 0 = clean tree, 1 = new
    unsuppressed findings (docs/ANALYSIS.md)."""
    from ..lint import main as lint_main
    argv = []
    if args.as_json:
        argv.append("--json")
    if args.root:
        argv += ["--root", args.root]
    if args.docs:
        argv += ["--docs", args.docs]
    if args.baseline:
        argv += ["--baseline", args.baseline]
    if args.show_suppressed:
        argv.append("--show-suppressed")
    if args.changed is not None:
        argv += ["--changed", args.changed]
    if args.lock_coverage:
        argv.append("--lock-coverage")
    if args.observed:
        argv += ["--observed", args.observed]
    return lint_main(argv)


def cmd_config(args) -> int:
    """Get/set dotted config keys in ~/.cs.json (reference:
    subcommands/config.py — ``cs config defaults.submit.command-prefix
    'time '`` writes, ``cs config KEY`` reads).  Merges into the existing
    file: clobbering it would silently delete unrelated keys (the
    plugins mapping, custom settings)."""
    cfg = load_cs_config()
    if cfg is None:
        # a corrupt file must never be silently replaced: a write from
        # here would destroy every unrelated setting
        print(f"error: {CONFIG_PATH} exists but is not valid JSON; "
              "fix or remove it first", file=sys.stderr)
        return 1
    if args.set_url:
        cfg["clusters"] = [{"name": "default", "url": args.set_url}]
        CONFIG_PATH.write_text(json.dumps(cfg, indent=2))
        out(cfg)
        return 0
    if args.key is None:
        cfg.setdefault("clusters", [{"name": "default", "url": u}
                                    for u in load_urls(args)])
        out(cfg)
        return 0
    path = args.key.split(".")
    if args.value is None:  # read
        node = cfg
        for part in path:
            if not isinstance(node, dict) or part not in node:
                print(f"configuration key '{args.key}' not found",
                      file=sys.stderr)
                return 1
            node = node[part]
        out(node)
        return 0
    node = cfg  # write: create intermediate tables as needed
    for i, part in enumerate(path[:-1]):
        if part not in node:
            node[part] = {}
        node = node[part]
        if not isinstance(node, dict):
            # a typo'd path through an existing scalar/list must not
            # silently clobber it
            print(f"error: '{'.'.join(path[:i + 1])}' exists and is not "
                  "a table; refusing to overwrite it", file=sys.stderr)
            return 1
    try:
        value: Any = json.loads(args.value)  # "5" -> 5, "true" -> True
    except ValueError:
        value = args.value                   # plain string
    node[path[-1]] = value
    CONFIG_PATH.write_text(json.dumps(cfg, indent=2))
    out({args.key: value})
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cs", description="cook_tpu scheduler CLI")
    p.add_argument("--url", help="scheduler URL")
    p.add_argument("--user", help="submit/query as this user")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("submit", help="submit a job")
    sp.add_argument("--name")
    sp.add_argument("--pool")
    sp.add_argument("--cpus", type=float)
    sp.add_argument("--mem", type=float)
    sp.add_argument("--gpus", type=float)
    sp.add_argument("--priority", type=int)
    sp.add_argument("--max-retries", dest="max_retries", type=int)
    sp.add_argument("--env", action="append")
    sp.add_argument("--label", action="append")
    sp.add_argument("--constraint", action="append",
                    help="attr:EQUALS:value")
    sp.add_argument("--ports", type=int,
                    help="host ports to assign (PORT0.. in the task env)")
    sp.add_argument("--docker-image", dest="docker_image",
                    help="container image to run the command in")
    sp.add_argument("--volume", action="append",
                    help="host:container bind for --docker-image")
    sp.add_argument("--uri", action="append",
                    help="artifact fetched into the sandbox before the "
                         "command runs")
    sp.add_argument("--executor", choices=["cook", ""],
                    help="'cook' wraps the command in the progress-"
                         "tracking executor")
    sp.add_argument("--application",
                    help="submitting application, name[:version]")
    sp.add_argument("--gang-size", dest="gang_size", type=int,
                    help="submit the command as an all-or-nothing gang "
                         "of N member jobs (one group; docs/GANG.md)")
    sp.add_argument("--gang-topology", dest="gang_topology",
                    help="host attribute every gang member's host must "
                         "share, e.g. slice-id")
    sp.add_argument("--gang-policy", dest="gang_policy",
                    choices=["requeue", "kill"],
                    help="what a member failure does to the rest of the "
                         "gang (default requeue)")
    sp.add_argument("--gang-min", dest="gang_min", type=int,
                    help="ELASTIC gang: minimum member count the gang "
                         "may legally run at (docs/GANG.md elasticity; "
                         "default = --gang-size, i.e. rigid)")
    sp.add_argument("--gang-max", dest="gang_max", type=int,
                    help="ELASTIC gang: maximum member count to grow "
                         "to (default = --gang-size)")
    sp.add_argument("--raw", action="store_true",
                    help="read full JSON job spec(s) from stdin")
    sp.add_argument("--command-prefix", dest="command_prefix",
                    help="string prepended to every submitted command "
                         "(default: config defaults.submit.command-prefix)")
    sp.add_argument("command", nargs="*",
                    help="command to run; read from stdin when omitted "
                         "(one job per line)")
    sp.set_defaults(fn=cmd_submit)

    for name, fn in (("show", cmd_show), ("wait", cmd_wait),
                     ("kill", cmd_kill), ("unscheduled", cmd_unscheduled)):
        sp = sub.add_parser(name)
        # zero positional refs -> read uuids/entity-refs from stdin, so
        # `cs jobs --json | cs kill` pipes compose (reference:
        # test_piping_from_jobs_to_kill_show_wait)
        sp.add_argument("uuid", nargs="*",
                        help="job uuid or https://cluster/jobs/<uuid> "
                             "entity ref; stdin when omitted")
        if name == "wait":
            sp.add_argument("--timeout", type=float, default=300.0)
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("retry")
    sp.add_argument("uuid", nargs="*", help="job uuid(s)")
    sp.add_argument("--retries", type=int)
    sp.add_argument("--increment", type=int,
                    help="raise retries BY this much instead of setting")
    sp.add_argument("--group", action="append",
                    help="retry a whole group (repeatable)")
    sp.add_argument("--failed-only", dest="failed_only",
                    action=argparse.BooleanOptionalAction, default=None,
                    help="only resurrect failed members; --no-failed-only "
                         "raises retries on everything (server default: "
                         "failed-only iff groups given)")
    sp.set_defaults(fn=cmd_retry)

    sp = sub.add_parser("jobs", help="list your jobs")
    sp.add_argument("--for-user", dest="for_user")
    sp.add_argument("--state", help="waiting+running+completed")
    sp.add_argument("-1", "--one-per-line", dest="one_per_line",
                    action="store_true",
                    help="print bare uuids, one per line (for piping)")
    sp.set_defaults(fn=cmd_jobs)

    sp = sub.add_parser("usage")
    sp.add_argument("--for-user", dest="for_user")
    sp.add_argument("--pool", help="restrict the report to one pool")
    sp.add_argument("--group-breakdown", dest="group_breakdown",
                    action="store_true",
                    help="split running usage by job group")
    sp.set_defaults(fn=cmd_usage)

    sp = sub.add_parser("pools")
    sp.set_defaults(fn=cmd_pools)

    sp = sub.add_parser("admin")
    sp.add_argument("admin_cmd",
                    choices=["queue", "share", "quota", "stats",
                             "usage", "rebalancer"])
    sp.add_argument("--for-user", dest="for_user")
    sp.add_argument("--pool")
    sp.add_argument("--set", action="append",
                    help="resource=value (cpus=10)")
    # windowed instance-stats args (stats subcommand)
    sp.add_argument("--status", help="unknown|running|success|failed")
    sp.add_argument("--start", help="epoch-ms or ISO-8601")
    sp.add_argument("--end", help="epoch-ms or ISO-8601")
    sp.add_argument("--name", help="job-name filter (* wildcard)")
    sp.set_defaults(fn=cmd_admin)

    sp = sub.add_parser("cat", help="print a sandbox file")
    sp.add_argument("uuid", nargs=1)
    sp.add_argument("path")
    sp.set_defaults(fn=cmd_cat)

    sp = sub.add_parser("tail", help="tail a sandbox file")
    sp.add_argument("uuid", nargs=1)
    sp.add_argument("path")
    sp.add_argument("--lines", type=int, default=10)
    sp.add_argument("--bytes", type=int, default=0,
                    help="read granularity override")
    sp.set_defaults(fn=cmd_tail)

    sp = sub.add_parser("ls", help="list sandbox files")
    sp.add_argument("uuid", nargs=1)
    sp.add_argument("path", nargs="?", default="")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_ls)

    sp = sub.add_parser("ssh", help="ssh to the instance's sandbox")
    sp.add_argument("uuid", nargs=1)
    sp.add_argument("--dry-run", dest="dry_run", action="store_true")
    sp.set_defaults(fn=cmd_ssh)

    sp = sub.add_parser("why", help="why isn't my job running: the "
                                    "per-job scheduling audit timeline "
                                    "+ live unscheduled reasons")
    sp.add_argument("uuid", nargs="*",
                    help="job uuid or entity ref; stdin when omitted")
    sp.add_argument("--json", action="store_true",
                    help="raw timeline document instead of the "
                         "rendered lifecycle")
    sp.add_argument("--perfetto", metavar="FILE",
                    help="also export the newest cycle's Chrome trace "
                         "with this job's events as a dedicated track")
    sp.set_defaults(fn=cmd_why)

    sp = sub.add_parser("debug", help="flight recorder: cycle records, "
                                      "Perfetto trace export, fault/"
                                      "breaker states, replication/"
                                      "failover panel")
    sp.add_argument("debug_cmd",
                    choices=["cycles", "trace", "faults", "replication",
                             "health", "requests", "optimizer", "fleet",
                             "storage"])
    sp.add_argument("trace_id", nargs="?",
                    help="trace to export (trace subcommand); default: "
                         "the newest cycle record's trace")
    sp.add_argument("--limit", type=int, default=50,
                    help="cycle records to list (cycles subcommand)")
    sp.add_argument("--out", dest="out_file",
                    help="write the trace JSON here instead of stdout")
    sp.set_defaults(fn=cmd_debug)

    sp = sub.add_parser("lint", help="repo-native static analysis: lock "
                                     "discipline, JIT hygiene, docs-"
                                     "registry completeness "
                                     "(docs/ANALYSIS.md); exits nonzero "
                                     "on any unbaselined finding")
    sp.add_argument("--json", action="store_true", dest="as_json")
    sp.add_argument("--root", default=None)
    sp.add_argument("--docs", default=None)
    sp.add_argument("--baseline", default=None)
    sp.add_argument("--show-suppressed", action="store_true")
    sp.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="BASE",
                    help="findings restricted to files modified vs a "
                         "git base (default HEAD)")
    sp.add_argument("--lock-coverage", action="store_true",
                    dest="lock_coverage",
                    help="static-vs-observed lock-edge coverage diff")
    sp.add_argument("--observed", default=None, metavar="FILE",
                    help="observed edges source for --lock-coverage "
                         "(a /debug/health JSON)")
    sp.set_defaults(fn=cmd_lint)

    sp = sub.add_parser("config")
    sp.add_argument("--set-url", dest="set_url")
    sp.add_argument("key", nargs="?", help="dotted config key to get/set")
    sp.add_argument("value", nargs="?", help="value to set (JSON or str)")
    sp.set_defaults(fn=cmd_config)
    _register_plugins(sub)
    return p


def _register_plugins(subparsers) -> None:
    """Subcommand plugins (reference: cli/cook/plugins.py + the
    test_cli_subcommand_plugin integration tier): ~/.cs.json may carry
    {"plugins": {"<name>": "dotted.module:register"}}; each register
    callable gets the subparsers object and adds its own parser (with
    set_defaults(fn=...)).  A broken plugin is reported and skipped — it
    must not take the whole CLI down."""
    import importlib
    cfg = load_cs_config()
    if not cfg:
        return
    for name, path in (cfg.get("plugins") or {}).items():
        try:
            module, _, attr = path.partition(":")
            register = getattr(importlib.import_module(module),
                               attr or "register")
            register(subparsers)
        except Exception as e:  # noqa: BLE001 - plugin faults are isolated
            print(f"warning: cli plugin {name!r} ({path}) failed to "
                  f"load: {e}", file=sys.stderr)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except JobClientError as e:
        print(f"error: {e.message}", file=sys.stderr)
        return 1
    except (OSError, TimeoutError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
