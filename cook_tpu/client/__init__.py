"""Python job client.

Parity with the reference's Python jobclient (reference:
jobclient/python/cookclient/__init__.py:419 JobClient): submit/query/kill/
wait plus admin helpers, over stdlib urllib (no extra dependencies).
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Union

# a completed job renders as success|failed (the server resolves the raw
# completed state from instances, reference: tools.clj:310-321); "completed"
# is kept for compatibility with older servers
TERMINAL_STATES = frozenset({"completed", "success", "failed"})


class JobClientError(Exception):
    def __init__(self, status: int, message: str,
                 body: Optional[Dict] = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message
        # the parsed JSON error body, when there was one — carries the
        # indeterminate-commit contract (``{"indeterminate": true,
        # "jobs": [...]}``, HTTP 504; docs/DEPLOY.md)
        self.body = body or {}

    @property
    def indeterminate(self) -> bool:
        """True when the server could not confirm whether the write
        committed (replication unconfirmed mid-failover).  Safe to
        retry: submission is idempotent on job uuid."""
        return bool(self.body.get("indeterminate"))

    @property
    def request_id(self) -> Optional[str]:
        """The server-echoed X-Cook-Request-Id carried in the error body:
        quote it in a report and an operator joins it to the server's
        slow-request ring (GET /debug/requests) and the trace."""
        return self.body.get("request_id")

    @property
    def reason(self) -> Optional[str]:
        """Machine-readable shed/throttle reason on an admission 429
        ("rate-limited", "user-pending-cap", "brownout-shed", ...)."""
        return self.body.get("reason")

    @property
    def scope(self) -> Optional[str]:
        """Which limit rejected the request ("user", "ip", "global")."""
        return self.body.get("scope")

    @property
    def retry_after_s(self) -> Optional[float]:
        """The server's Retry-After advice in seconds, when it sent one
        (admission 429s and 503s always do)."""
        v = self.body.get("retry_after_s")
        return float(v) if v is not None else None

    @property
    def throttled(self) -> bool:
        """True for an admission rejection (HTTP 429).  Unlike an
        indeterminate 504, a 429 means the server REFUSED the request
        before touching state — the exact same request is safe to retry
        verbatim after backing off (non-indeterminate by construction)."""
        return self.status == 429


class JobClient:
    def __init__(self, url: str, user: str = "anonymous",
                 impersonate: Optional[str] = None, timeout_s: float = 30.0,
                 token: Optional[str] = None,
                 basic_auth: Optional[tuple] = None,
                 read_your_writes: bool = True):
        self.url = url.rstrip("/")
        self.user = user
        self.impersonate = impersonate
        self.timeout_s = timeout_s
        # bearer/negotiate ticket (rest/auth.py HmacTokenAuthenticator) or
        # (user, password) basic credentials for verified servers
        self.token = token
        self.basic_auth = basic_auth
        # trace context of the most recent request (W3C traceparent is
        # minted per request — or inherited from an active in-process
        # span — and sent as a header; the server opens its http.request
        # root under it, so this id keys GET /debug/trace server-side)
        self.last_trace_id: Optional[str] = None
        # the server-echoed X-Cook-Request-Id of the most recent response
        self.last_request_id: Optional[str] = None
        # read-your-writes over the follower fleet (docs/DEPLOY.md):
        # leader write responses carry X-Cook-Commit-Offset (an OPAQUE
        # session token, "<epoch>:<offset>" on fenced journals); with
        # read_your_writes on, later GETs thread the most recent token
        # back as X-Cook-Min-Offset so a behind follower waits briefly
        # or hands the read to the leader — this client never reads a
        # state older than its own confirmed writes
        self.read_your_writes = read_your_writes
        # overload etiquette (docs/ROBUSTNESS.md brownout ladder): how
        # many times one request waits out a 429/503 Retry-After before
        # surfacing the error.  0 disables the wait (the error carries
        # retry_after_s for the caller's own pacing).  The wait is the
        # server's advice bounded by a full-jitter backoff ladder, so a
        # fleet of throttled clients desynchronizes instead of returning
        # in one synchronized retry wave.
        self.throttle_retries = 2
        #: hard ceiling on a single honored Retry-After sleep
        self.throttle_cap_s = 30.0
        self.last_commit_offset: Optional[str] = None
        # partitioned write plane (docs/DEPLOY.md): a partitioned
        # leader's token is a VECTOR of per-partition entries
        # ("p0:3:128,p1:3:64").  The client keeps the LATEST entry PER
        # PARTITION (each partition is its own offset space and its own
        # session: latest-wins per partition, exactly the single-token
        # rule applied P times) and threads the joined vector back as
        # X-Cook-Min-Offset — so a write to partition 0 followed by a
        # write to partition 1 still guarantees read-your-writes for
        # BOTH on later reads.
        self._commit_tokens: dict = {}
        # staleness of the most recent follower-served response
        # (X-Cook-Replication-Offset / -Age-Ms), None when the leader
        # answered
        self.last_replication_offset: Optional[int] = None
        self.last_replication_age_ms: Optional[float] = None
        # pooled keep-alive connections, one per (thread, host:port):
        # ThreadingHTTPServer spawns a thread per CONNECTION, so per-
        # request connections meant per-request thread churn + TCP
        # handshakes — the 4->8 reader QPS regression in the r8 bench.
        # Thread-local so one client shared across threads stays safe.
        self._pool = threading.local()

    # ------------------------------------------------------------- plumbing
    #: a reused keep-alive socket idle past this is proactively recycled
    #: before a NON-idempotent request: the server's idle timeout may
    #: have closed it, and a write whose response is lost must never be
    #: silently re-sent (see _exchange)
    _IDLE_RECYCLE_S = 10.0

    def _connection(self, scheme: str, netloc: str,
                    fresh_for_write: bool = False):
        conns = getattr(self._pool, "conns", None)
        if conns is None:
            conns = self._pool.conns = {}
        key = (scheme, netloc)
        conn = conns.get(key)
        if conn is not None and fresh_for_write \
                and conn._cook_served > 0 \
                and time.monotonic() - conn._cook_last_use \
                > self._IDLE_RECYCLE_S:
            self._drop_connection(scheme, netloc)
            conn = None
        if conn is None:
            cls = (http.client.HTTPSConnection if scheme == "https"
                   else http.client.HTTPConnection)
            conn = cls(netloc, timeout=self.timeout_s)
            conn._cook_served = 0  # requests completed on this socket
            conn._cook_last_use = time.monotonic()
            conns[key] = conn
        return conn

    def _drop_connection(self, scheme: str, netloc: str) -> None:
        conns = getattr(self._pool, "conns", {})
        conn = conns.pop((scheme, netloc), None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def close(self) -> None:
        """Close this thread's pooled keep-alive connections."""
        for scheme, netloc in list(getattr(self._pool, "conns", {})):
            self._drop_connection(scheme, netloc)

    def _exchange(self, scheme: str, netloc: str, method: str,
                  target: str, data: Optional[bytes],
                  headers: Dict[str, str]):
        """One HTTP exchange over the pooled keep-alive connection.
        A REUSED connection the server closed while idle fails on the
        next exchange; the retry policy distinguishes WHERE it failed:

        - during ``request()`` (send phase): nothing reached the
          server — safe to retry ANY method once on a fresh socket;
        - during ``getresponse()``: the server may have processed the
          request and died before answering — only idempotent GETs are
          retried (a silently re-sent POST could duplicate its effect;
          writes surface the error like the per-request-connection
          client did).  Non-idempotent requests avoid this window by
          recycling long-idle sockets up front (_IDLE_RECYCLE_S)."""
        retriable = (http.client.BadStatusLine,
                     http.client.CannotSendRequest,
                     ConnectionError, BrokenPipeError, OSError)
        for attempt in (0, 1):
            conn = self._connection(scheme, netloc,
                                    fresh_for_write=method != "GET")
            reused = conn._cook_served > 0
            try:
                conn.request(method, target, body=data, headers=headers)
            except retriable:
                self._drop_connection(scheme, netloc)
                if attempt == 0 and reused:
                    continue
                raise
            try:
                resp = conn.getresponse()
                raw = resp.read()  # drain fully: keep-alive reuse
            except retriable:
                self._drop_connection(scheme, netloc)
                if attempt == 0 and reused and method == "GET":
                    continue
                raise
            conn._cook_served += 1
            conn._cook_last_use = time.monotonic()
            return resp, raw

    def _merge_commit_token(self, token: str) -> None:
        """Fold one X-Cook-Commit-Offset into the session token: plain
        tokens replace wholesale (latest wins); partition-qualified
        vectors replace per partition; CELL-qualified entries (a
        federation front door's ``cell/p0:3:128`` — docs/DEPLOY.md
        multi-cell federation) replace per (cell, partition), so one
        session token carries read-your-writes across every cell the
        session touched.  All string-level — the entries stay opaque."""
        entries = [e.strip() for e in token.split(",") if e.strip()]

        def _key(e: str) -> Optional[str]:
            # merge key per entry: "p<part>" intra-cell, "<cell>/" or
            # "<cell>/p<part>" when a front door qualified it
            cell, sep, rest = e.partition("/")
            if sep and cell and "/" not in rest:
                if rest.startswith("p") and ":" in rest:
                    return cell + "/" + rest.partition(":")[0]
                return cell + "/"
            return e.partition(":")[0] \
                if e.startswith("p") and ":" in e else None

        keys = [_key(e) for e in entries]
        if not entries or any(k is None for k in keys):
            # legacy single token (or something unrecognized: treat as
            # the opaque session token it is).  Wholesale replacement
            # retires any per-partition vector too — the server that
            # minted this token is not the partitioned plane those
            # entries measured, and resurrecting them on the next
            # vector merge would gate reads on an obsolete journal.
            self._commit_tokens.clear()
            self.last_commit_offset = token
            return
        for k, e in zip(keys, entries):
            self._commit_tokens[k] = e
        self.last_commit_offset = ",".join(
            self._commit_tokens[k]
            for k in sorted(self._commit_tokens))

    def _request(self, method: str, path: str,
                 params: Optional[Dict[str, Union[str, Sequence[str]]]] = None,
                 body: Optional[Dict] = None) -> Any:
        query = ""
        if params:
            pairs = []
            for k, v in params.items():
                if isinstance(v, (list, tuple)):
                    pairs.extend((k, item) for item in v)
                else:
                    pairs.append((k, v))
            query = "?" + urllib.parse.urlencode(pairs)
        data = json.dumps(body).encode() if body is not None else None
        url = self.url + path + query
        # Dapper-style propagation: every request carries a W3C
        # traceparent — an active in-process span's context when one
        # exists (tests, embedded clients), a freshly minted trace
        # otherwise — so the server's http.request span, store txn,
        # journal fsync, and replication ack wait all stitch under ONE
        # trace this client can name (docs/OBSERVABILITY.md)
        from ..utils import tracing
        cur = tracing.tracer.current()
        traceparent = (tracing.make_traceparent(cur.trace_id, cur.span_id)
                       if cur is not None else tracing.make_traceparent())
        self.last_trace_id = \
            tracing.parse_traceparent(traceparent)[0]
        headers = {"Content-Type": "application/json",
                   "X-Cook-User": self.user,
                   "traceparent": traceparent,
                   **({"X-Cook-Impersonate": self.impersonate}
                      if self.impersonate else {})}
        if data is not None:
            headers["Content-Length"] = str(len(data))
        if method == "GET" and self.read_your_writes \
                and self.last_commit_offset:
            # the read-your-writes token: a follower behind this
            # position waits briefly, then redirects the read to the
            # leader
            headers["X-Cook-Min-Offset"] = self.last_commit_offset
        if self.token:
            headers["Authorization"] = "Bearer " + self.token
        elif self.basic_auth:
            import base64
            cred = base64.b64encode(
                f"{self.basic_auth[0]}:{self.basic_auth[1]}".encode()).decode()
            headers["Authorization"] = "Basic " + cred
        raw = None
        # transient-failure budget for idempotent requests: a dropped
        # connection mid-failover must not surface as an error when a
        # jittered retry (utils/retry.py) would land on the new leader
        transient = None
        from ..utils.retry import Backoff
        if method == "GET":
            transient = [2, Backoff(base_s=0.1, cap_s=1.0)]
        # admission throttling (429) / overload (503): the server's
        # Retry-After is honored with full jitter — never a tight loop,
        # never an unbounded sleep (see throttle_retries)
        throttle = [max(0, int(self.throttle_retries)),
                    Backoff(base_s=0.5, cap_s=self.throttle_cap_s)]
        # 8 hops: room for the transient + throttle retry budgets on top
        # of the 307 leader-redirect chain
        for _hop in range(8):  # follow leader redirects (307) incl. POST,
            parsed = urllib.parse.urlsplit(url)
            target = (parsed.path or "/") \
                + ("?" + parsed.query if parsed.query else "")
            try:
                resp, raw = self._exchange(parsed.scheme or "http",
                                           parsed.netloc, method, target,
                                           data, headers)
            except (urllib.error.URLError, ConnectionError, OSError):
                if transient is None or transient[0] <= 0:
                    raise
                transient[0] -= 1
                time.sleep(transient[1].next_delay())
                continue
            echoed_id = resp.getheader("X-Cook-Request-Id")
            forwarded_id = headers.get("X-Cook-Request-Id")
            if forwarded_id and echoed_id and echoed_id != forwarded_id:
                # the hop adopted a DIFFERENT id than the one this chain
                # carries: the redirect's log/ring entries and the
                # leader's can no longer be joined — fail loudly rather
                # than hand back an id that names only half the request
                raise JobClientError(
                    502, "request-id echo mismatch across redirect: "
                         f"forwarded {forwarded_id}, got {echoed_id}")
            self.last_request_id = echoed_id
            co = resp.getheader("X-Cook-Commit-Offset")
            if co is not None:
                # the token is OPAQUE and the LATEST write wins, not a
                # max(): the server's offset space re-bases smaller on
                # a journal checkpoint (and changes epoch on failover),
                # and a pinned stale token from an old space would be
                # unsatisfiable forever.  The read-your-writes session
                # token is the most recent confirmed write, exactly
                # like any session token.  Partition-qualified entries
                # ("pN:...") apply that rule PER PARTITION and the
                # session token becomes the joined vector.
                self._merge_commit_token(co)
            ro = resp.getheader("X-Cook-Replication-Offset")
            self.last_replication_offset = \
                int(ro) if ro and ro.isdigit() else None
            age = resp.getheader("X-Cook-Replication-Age-Ms")
            try:
                self.last_replication_age_ms = \
                    float(age) if age is not None else None
            except ValueError:
                self.last_replication_age_ms = None
            if resp.status == 307 and resp.getheader("Location"):
                url = resp.getheader("Location")
                if echoed_id:
                    # forward the id the redirecting node (a follower)
                    # minted, so the leader ADOPTS it instead of minting
                    # a second one — the two log/ring entries for this
                    # one logical request join on a single id
                    # (docs/OBSERVABILITY.md "Tracing one request")
                    headers["X-Cook-Request-Id"] = echoed_id
                continue
            if resp.status >= 400:
                try:
                    err_body = json.loads(raw)
                    message = err_body.get(
                        "error", f"HTTP {resp.status}")
                except Exception:
                    err_body = {}
                    message = f"HTTP {resp.status}: {resp.reason}"
                if resp.status in (429, 503):
                    # surface the server's pacing advice on the error
                    # even when the retry budget is spent
                    ra = resp.getheader("Retry-After")
                    try:
                        advised = float(ra) if ra is not None else None
                    except ValueError:
                        advised = None
                    if advised is not None:
                        err_body.setdefault("retry_after_s", advised)
                    if throttle[0] > 0 and advised is not None:
                        throttle[0] -= 1
                        # server advice, jittered and capped: sleep a
                        # uniform draw over [0, advice] plus the ladder's
                        # own jitter, bounded by throttle_cap_s and never
                        # shorter than the ladder's first rung (a 429
                        # with Retry-After: 0 must not tight-loop)
                        delay = min(self.throttle_cap_s,
                                    max(throttle[1].next_delay(),
                                        random.uniform(0.0, advised)))
                        time.sleep(delay)
                        continue
                if echoed_id:
                    err_body.setdefault("request_id", echoed_id)
                raise JobClientError(resp.status, message, body=err_body)
            break
        else:
            raise JobClientError(508, "redirect loop")
        if path in ("/metrics", "/metrics/fleet"):
            return raw.decode()
        return json.loads(raw) if raw else None

    # ---------------------------------------------------------------- jobs
    def submit(self, jobs: List[Dict], pool: Optional[str] = None,
               groups: Optional[List[Dict]] = None,
               indeterminate_retries: int = 2,
               idempotent: bool = False) -> List[str]:
        """Submit a batch.  Every spec gets a client-side uuid up front,
        which makes the submission idempotent on job uuid: when the
        server answers HTTP 504 ``indeterminate`` (the commit is
        journaled on the leader but unconfirmed on its mirror — a
        failover may or may not preserve it), the SAME batch is resent
        with ``"idempotent": true`` so the post-failover leader treats
        surviving jobs as successes and creates only the missing ones —
        the retry neither loses nor duplicates (docs/DEPLOY.md).
        ``indeterminate_retries=0`` disables the automatic retry; the
        504 then surfaces as a :class:`JobClientError` whose
        ``indeterminate`` property is True — re-calling submit with the
        same uuid-carrying specs and ``idempotent=True`` is the manual
        form of the same recovery."""
        import uuid as _uuid
        jobs = [dict(spec) for spec in jobs]
        for spec in jobs:
            spec.setdefault("uuid", str(_uuid.uuid4()))
        body: Dict[str, Any] = {"jobs": jobs}
        if pool:
            body["pool"] = pool
        if groups:
            body["groups"] = groups
        if idempotent:
            body["idempotent"] = True
        from ..utils.retry import Backoff
        backoff = Backoff(base_s=0.2, cap_s=2.0)
        attempts = max(0, int(indeterminate_retries))
        while True:
            try:
                return self._request("POST", "/jobs", body=body)["jobs"]
            except JobClientError as e:
                if not e.indeterminate or attempts <= 0:
                    raise
                attempts -= 1
                body["idempotent"] = True
                time.sleep(backoff.next_delay())

    def submit_one(self, command: str, **spec) -> str:
        spec["command"] = command
        return self.submit([spec])[0]

    def query(self, uuids: Sequence[str],
              partial: bool = False) -> List[Dict]:
        params: Dict[str, Any] = {"uuid": list(uuids)}
        if partial:
            params["partial"] = "true"
        return self._request("GET", "/jobs", params=params)

    def job(self, uuid: str) -> Dict:
        return self._request("GET", f"/jobs/{uuid}")

    def jobs(self, user: Optional[str] = None,
             states: Optional[Sequence[str]] = None) -> List[Dict]:
        params: Dict[str, str] = {}
        if user:
            params["user"] = user
        if states:
            params["state"] = "+".join(states)
        return self._request("GET", "/jobs", params=params)

    def kill(self, uuids: Sequence[str]) -> Dict:
        return self._request("DELETE", "/jobs", params={"uuid": list(uuids)})

    def retry(self, uuid: Optional[str] = None, retries: Optional[int] = None,
              *, jobs: Optional[Sequence[str]] = None,
              groups: Optional[Sequence[str]] = None,
              increment: Optional[int] = None,
              failed_only: Optional[bool] = None) -> Dict:
        """PUT /retry (reference: UpdateRetriesRequest rest/api.clj:2480):
        raise retries to ``retries`` or by ``increment`` on jobs and/or
        groups; ``failed_only`` defaults server-side to True iff groups."""
        body: Dict[str, Any] = {}
        if uuid is not None:
            body["job"] = uuid
        if jobs is not None:
            body["jobs"] = list(jobs)
        if groups is not None:
            body["groups"] = list(groups)
        if retries is not None:
            body["retries"] = retries
        if increment is not None:
            body["increment"] = increment
        if failed_only is not None:
            body["failed_only"] = failed_only
        return self._request("PUT", "/retry", body=body)

    def wait(self, uuids: Sequence[str], timeout_s: float = 300.0,
             poll_s: float = 0.5) -> List[Dict]:
        """Block until all jobs complete (reference: cli wait subcommand)."""
        deadline = time.time() + timeout_s
        while True:
            jobs = self.query(uuids)
            if all(j["state"] in TERMINAL_STATES for j in jobs):
                return jobs
            if time.time() > deadline:
                raise TimeoutError(
                    f"jobs not completed within {timeout_s}s")
            time.sleep(poll_s)

    def instance(self, task_id: str) -> Dict:
        return self._request("GET", f"/instances/{task_id}")

    def kill_instances(self, task_ids: Sequence[str]) -> Dict:
        return self._request("DELETE", "/instances",
                             params={"uuid": list(task_ids)})

    # --------------------------------------------------------------- groups
    def group(self, uuids: Sequence[str], detailed: bool = False
              ) -> List[Dict]:
        params: Dict[str, Any] = {"uuid": list(uuids)}
        if detailed:
            params["detailed"] = "true"
        return self._request("GET", "/group", params=params)

    def kill_groups(self, uuids: Sequence[str]) -> Dict:
        return self._request("DELETE", "/group",
                             params={"uuid": list(uuids)})

    def list_jobs(self, user: str, states: Optional[Sequence[str]] = None,
                  start_ms: Optional[int] = None,
                  end_ms: Optional[int] = None,
                  limit: Optional[int] = None) -> List[Dict]:
        params: Dict[str, Any] = {"user": user}
        if states:
            params["state"] = "+".join(states)
        if start_ms is not None:
            params["start-ms"] = str(start_ms)
        if end_ms is not None:
            params["end-ms"] = str(end_ms)
        if limit is not None:
            params["limit"] = str(limit)
        return self._request("GET", "/list", params=params)

    def shutdown_leader(self) -> Dict:
        return self._request("POST", "/shutdown-leader", body={})

    # ---------------------------------------------------------------- admin
    def usage(self, user: Optional[str] = None,
              pool: Optional[str] = None,
              group_breakdown: bool = False) -> Dict:
        """GET /usage.  No user = the all-users report (admin-only);
        ``pool`` restricts either form; ``group_breakdown`` adds the
        per-group running-jobs split."""
        params: Dict[str, str] = {}
        if user is not None:
            params["user"] = user
        if pool is not None:
            params["pool"] = pool
        if group_breakdown:
            params["group_breakdown"] = "true"
        return self._request("GET", "/usage", params=params)

    def queue(self) -> Dict:
        return self._request("GET", "/queue")

    def pools(self) -> List[Dict]:
        return self._request("GET", "/pools")

    def unscheduled_jobs(self, uuids: Sequence[str]) -> List[Dict]:
        return self._request("GET", "/unscheduled_jobs",
                             params={"job": list(uuids)})

    def get_share(self, user: str) -> Dict:
        return self._request("GET", "/share", params={"user": user})

    def set_share(self, user: str, pools: Dict[str, Dict[str, float]],
                  reason: str = "") -> Dict:
        return self._request("POST", "/share",
                             body={"user": user, "pools": pools,
                                   "reason": reason})

    def get_quota(self, user: str) -> Dict:
        return self._request("GET", "/quota", params={"user": user})

    def set_quota(self, user: str, pools: Dict[str, Dict[str, float]],
                  reason: str = "") -> Dict:
        return self._request("POST", "/quota",
                             body={"user": user, "pools": pools,
                                   "reason": reason})

    def failure_reasons(self) -> List[Dict]:
        return self._request("GET", "/failure_reasons")

    def stats(self, status: Optional[str] = None,
              start: Optional[str] = None, end: Optional[str] = None,
              name: Optional[str] = None) -> Dict:
        """GET /stats/instances.  With a status/start/end window, returns
        the reference-shaped histogram report (task_stats.clj); with no
        arguments, the quick by-status/by-reason aggregate."""
        if status is None and start is None and end is None and name is None:
            return self._request("GET", "/stats/instances")
        return self._request(
            "GET", "/stats/instances",
            params={k: v for k, v in (("status", status), ("start", start),
                                      ("end", end), ("name", name))
                    if v is not None})

    def settings(self) -> Dict:
        return self._request("GET", "/settings")

    def set_rebalancer(self, params: Dict) -> Dict:
        """Live rebalancer tuning (admin): {"min-dru-diff": 0.2, ...}."""
        return self._request("POST", "/settings/rebalancer", body=params)

    def info(self) -> Dict:
        return self._request("GET", "/info")

    def metrics(self) -> str:
        return self._request("GET", "/metrics")

    def debug_cycles(self, limit: int = 50) -> Dict:
        """GET /debug/cycles — the scheduler's flight-recorder ring of
        per-cycle records (newest last)."""
        return self._request("GET", "/debug/cycles",
                             params={"limit": str(limit)})

    def debug_trace(self, trace_id: Optional[str] = None,
                    job: Optional[str] = None) -> Dict:
        """GET /debug/trace — spans as Chrome trace-event JSON, loadable
        in chrome://tracing / ui.perfetto.dev.  With ``job``, the job's
        audit timeline is stitched in as a per-job instant-event track;
        ``job`` ALONE returns the fully stitched per-job view (launching
        cycle flamegraph + submission request track + audit lane)."""
        params: Dict = {}
        if trace_id:
            params["trace_id"] = trace_id
        if job:
            params["job"] = job
        return self._request("GET", "/debug/trace", params=params)

    def debug_requests(self, limit: int = 50) -> Dict:
        """GET /debug/requests — the serving plane's recent + slow
        request rings with per-phase breakdowns (redacted params)."""
        return self._request("GET", "/debug/requests",
                             params={"limit": str(limit)})

    def debug_health(self) -> Dict:
        """GET /debug/health — the one-shot roll-up behind ``cs debug
        health``: SLO burn rates, breaker states, replication lag,
        pipeline depth, repack counters, audit queue depth."""
        return self._request("GET", "/debug/health")

    def job_timeline(self, uuid: str) -> Dict:
        """GET /debug/job/<uuid>/timeline — the job's full scheduling
        audit trail plus, while it waits, the unscheduled explainer's
        current reasons and the user's fairness position (`cs why`)."""
        return self._request("GET", f"/debug/job/{uuid}/timeline")

    def debug_faults(self) -> Dict:
        """GET /debug/faults — armed fault points, per-cluster circuit
        breaker states, and open launch intents (docs/ROBUSTNESS.md)."""
        return self._request("GET", "/debug/faults")

    def debug_replication(self) -> Dict:
        """GET /debug/replication — the failover panel: per-follower
        offsets, min_acked, synced set, mirror position, and the
        candidate positions published into the election medium."""
        return self._request("GET", "/debug/replication")

    def debug_optimizer(self) -> Dict:
        """GET /debug/optimizer — the goodput loop's decision panel:
        last per-pool decisions, cycle counts/errors, and the elastic
        resize plane's live state (docs/GANG.md elasticity)."""
        return self._request("GET", "/debug/optimizer")

    def debug_fleet(self) -> Dict:
        """GET /debug/fleet — the federated fleet panel behind ``cs
        debug fleet``: per-member health, staleness, burn, saturation
        hot-spots, and last-scrape age (docs/OBSERVABILITY.md)."""
        return self._request("GET", "/debug/fleet")

    def debug_storage(self) -> Dict:
        """GET /debug/storage — the persistence-integrity panel behind
        ``cs debug storage``: per-partition scrub progress, corruption/
        repair counters, checkpoint manifest status, mirror poison
        state (docs/DEPLOY.md corrupted-journal runbook)."""
        return self._request("GET", "/debug/storage")

    def debug_trace_spans(self, trace_id: str) -> Dict:
        """GET /debug/trace/spans — ONE member's raw span-ring docs for
        a trace id; the fleet trace collector's per-member stitch
        source (normally you want ``debug_trace`` instead)."""
        return self._request("GET", "/debug/trace/spans",
                             params={"trace_id": trace_id})

    def metrics_fleet(self) -> str:
        """GET /metrics/fleet — merged fleet exposition: every member's
        /metrics re-labeled with instance/role."""
        return self._request("GET", "/metrics/fleet")
