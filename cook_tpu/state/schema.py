"""Entity schema: jobs, instances, groups, pools, shares, quotas.

Mirrors the reference Datomic schema (reference: scheduler/src/cook/schema.clj:20-1100)
as plain Python dataclasses.  The reference keeps ~200 attributes; we keep the
behavior-bearing subset and a ``labels``/``env`` escape hatch for the rest.

Resource vectors are ordered (cpus, mem, gpus, disk) so host-side entities
convert losslessly into the (N x R) tensors consumed by the kernels in
``cook_tpu.ops``.
"""

from __future__ import annotations

import dataclasses
import enum
import time
import uuid as uuidlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# Resource dimension order used by every kernel in cook_tpu.ops.
RESOURCE_DIMS: Tuple[str, ...] = ("cpus", "mem", "gpus", "disk")
NUM_RESOURCE_DIMS = len(RESOURCE_DIMS)

DEFAULT_JOB_PRIORITY = 50  # reference: util/default-job-priority (tools.clj)
MAX_JOB_PRIORITY = 100


# Job-label keys with placement semantics (consumed by the constraint
# compiler in sched/constraints.py and the columnar index's complex-job
# classifier in state/index.py; reference: constraints.clj:122,164)
GPU_MODEL_LABEL = "gpu-model"
DISK_TYPE_LABEL = "disk-type"


class JobState(enum.Enum):
    """Job lifecycle (reference: schema.clj job state machine, :job/update-state
    schema.clj:1202-1239): waiting <-> running -> completed."""

    WAITING = "waiting"
    RUNNING = "running"
    COMPLETED = "completed"


class InstanceStatus(enum.Enum):
    """Instance lifecycle (reference: :instance/update-state schema.clj:1242-1308):
    unknown -> running -> {success, failed}."""

    UNKNOWN = "unknown"
    RUNNING = "running"
    SUCCESS = "success"
    FAILED = "failed"


TERMINAL_INSTANCE_STATUSES = (InstanceStatus.SUCCESS, InstanceStatus.FAILED)


@dataclass(frozen=True)
class Reason:
    """Failure reason (reference: scheduler/src/cook/mesos/reason.clj).

    ``mea_culpa`` failures are the cluster's fault and do not consume user
    retries (up to ``failure_limit`` occurrences, None = unlimited).
    """

    code: int
    name: str
    mea_culpa: bool = False
    failure_limit: Optional[int] = None


class Reasons:
    """Registry of failure reasons, mirroring reason.clj's reason table."""

    NORMAL_EXIT = Reason(0, "normal-exit")
    UNKNOWN = Reason(1, "unknown")
    KILLED_BY_USER = Reason(2, "killed-by-user")
    PREEMPTED_BY_REBALANCER = Reason(3, "preempted-by-rebalancer", mea_culpa=True)
    PREEMPTED_BY_POOL = Reason(4, "preempted-by-pool", mea_culpa=True)
    MAX_RUNTIME_EXCEEDED = Reason(5, "max-runtime-exceeded")
    NON_ZERO_EXIT = Reason(6, "non-zero-exit")
    NODE_LOST = Reason(7, "node-lost", mea_culpa=True)
    CONTAINER_LAUNCH_FAILED = Reason(8, "container-launch-failed", mea_culpa=True, failure_limit=3)
    HEARTBEAT_LOST = Reason(9, "heartbeat-lost", mea_culpa=True)
    CHECKPOINT_FAILURE = Reason(10, "checkpoint-failure", mea_culpa=True, failure_limit=3)
    STRAGGLER = Reason(11, "straggler", mea_culpa=True)
    CANCELLED_DURING_LAUNCH = Reason(12, "cancelled-during-launch", mea_culpa=True)
    REASON_POD_SUBMISSION_FAILED = Reason(13, "pod-submission-failed", mea_culpa=True, failure_limit=10)
    # pod entered phase Unknown: kubernetes lost track of it; the cluster's
    # fault, retry free (reference: the controller's :pod/unknown arms)
    UNKNOWN_MEA_CULPA = Reason(14, "unknown-mea-culpa", mea_culpa=True, failure_limit=3)
    # stuck/unschedulable pod reaped by the detector
    # (reference: kubernetes/api.clj:1820-1846)
    POD_STUCK = Reason(15, "pod-stuck", mea_culpa=True, failure_limit=3)
    # task exceeded its requested memory and was hard-killed by the agent
    # (reference: "Container memory limit exceeded", reason 2002 in
    # reason.clj — the user's fault, consumes a retry)
    MEMORY_LIMIT_EXCEEDED = Reason(16, "memory-limit-exceeded")
    # a gang sibling failed: this (blameless) member was killed by the
    # gang policy so the whole gang requeues atomically (docs/GANG.md).
    # Unlimited free retries — the member that actually failed carries
    # its own reason and consumes ITS budget; like
    # CANCELLED_DURING_LAUNCH, the kill proves nothing about the host,
    # so the matcher does not novel-host-exclude it.
    GANG_MEMBER_LOST = Reason(17, "gang-member-lost", mea_culpa=True)
    # an ELASTIC gang member shed by the resize pass (checkpoint/grace
    # shrink, docs/GANG.md elasticity): the cluster reclaimed surplus
    # capacity, the member did nothing wrong — mea-culpa, free retries,
    # no novel-host exclusion (the member wants its host back on grow),
    # and the gang policy never reacts to it (the gang stays whole at
    # its post-shrink size).
    GANG_RESIZED = Reason(18, "gang-resized", mea_culpa=True)
    # a whole CELL's capacity was reclaimed (spot/preemptible tier) or
    # lost outright and the federation router re-routed this job's
    # demand to a surviving cell (cook_tpu/federation): the platform
    # took the capacity back, the job did nothing wrong — mea-culpa,
    # free retries.  The refund is the spot tier's contract: capacity
    # is cheap BECAUSE reclaim costs the user nothing.
    CELL_RECLAIMED = Reason(19, "cell-reclaimed", mea_culpa=True)

    _by_code: Dict[int, Reason] = {}
    _by_name: Dict[str, Reason] = {}

    @classmethod
    def all(cls) -> List[Reason]:
        return [v for v in vars(cls).values() if isinstance(v, Reason)]

    @classmethod
    def by_code(cls, code: int) -> Reason:
        if not cls._by_code:
            cls._by_code = {r.code: r for r in cls.all()}
        return cls._by_code.get(code, cls.UNKNOWN)

    @classmethod
    def by_name(cls, name: str) -> Reason:
        if not cls._by_name:
            cls._by_name = {r.name: r for r in cls.all()}
        return cls._by_name.get(name, cls.UNKNOWN)


@dataclass(frozen=True)
class Resources:
    """A point in resource space. Arithmetic is element-wise.

    Reference jobs carry cpus/mem(/gpus); hosts additionally advertise disk and
    port ranges (ports are handled host-side at launch, mesos/task.clj:209-237).
    """

    cpus: float = 0.0
    mem: float = 0.0
    gpus: float = 0.0
    disk: float = 0.0

    def as_tuple(self) -> Tuple[float, float, float, float]:
        return (self.cpus, self.mem, self.gpus, self.disk)

    def __add__(self, other: "Resources") -> "Resources":
        return Resources(*(a + b for a, b in zip(self.as_tuple(), other.as_tuple())))

    def __sub__(self, other: "Resources") -> "Resources":
        return Resources(*(a - b for a, b in zip(self.as_tuple(), other.as_tuple())))

    def fits_in(self, other: "Resources") -> bool:
        return all(a <= b for a, b in zip(self.as_tuple(), other.as_tuple()))

    def non_negative(self) -> bool:
        return all(a >= 0 for a in self.as_tuple())


@dataclass
class Constraint:
    """User-specified placement constraint (reference: schema.clj
    :constraint/{attribute,operator,pattern}; constraints.clj:356-430).

    operator is one of EQUALS ("EQUALS") today; the mask compiler in
    cook_tpu.sched.constraints interprets it against host attributes.
    """

    attribute: str
    operator: str
    pattern: str


class CheckpointMode(enum.Enum):
    # reference: schema.clj :job/checkpoint modes
    AUTO = "auto"
    PERIODIC = "periodic"
    PREEMPTION = "preemption"


@dataclass
class Checkpoint:
    """Job checkpointing declaration (reference: schema.clj:84, kubernetes/api.clj:1173-1267)."""

    mode: CheckpointMode = CheckpointMode.AUTO
    volume_mounts: List[str] = field(default_factory=list)
    period_sec: Optional[int] = None
    options: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Application:
    """Submitting-application metadata (reference: schema.clj
    :job.application/{name,version,workload-class,workload-id,
    workload-details})."""

    name: str = ""
    version: str = ""
    workload_class: str = ""
    workload_id: str = ""
    workload_details: str = ""


@dataclass
class Job:
    """A user's unit of work (reference: schema.clj:20-682 job attributes)."""

    uuid: str
    user: str
    command: str = ""
    name: str = "cookjob"
    resources: Resources = field(default_factory=lambda: Resources(cpus=1.0, mem=128.0))
    priority: int = DEFAULT_JOB_PRIORITY  # 0-100
    max_retries: int = 1
    max_runtime_ms: int = 2**53
    expected_runtime_ms: Optional[int] = None
    pool: str = "default"
    state: JobState = JobState.WAITING
    submit_time_ms: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    container: Optional[Dict[str, Any]] = None
    # count of host ports to assign at launch (reference: :job/ports,
    # assigned from the offer's ranges in mesos/task.clj:209-237 and
    # exported as PORT0.. in the task environment)
    ports: int = 0
    # artifacts fetched into the sandbox before the command runs
    # (reference: :job/uri, mesos fetcher semantics task.clj:114-160);
    # each: {"value": path-or-url, "executable": bool, "extract": bool,
    # "cache": bool}
    uris: List[Dict[str, Any]] = field(default_factory=list)
    # executor choice (reference: :job/executor "cook"|"mesos"): "cook"
    # runs under the progress-tracking executor, "" = backend default
    executor: str = ""
    # per-job progress plumbing (reference: :job/progress-output-file,
    # :job/progress-regex-string)
    progress_output_file: str = ""
    progress_regex_string: str = ""
    # declared input datasets for locality-aware plugins (reference:
    # :job/datasets, consumed by the data-locality fitness calculator)
    datasets: List[Dict[str, Any]] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    group: Optional[str] = None  # group uuid
    application: Optional[Application] = None
    checkpoint: Optional[Checkpoint] = None
    disable_mea_culpa_retries: bool = False
    # commit-latch: submitted-but-uncommitted jobs are invisible to queries
    # (reference: metatransaction/core.clj filter-committed; schema.clj:28).
    committed: bool = True
    # instances by task_id, newest last
    instances: List[str] = field(default_factory=list)
    # count of mea-culpa failures per reason code (for failure_limit accounting,
    # reference: :job/all-attempts-consumed? logic)
    mea_culpa_failures: Dict[int, int] = field(default_factory=dict)
    # set when the job reached completed because the user killed it
    user_killed: bool = False
    # rebalancer host reservation consumed by the matcher (rebalancer.clj:419-432)
    reserved_host: Optional[str] = None
    # "under investigation" flag driving the unscheduled-jobs explainer
    # (reference: :job/under-investigation; the next match cycle records a
    # placement-failure summary for investigated jobs, fenzo_utils.clj:75-99)
    under_investigation: bool = False
    # {"resources": {"cpus": host_count, ...},
    #  "constraints": {"novel_host_constraint": host_count, ...}}
    # (reference: :job/last-fenzo-placement-failure)
    last_placement_failure: Optional[Dict[str, Any]] = None
    last_waiting_start_ms: int = 0
    # request trace context stamped at submission (the client's W3C
    # traceparent / the REST ingress `http.request` span): joins this
    # job's audit lifecycle to the serving-plane trace so
    # `GET /debug/trace?job=` can stitch the submission request next to
    # the cycle that launched it (docs/OBSERVABILITY.md)
    trace_id: Optional[str] = None

    def attempts_used(self, instances: Dict[str, "Instance"]) -> int:
        """Number of retries consumed: failed, non-mea-culpa instances
        (mea-culpa failures under their limit don't count;
        reference: :job/all-attempts-consumed? + reason failure limits)."""
        used = 0
        mea_culpa_counts: Dict[int, int] = {}
        for tid in self.instances:
            inst = instances.get(tid)
            if inst is None or inst.status is not InstanceStatus.FAILED:
                continue
            reason = Reasons.by_code(inst.reason_code if inst.reason_code is not None else 1)
            if reason.mea_culpa and not self.disable_mea_culpa_retries:
                n = mea_culpa_counts.get(reason.code, 0) + 1
                mea_culpa_counts[reason.code] = n
                if reason.failure_limit is None or n <= reason.failure_limit:
                    continue  # free retry
            used += 1
        return used


@dataclass
class Instance:
    """One attempt at running a job (reference: schema.clj:683-1100)."""

    task_id: str
    job_uuid: str
    status: InstanceStatus = InstanceStatus.UNKNOWN
    hostname: str = ""
    slave_id: str = ""
    compute_cluster: str = ""
    start_time_ms: int = 0
    end_time_ms: Optional[int] = None
    mesos_start_time_ms: Optional[int] = None
    reason_code: Optional[int] = None
    preempted: bool = False
    progress: int = 0
    progress_message: str = ""
    progress_sequence: int = 0
    exit_code: Optional[int] = None
    sandbox_directory: str = ""
    # base URL of the instance's sandbox file server (the reference exposes
    # output_url on instance maps for Mesos-agent / sidecar file access)
    output_url: str = ""
    ports: List[int] = field(default_factory=list)
    queue_time_ms: int = 0
    cancelled: bool = False
    # "location" attribute of the host this instance ran on, recorded at
    # launch so checkpoint-locality can pin the job's next instance to the
    # same location (reference: constraints.clj:218-240 reads the prior
    # instance's node; here the matcher snapshots the offer attribute)
    node_location: str = ""


class GroupPlacementType(enum.Enum):
    # reference: schema.clj host-placement types; constraints.clj:586-676
    ALL = "all"
    UNIQUE = "unique"
    BALANCED = "balanced"
    ATTRIBUTE_EQUALS = "attribute-equals"


# Gang member-failure policies (docs/GANG.md): what happens to the rest
# of a gang when one member's instance fails.
GANG_POLICY_REQUEUE = "requeue"   # kill siblings mea-culpa, whole gang retries
GANG_POLICY_KILL = "kill"         # kill the whole gang's jobs outright
GANG_POLICIES = (GANG_POLICY_REQUEUE, GANG_POLICY_KILL)


@dataclass
class Group:
    """Job group with placement constraints + straggler handling
    (reference: schema.clj group attributes; group.clj).

    With ``gang=True`` the group is a multi-host slice job scheduled
    all-or-nothing (docs/GANG.md): all ``gang_size`` members must match
    in the same cycle, launch in one guard transaction, and — under the
    default ``requeue`` policy — a member failure kills and requeues the
    whole gang.  ``gang_topology`` optionally names a host attribute
    (e.g. "slice-id") whose value must be equal across every member's
    host, with the matcher preferring the slice with the most feasible
    capacity.

    ELASTIC gangs (docs/GANG.md elasticity): ``gang_min``/``gang_max``
    relax the rigid size — the gang launches whole at any member count
    in ``[gang_min, gang_max]``, grows into spare capacity and shrinks
    under pressure via the resize pass.  ``0`` (the default) means
    "same as gang_size": a group with ``gang_min == gang_max ==
    gang_size`` is exactly the rigid gang, decision-identical to a
    pre-elasticity build."""

    uuid: str
    name: str = "defaultgroup"
    placement_type: GroupPlacementType = GroupPlacementType.ALL
    placement_attribute: Optional[str] = None
    placement_minimum: int = 2  # for BALANCED
    straggler_quantile: Optional[float] = None   # e.g. 0.5
    straggler_multiplier: Optional[float] = None  # e.g. 2.0
    jobs: List[str] = field(default_factory=list)
    gang: bool = False
    gang_size: int = 0
    gang_topology: Optional[str] = None
    gang_policy: str = GANG_POLICY_REQUEUE
    # elasticity bounds; 0 = rigid (defaults to gang_size)
    gang_min: int = 0
    gang_max: int = 0


def gang_bounds(group) -> Tuple[int, int]:
    """The effective ``(min, max)`` member-count bounds of a gang group
    (docs/GANG.md elasticity).  Unset (0) bounds default to
    ``gang_size``, so rigid gangs read ``(size, size)``."""
    size = int(getattr(group, "gang_size", 0) or 0)
    lo = int(getattr(group, "gang_min", 0) or 0) or size
    hi = int(getattr(group, "gang_max", 0) or 0) or size
    return lo, hi


def gang_is_elastic(group) -> bool:
    """True when the gang's legal member count differs from its rigid
    all-or-nothing declaration — the gate every elastic-only code path
    checks so rigid gangs stay decision-identical to a pre-elasticity
    build.  NOTE ``lo != hi`` alone would be wrong: a gang declaring
    ``min == max < size`` (run exactly M of the N members) must take
    the elastic admission/reduction/growth-cap path too, or the rigid
    cohort gate (all N) and the min-threshold reduction (M) strand a
    permanent partial gang between them."""
    if not getattr(group, "gang", False):
        return False
    size = int(getattr(group, "gang_size", 0) or 0)
    lo, hi = gang_bounds(group)
    return not (lo == hi == size)


class DruMode(enum.Enum):
    # reference: schema.clj :pool/dru-mode default|gpu
    DEFAULT = "default"
    GPU = "gpu"


class SchedulerKind(enum.Enum):
    """Which matcher drives a pool (reference: config.clj pool-schedulers;
    'fenzo' -> our batched greedy kernel, 'kubernetes' -> direct backpressure mode)."""

    BATCH = "batch"       # rank + bin-pack match (Fenzo-style)
    DIRECT = "direct"     # direct submission under backpressure (Kenzo-style)


@dataclass
class Pool:
    """Scheduling pool (reference: schema.clj pool attributes; pool.clj)."""

    name: str
    purpose: str = ""
    state: str = "active"  # active | inactive
    dru_mode: DruMode = DruMode.DEFAULT
    scheduler: SchedulerKind = SchedulerKind.BATCH


@dataclass
class ShareEntry:
    """Per-user per-pool fair-share weights = DRU divisors
    (reference: share.clj; 'default' user is the fallback)."""

    user: str
    pool: str
    resources: Dict[str, float] = field(default_factory=dict)
    reason: str = ""


@dataclass
class QuotaEntry:
    """Per-user per-pool hard caps, including job count
    (reference: quota.clj; :count is a quota dimension)."""

    user: str
    pool: str
    resources: Dict[str, float] = field(default_factory=dict)  # cpus/mem/gpus
    count: float = float("inf")
    reason: str = ""


def new_uuid() -> str:
    return str(uuidlib.uuid4())


def now_ms() -> int:
    return int(time.time() * 1000)


def job_usage(job: Job) -> Dict[str, float]:
    """Usage map of one job, including count=1 (reference: tools.clj job->usage)."""
    u = {"count": 1.0, "cpus": job.resources.cpus, "mem": job.resources.mem}
    if job.resources.gpus:
        u["gpus"] = job.resources.gpus
    return u


def add_usage(a: Dict[str, float], b: Dict[str, float]) -> Dict[str, float]:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) + v
    return out


def below_quota(quota: Dict[str, float], usage: Dict[str, float]) -> bool:
    """True iff usage <= quota on every dimension present in usage
    (reference: tools.clj below-quota?, missing quota key treated as 0)."""
    return all(v <= quota.get(k, 0.0) for k, v in usage.items())


import copy as _copy


def _shallow(obj):
    """Fast shallow copy: copy.copy() routes dataclass instances through
    __reduce_ex__/_reconstruct, ~4x the cost of a __dict__ transplant."""
    c = obj.__class__.__new__(obj.__class__)
    c.__dict__.update(obj.__dict__)
    return c


def _clone_job(j: Job) -> Job:
    c = _shallow(j)  # new object, attributes shared
    # re-copy every mutable field so a txn fn mutating the clone can never
    # leak into the stored entity (Resources/enums/strs are immutable and
    # stay shared; rare nested dicts keep full deepcopy safety)
    c.labels = dict(j.labels)
    c.env = dict(j.env)
    c.instances = list(j.instances)
    c.mea_culpa_failures = dict(j.mea_culpa_failures)
    c.constraints = [_shallow(x) for x in j.constraints]
    c.uris = [dict(u) for u in j.uris]
    c.datasets = _copy.deepcopy(j.datasets) if j.datasets else []
    if j.container is not None:
        c.container = _copy.deepcopy(j.container)
    if j.application is not None:
        c.application = _shallow(j.application)
    if j.checkpoint is not None:
        k = _shallow(j.checkpoint)
        k.volume_mounts = list(j.checkpoint.volume_mounts)
        k.options = _copy.deepcopy(j.checkpoint.options)
        c.checkpoint = k
    if j.last_placement_failure is not None:
        c.last_placement_failure = _copy.deepcopy(j.last_placement_failure)
    return c


def _clone_instance(i: Instance) -> Instance:
    c = _shallow(i)
    c.ports = list(i.ports)
    return c


def _clone_group(g: Group) -> Group:
    c = _shallow(g)
    c.jobs = list(g.jobs)
    return c


def _clone_share(s: ShareEntry) -> ShareEntry:
    c = _shallow(s)
    c.resources = dict(s.resources)
    return c


def _clone_quota(q: QuotaEntry) -> QuotaEntry:
    c = _shallow(q)
    c.resources = dict(q.resources)
    return c


_CLONERS = {
    Job: _clone_job,
    Instance: _clone_instance,
    Group: _clone_group,
    Pool: _shallow,  # every Pool field is immutable
    ShareEntry: _clone_share,
    QuotaEntry: _clone_quota,
}


def fast_clone(ent: Any) -> Any:
    """Typed entity copy with deepcopy semantics at a fraction of the cost.

    ``copy.deepcopy``'s generic machinery (memo dict, reconstruct, per-object
    dispatch) dominates the store's transaction reads at 100k-job scale; a
    typed clone of the known entity classes is ~10x cheaper while preserving
    the same guarantee: mutating the returned object (including its mutable
    containers) never affects the stored original.  Unknown types fall back
    to deepcopy.
    """
    fn = _CLONERS.get(type(ent))
    return fn(ent) if fn is not None else _copy.deepcopy(ent)


# per-class field-name cache for to_json: the journal serializes every
# committed entity, so the generic ``dataclasses.asdict`` path (recursive
# deepcopy machinery, then to_json recursing AGAIN over the copy) was the
# single hottest function of the REST submit path — ~86% of an in-process
# batch submit's wall time at batch 20.  Walking getattr over cached
# field names emits the identical wire form at ~10x less cost (the same
# move fast_clone makes over copy.deepcopy).
_TO_JSON_FIELDS: Dict[type, tuple] = {}
_JSON_SCALARS = frozenset((str, int, float, bool, type(None)))


def to_json(obj: Any) -> Any:
    """Recursively convert entities to JSON-serializable structures."""
    cls = obj.__class__
    if cls in _JSON_SCALARS:
        return obj
    names = _TO_JSON_FIELDS.get(cls)
    if names is not None:
        return {n: to_json(getattr(obj, n)) for n in names}
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        names = tuple(f.name for f in dataclasses.fields(cls))
        _TO_JSON_FIELDS[cls] = names
        return {n: to_json(getattr(obj, n)) for n in names}
    if isinstance(obj, dict):
        return {k: to_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_json(v) for v in obj]
    return obj
