"""Transactional in-memory state store with guard transactions and a tx feed.

Plays the role of the reference's Datomic peer + transactor
(reference: scheduler/src/cook/datomic.clj, schema.clj db-fns,
metatransaction/core.clj):

- **All-or-nothing transactions** with an undo log; a guard raising
  :class:`AbortTransaction` rolls everything back (the reference's
  ":job/allowed-to-start? aborts the txn" discipline, schema.clj:1311-1325).
- **Tx-report feed**: subscribers receive the event list of every committed
  transaction (reference: create-tx-report-mult datomic.clj:49, consumed by
  monitor-tx-report-queue scheduler.clj:378-448 to kill orphaned instances).
- **Commit latch**: batch-submitted jobs stay invisible to queries until the
  latch commits (reference: metatransactions + :job/commit-latch schema.clj:28).
- **Snapshot/restore**: full-state JSON round-trip; a new leader resumes by
  re-reading state (SURVEY.md section 5 checkpoint/resume).
- **Durable redo journal**: every committed transaction's write/delete set is
  appended as one JSON line; :meth:`Store.open` replays snapshot + journal so
  a restarted leader re-reads everything, like the reference's leader
  re-reading Datomic (mesos.clj:296-313). :meth:`checkpoint` compacts.
"""

from __future__ import annotations

import copy
import errno
import json
import os
import threading
import time
from contextlib import nullcontext
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..utils import tracing
from ..utils.locks import named_lock, named_rlock
from ..utils.metrics import registry as _metrics
from . import machines
from .integrity import (
    JournalCorruptionError,
    ScanResult,
    hygiene_sweep,
    scan_journal,
    seal_record,
    verify_snapshot,
    verify_window,
    write_manifest,
)
from .schema import (
    Application,
    fast_clone,
    Checkpoint,
    CheckpointMode,
    Constraint,
    DruMode,
    Group,
    GroupPlacementType,
    Instance,
    InstanceStatus,
    Job,
    JobState,
    Pool,
    QuotaEntry,
    Resources,
    SchedulerKind,
    ShareEntry,
    now_ms,
    to_json,
)


class StaleEpochError(RuntimeError):
    """A deposed leader attempted to touch a journal another leader has
    fenced at a higher election epoch."""


class StorageFullError(OSError):
    """ENOSPC on the journal write path.  A CLEAN abort: the torn
    fragment (if any) was excised, nothing installed, the store keeps
    serving reads — the REST layer maps this to 503 and escalates the
    admission controller to its shed-writes stage (sched/admission.py)
    instead of the daemon dying on a full disk.  Subclasses OSError so
    every pre-existing ``except OSError`` around an append still
    catches a full disk."""


class ReplicationTimeout(RuntimeError):
    """Sync replication refused the transaction BEFORE its record was
    written anywhere (the CP quorum gate, or the stream down pre-write):
    a clean abort — nothing on disk, nothing installed, safe to retry."""


class ReplicationIndeterminate(RuntimeError):
    """Sync replication could not CONFIRM the transaction: the record is
    durable in the local journal and may or may not have reached a
    mirror.  The transaction IS applied locally (excising the record
    would resurrect it as a phantom commit on a mirror that did fsync it
    before a failover — ADVICE r5), but the caller must report the
    outcome as ambiguous: if this leader survives, the record re-syncs
    and the commit stands; if a mirror that missed it promotes, the
    commit is lost.  Journal replay resolves it on the next open either
    way.  REST surfaces this as HTTP 504 with an ``indeterminate`` body;
    retries are safe — submission is idempotent on job uuid."""


class AbortTransaction(Exception):
    """Raised inside a transaction to roll back all of its writes."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class TxEvent:
    __slots__ = ("kind", "data")

    def __init__(self, kind: str, **data: Any):
        self.kind = kind
        self.data = data

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TxEvent({self.kind}, {self.data})"


class _Txn:
    """One open transaction: copy-on-write views over the store's entity maps."""

    #: peeked store entities spot-checked per txn (``__debug__`` only):
    #: mutation by a guard is deterministic, so checking the first few
    #: catches it without taxing 1000-launch batches
    _PEEK_CHECKS = 8

    def __init__(self, store: "Store"):
        self._store = store
        self._writes: Dict[Tuple[str, str], Any] = {}
        self._deletes: set = set()
        self.events: List[TxEvent] = []
        # latch registrations/releases applied atomically with the commit
        self.latch_registrations: List[Tuple[str, List[str]]] = []
        self.latch_pops: List[str] = []
        # (table, key, entity, fingerprint) of peeked LIVE store entities,
        # re-verified at commit (__debug__ only; see peek())
        self._peeks: List[Tuple[str, str, Any, str]] = []

    def _get(self, table: str, key: str, for_write: bool,
             clone: bool = True) -> Any:
        wk = (table, key)
        if wk in self._deletes:
            return None
        if wk in self._writes:
            return self._writes[wk]
        ent = getattr(self._store, "_" + table).get(key)
        if ent is None:
            return None
        if not clone:
            # peek mode: a guard that only INSPECTS must not pay the
            # defensive copy; the caller promises not to mutate
            return ent
        # Reads are deep-copied too: a transaction fn mutating a read-returned
        # entity must not leak into the store outside the write log (the
        # all-or-nothing guarantee would silently break on abort otherwise).
        ent = fast_clone(ent)
        if for_write:
            self._writes[wk] = ent
        return ent

    # -- reads (txn-local view) ---------------------------------------------
    def job(self, uuid: str) -> Optional[Job]:
        return self._get("jobs", uuid, for_write=False)

    def instance(self, task_id: str) -> Optional[Instance]:
        return self._get("instances", task_id, for_write=False)

    def group(self, uuid: str) -> Optional[Group]:
        return self._get("groups", uuid, for_write=False)

    def instances_of(self, job: Job) -> Dict[str, Instance]:
        return {tid: inst for tid in job.instances
                if (inst := self._get("instances", tid, for_write=False)) is not None}

    # -- writes --------------------------------------------------------------
    def job_w(self, uuid: str) -> Optional[Job]:
        return self._get("jobs", uuid, for_write=True)

    def instance_w(self, task_id: str) -> Optional[Instance]:
        return self._get("instances", task_id, for_write=True)

    def group_w(self, uuid: str) -> Optional[Group]:
        return self._get("groups", uuid, for_write=True)

    def put(self, table: str, key: str, entity: Any) -> None:
        self._deletes.discard((table, key))
        self._writes[(table, key)] = entity

    def delete(self, table: str, key: str) -> None:
        self._writes.pop((table, key), None)
        self._deletes.add((table, key))

    def peek(self, table: str, key: str) -> Any:
        """Txn-consistent READ-ONLY view WITHOUT the defensive clone.
        For guards that only inspect: _get's copy-on-read exists so a
        mutating txn fn can't leak into the store, but a guard that
        mutates nothing pays the full entity clone for every launch.
        The caller MUST NOT mutate the returned entity — under
        ``__debug__`` a fingerprint taken here is re-checked at commit
        (``_verify_peeks``), so a guard that breaks the promise fails the
        transaction loudly instead of silently corrupting committed
        state outside the undo log."""
        ent = self._get(table, key, for_write=False, clone=False)
        if __debug__ and ent is not None \
                and (table, key) not in self._writes \
                and len(self._peeks) < self._PEEK_CHECKS:
            # only LIVE store entities are frozen; a peek that resolved
            # to this txn's own write intent may be legally mutated via
            # the _w accessors afterwards
            self._peeks.append((table, key, ent, repr(ent)))
        return ent

    def _verify_peeks(self) -> None:
        """``__debug__``-mode commit check: no peeked store entity was
        mutated (peek's no-clone contract, spot-checked)."""
        for table, key, ent, fp in self._peeks:
            if repr(ent) != fp:
                raise AssertionError(
                    f"peeked entity {table}/{key} was mutated inside the "
                    "transaction: peek()/peek_instances_of return LIVE "
                    "store entities; use the *_w accessors for writes")

    def peek_instances_of(self, job: Job) -> Dict[str, Instance]:
        """``instances_of`` for read-only guards (no defensive clones):
        one definition of "a job's instances as this txn sees them"."""
        return {tid: inst for tid in job.instances
                if (inst := self.peek("instances", tid)) is not None}

    def abort(self, reason: str) -> None:
        raise AbortTransaction(reason)

    def event(self, kind: str, **data: Any) -> None:
        self.events.append(TxEvent(kind, **data))

    def create_new_jobs(self, jobs: List[Job], now: int,
                        committed: bool) -> List[str]:
        """Bulk insert of FRESH jobs — the hottest write path at the
        1M-job design point.  Owns the same bookkeeping put()/event()
        do, with the per-call wrapper overhead hoisted out of the loop;
        living on _Txn keeps the writes/deletes/events invariants in one
        class (the never-in-both rule, delete-then-recreate legality)."""
        writes, deletes, events = self._writes, self._deletes, self.events
        existing = self._store._jobs
        for job in jobs:
            u = job.uuid
            key = ("jobs", u)
            if (u in existing and key not in deletes) or key in writes:
                # same visibility rule as self.job(): deletes shadow the
                # store, so same-txn delete-then-recreate stays legal
                self.abort(f"duplicate job uuid {u}")
            deletes.discard(key)
            job = fast_clone(job)
            if not job.submit_time_ms:
                job.submit_time_ms = now
            job.last_waiting_start_ms = job.submit_time_ms
            job.committed = committed
            writes[key] = job
            events.append(TxEvent("job-created", uuid=u,
                                  user=job.user, pool=job.pool,
                                  **({"trace": job.trace_id}
                                     if job.trace_id else {})))
        return [j.uuid for j in jobs]

    # -- composite ops shared by several public store methods ---------------
    def recompute_job_state(self, job: Job) -> None:
        """Re-derive job state from instances; emits job-state event on change
        (reference: :job/update-state side of :instance/update-state)."""
        # next_job_state only READS the instances — the non-cloning view
        # saves one Instance clone per live attempt on every status update
        new_state, reason = machines.next_job_state(
            job, self.peek_instances_of(job))
        if new_state is not job.state:
            old = job.state
            job.state = new_state
            if new_state is JobState.WAITING:
                job.last_waiting_start_ms = self._store.clock()
            self.event("job-state", uuid=job.uuid, old=old.value,
                       new=new_state.value, reason=reason)


#: group-commit batch-size histogram bounds (records per durability round)
_GC_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                     512.0)


class _CommitWaiter:
    """One transaction's slot in a group-commit batch: resolved by the
    committer with this txn's outcome (None = confirmed committed, else
    the exception to raise) plus the shared round's cost breakdown so the
    waiter can attribute it into its own request trace."""

    __slots__ = ("offset", "done", "error", "batch_size", "fsync_s",
                 "ack_s", "stage")

    def __init__(self, offset: int, stage: "_GroupCommitStage"):
        self.offset = offset
        self.stage = stage
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.batch_size = 0
        self.fsync_s = 0.0
        self.ack_s = 0.0


class _GroupCommitStage:
    """Commit-latch group commit (the Gray/DeWitt lineage — amortize one
    log force across concurrent writers; the same move the fused cycle
    makes batching a whole match cycle's launches into one txn).

    Records are already WRITTEN + FLUSHED in commit order under the store
    lock when they reach this stage — a failed write still aborts cleanly
    inline.  What moves here is the expensive durability tail: ONE
    ``os.fsync`` and ONE ``repl.wait_acked(max offset)`` per batch
    instead of per transaction, with per-transaction outcomes
    (committed / :class:`ReplicationIndeterminate` — the PR 3 contract)
    demultiplexed back to each waiter.  A clean abort can no longer
    happen past this point: once a record is flushed and installed (and
    later transactions may have built on it), an unconfirmed fsync or
    ack is INDETERMINATE, never excised.

    Lock order: committers hold the store lock when enqueueing (store
    lock -> stage condvar); the committer thread takes the store lock
    only with the condvar released — no cycle."""

    def __init__(self, store: "Store", window_ms: float = 0.5,
                 max_batch: int = 256):
        self._store = store
        self.window_s = max(float(window_ms), 0.0) / 1000.0
        self.max_batch = max(int(max_batch), 1)
        self._cv = threading.Condition()
        self._pending: List[_CommitWaiter] = []
        self._stopped = False
        # advisory counters (single writer: the committer thread)
        self.batches = 0
        self.commits = 0
        self.indeterminate = 0
        self.max_batch_seen = 0
        _pl = store.partition_label()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="cook-group-commit" + (f"-{_pl}" if _pl else ""))
        self._thread.start()

    def enqueue(self, offset: int) -> _CommitWaiter:
        w = _CommitWaiter(int(offset), self)
        with self._cv:
            if self._stopped:
                # a closing store can no longer confirm durability; the
                # record is journaled+flushed, so the honest outcome is
                # the ambiguous one, not a hang
                w.error = ReplicationIndeterminate(
                    "store closing: group-commit durability unconfirmed")
                w.done.set()
                return w
            self._pending.append(w)
            self._cv.notify()
        return w

    def wait(self, w: _CommitWaiter) -> Optional[BaseException]:
        """Block until the waiter's batch resolves; returns the outcome
        exception (None = confirmed).  Bounded: the committer's own
        timeouts resolve every batch, but a committer death must not
        hang every writer forever."""
        timeout = max(60.0, float(self._store._repl_timeout_s) * 4)
        if not w.done.wait(timeout=timeout):
            return ReplicationIndeterminate(
                "group-commit round did not resolve in time; the record "
                "is journaled and flushed but durability is unconfirmed")
        return w.error

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            pending = len(self._pending)
        _pl = self._store.partition_label()
        return {"pending": pending, "batches": self.batches,
                "commits": self.commits,
                "indeterminate": self.indeterminate,
                "max_batch": self.max_batch_seen,
                "window_ms": round(self.window_s * 1000.0, 3),
                **({"partition": _pl} if _pl else {})}

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify()
        self._thread.join(timeout=5.0)

    # ------------------------------------------------------------ committer
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stopped:
                    self._cv.wait()
                if not self._pending:
                    return  # stopped and drained
                if self.window_s > 0 and not self._stopped \
                        and len(self._pending) < self.max_batch:
                    # coalescing window: stragglers arriving during the
                    # previous round's fsync/ack already batched; this
                    # only catches near-simultaneous committers
                    self._cv.wait(self.window_s)
                batch = self._pending[:self.max_batch]
                del self._pending[:len(batch)]
            self._commit_batch(batch)

    def _commit_batch(self, batch: List[_CommitWaiter]) -> None:
        from ..utils.faults import injector as _faults
        from ..utils.metrics import registry
        store = self._store
        target = max(w.offset for w in batch)
        n = len(batch)
        err: Optional[BaseException] = None
        fsync_s = ack_s = 0.0
        if store._journal_fsync:
            t0 = time.perf_counter()
            try:
                _faults.fire(
                    "store.journal.fsync",
                    lambda: OSError("injected journal fsync failure"))
                with store._lock:
                    f = store._journal_file
                if f is None:
                    # the store CLOSED under the stage (close() drains
                    # the committer first, so this only happens when
                    # that join timed out): no checkpoint covered the
                    # batch — the honest outcome is the ambiguous one,
                    # never a silently-skipped fsync reported committed
                    raise RuntimeError("journal closed mid-batch")
                os.fsync(f.fileno())
            except ValueError:
                # checkpoint() closed/swapped the journal between this
                # batch's writes and the fsync (a plain close() drains
                # this stage before touching the file): the atomic
                # snapshot — written under the store lock AFTER these
                # records installed, with its own fsync discipline —
                # durably covers every one, so the batch is confirmed
                pass
            except Exception as e:
                err = ReplicationIndeterminate(
                    "group-commit fsync failed; the batch is flushed to "
                    f"the OS but unconfirmed on disk: {e}")
            fsync_s = time.perf_counter() - t0
        srv = store._repl_server
        if err is None and srv is not None and store._repl_sync:
            t0 = time.perf_counter()
            acked = False
            try:
                _faults.fire(
                    "repl.ack",
                    lambda: ReplicationIndeterminate(
                        "injected replication ack loss"))
                acked = srv.wait_acked(target, store._repl_timeout_s)
            except ReplicationIndeterminate as e:
                err = e
            ack_s = time.perf_counter() - t0
            if err is None:
                if not acked and store._commit_offset < target:
                    # a checkpoint() interleaved between this batch's
                    # writes and the ack wait: the journal offset space
                    # re-based (followers full-resync from the new
                    # snapshot, which — written under the store lock
                    # AFTER these writes installed — covers every
                    # record), so the old-space target is unreachable
                    # by construction, not unconfirmed.  Same reasoning
                    # as the fsync half's closed-file case.
                    acked = True
                if not acked:
                    err = ReplicationIndeterminate(
                        "followers did not ack within "
                        f"{store._repl_timeout_s}s; the batch is in the "
                        "local journal and MAY be mirrored — it stands "
                        "if this leader survives and resolves at the "
                        "next failover replay otherwise")
                elif (store._repl_min_followers > 0
                      and srv.synced_follower_count
                      < store._repl_min_followers):
                    # same post-wait quorum recheck as the inline path
                    err = ReplicationIndeterminate(
                        "follower lost during ack wait; quorum below "
                        f"{store._repl_min_followers} — the batch is "
                        "journaled locally and may be mirrored")
        _pl = store.partition_label()
        registry.observe("cook_group_commit_batch_size", float(n),
                         buckets=_GC_BATCH_BUCKETS,
                         # per-partition series in the partitioned plane
                         # (docs/OBSERVABILITY.md); the classic plane's
                         # unlabeled series stays wire-identical
                         labels={"partition": _pl} if _pl else None)
        self.batches += 1
        self.max_batch_seen = max(self.max_batch_seen, n)
        if err is None:
            self.commits += n
        else:
            self.indeterminate += n
        for w in batch:
            w.batch_size = n
            w.fsync_s = fsync_s
            w.ack_s = ack_s
            w.error = err
            w.done.set()


#: The journal record-kind PROTOCOL REGISTRY — the one static home of
#: every top-level key a journal record may carry (docs/ROBUSTNESS.md
#: replay-completeness contract).  The `cs lint` journal-record pass
#: (cook_tpu/analysis/summaries.py) statically diffs this table against
#: (a) every key written at a ``journal_file.write(json.dumps(...))``
#: site and (b) every key handled by ``_apply_journal_record`` /
#: ``_replay_records`` — so a new record kind cannot ship without a
#: replay handler (it would silently vanish on leader replay, on
#: checkpoint re-seed, and on the read-replica tail), and a retired
#: kind cannot linger here undocumented.  Each value states the
#: kind's replay + checkpoint semantics.
JOURNAL_RECORD_KINDS: Dict[str, str] = {
    "tx": "transaction id high-water mark; applied by "
          "_apply_journal_record, re-derived from the snapshot after a "
          "checkpoint compaction",
    "ep": "election-epoch qualifier; drives the fence-skip rule in "
          "_replay_records (one home, shared with the read-replica "
          "tail) — lower-epoch records after a higher-epoch one were "
          "appended by a deposed leader and never committed",
    "barrier": "leader-takeover no-op marking the epoch boundary "
               "(open_exclusive); consumed by _replay_records, never "
               "applied as state",
    "w": "entity writes (table/key -> json); replayed by "
         "_apply_journal_record, absorbed into the snapshot at "
         "checkpoint",
    "d": "entity deletes (table/key); replayed by "
         "_apply_journal_record, absorbed into the snapshot at "
         "checkpoint",
    "lr": "latch registrations (latch uuid -> job uuids); replayed by "
          "_apply_journal_record, snapshot carries the latch table",
    "lp": "latch pops; replayed by _apply_journal_record",
    "a": "per-job audit docs (utils/audit.py) riding their txn record "
         "or a flush_audit advisory batch; replayed into the audit "
         "trail, RE-SEEDED into the fresh journal at checkpoint "
         "(the snapshot carries no audit lane), and applied by the "
         "read-replica tail so follower timeline GETs work",
}


class Store:
    """Thread-safe entity store. All mutation goes through :meth:`transact`."""

    def __init__(self, partition: Optional[int] = None) -> None:
        #: partition index when this store is one shard of a partitioned
        #: write plane (state/partition.py): scopes the lock names into
        #: the ``store[pN]`` rank family, qualifies the commit token with
        #: ``pN:`` (its own offset space — offsets are NEVER comparable
        #: across partitions), and labels the per-partition metrics.
        #: None = the classic single-store plane, wire-compatible with
        #: every prior round (P=1 compatibility mode).
        self.partition = partition
        _sfx = f"[p{partition}]" if partition is not None else ""
        # named+ranked for the lock-order sanitizer (utils/locks.py owns
        # the global acquisition-order contract; docs/ANALYSIS.md) —
        # partitioned stores get sibling-suffixed names so cross-partition
        # nesting is a reported violation from day one
        self._lock = named_rlock("store" + _sfx)
        # Injectable clock for every entity timestamp (submit/start/end/
        # queue-time); the simulator swaps in its virtual clock so recorded
        # wait times stay in trace time instead of mixing epochs.
        self.clock = now_ms
        self._jobs: Dict[str, Job] = {}
        self._instances: Dict[str, Instance] = {}
        self._groups: Dict[str, Group] = {}
        self._pools: Dict[str, Pool] = {}
        self._shares: Dict[str, ShareEntry] = {}   # key: f"{user}/{pool}"
        self._quotas: Dict[str, QuotaEntry] = {}   # key: f"{user}/{pool}"
        # dynamic config documents (reference: the DB-backed no-restart
        # config planes — rebalancer params at rebalancer.clj:535-557)
        self._configs: Dict[str, Dict[str, Any]] = {}
        # crash-consistent launch intents: one record per instance whose
        # backend dispatch has not been confirmed yet, written in the SAME
        # transaction as the instance (docs/ROBUSTNESS.md).  A leader that
        # dies between match and launch-ack leaves the intent in the
        # journal; startup reconciliation sweeps intents against actual
        # cluster state so the task is exactly-once relaunched or refunded
        # — never duplicated, never lost.
        self._intents: Dict[str, Dict[str, Any]] = {}
        self._latches: Dict[str, List[str]] = {}   # latch uuid -> job uuids
        self._tx_id = 0
        self._subscribers: List[Callable[[int, List[TxEvent]], None]] = []
        # Commit-ordered event delivery (the reference's tx-report *queue*):
        # events enqueue under the main lock and drain under _notify_lock, so
        # subscribers always observe transactions in tx_id order.
        self._event_queue: List[Tuple[int, List[TxEvent]]] = []
        self._notify_lock = named_lock("store.notify" + _sfx)
        self._draining = threading.local()
        # durable redo journal (attached via attach_journal / Store.open)
        self._journal_file = None
        self._journal_path: Optional[str] = None
        self._journal_dir: Optional[str] = None
        self._journal_fsync = False
        self._journal_poisoned = False
        # election-epoch fencing for a SHARED journal directory (the
        # reference's Datomic transactor is a networked store any new
        # leader re-reads, mesos.clj:153-328; here the journal dir is the
        # shared medium, so a deposed-but-alive leader must be prevented
        # from appending records a successor would replay)
        self._journal_epoch: Optional[int] = None
        self._epoch_path: Optional[str] = None
        self._epoch_stat: Optional[Tuple[int, int]] = None
        # socket journal replication (state/replication.py): when attached
        # with sync=True, a transaction only commits once every connected
        # follower fsynced its journal record (networked-durability slot,
        # reference: datomic.clj:79 out-of-process store)
        self._repl_server = None
        self._repl_sync = False
        self._repl_timeout_s = 5.0
        self._repl_min_followers = 0
        # byte offset of the journal end after the most recent committed
        # record — the leader's commit position, returned on REST write
        # responses (X-Cook-Commit-Offset) so clients can demand
        # read-your-writes from the follower fleet
        self._commit_offset = 0
        # group-commit admission batching (docs/PERFORMANCE.md): when
        # enabled, concurrent transactions' fsync + replication ack
        # rounds are amortized by a single committer thread
        self._group_commit: Optional[_GroupCommitStage] = None
        # True when the journal DIRECTORY is shared between leader hosts
        # (r4 topology: fencing protects concurrent appenders).  False for
        # a local fenced journal in the replication topology, where a
        # failed append may safely truncate (no concurrent appender).
        self._journal_shared = True
        # storage-integrity bookkeeping (docs/ROBUSTNESS.md WAL v2): the
        # background scrub's verified frontier + corruption/repair
        # counters, the boot hygiene sweep's removal count, and ENOSPC
        # clean aborts — surfaced on GET /debug/storage and the monitor's
        # storage sweep
        self._scrub_offset = 0
        self._scrub_corruptions = 0
        self._scrub_repairs = 0
        self._scrub_last_ts = 0.0
        self._hygiene_removed = 0
        self._enospc_aborts = 0
        # per-job scheduling audit trail (utils/audit.py): lifecycle
        # events feed off this store's tx events and are journaled
        # atomically with their transaction ("a" key on the txn record);
        # decision paths record advisory events directly and
        # flush_audit() journals them once per cycle.  Store-scoped (not
        # a module global) so a promoted leader's replayed trail is
        # genuinely its own, not a leak from the deposed process.
        from ..utils.audit import AuditTrail
        self.audit = AuditTrail(clock=lambda: self.clock())
        # fed through the commit-ordered subscriber queue (FIRST in the
        # list, ahead of any scheduler subscription): recording inline
        # after the lock release could interleave two transactions'
        # lifecycle events out of commit order (e.g. "instance: running"
        # before "launched"), diverging from the journal's "a"-record
        # order a promoted leader would replay
        self._subscribers.append(
            lambda _tx_id, events: self.audit.on_tx_events(events))

    # ------------------------------------------------------------------ txns
    def transact(self, fn: Callable[[_Txn], Any]) -> Any:
        """Run ``fn`` transactionally. Its writes are installed atomically on
        normal return; AbortTransaction rolls back and re-raises.

        :class:`ReplicationIndeterminate` is the one exception that does
        NOT roll back: the record is already durable in the local journal
        (and possibly on a mirror), so the writes install locally and the
        exception re-raises for the caller to report the ambiguous
        outcome (docs/DEPLOY.md indeterminate-commit contract).

        Under group commit the record is written+flushed (and the writes
        installed) inside the lock as always, but the fsync/replication-
        ack round resolves on the shared committer AFTER the lock is
        released — this thread blocks on its waiter and re-raises the
        demuxed outcome, so callers observe the same contract with the
        expensive tail amortized across concurrent committers."""
        indeterminate: Optional[ReplicationIndeterminate] = None
        waiter: Optional[_CommitWaiter] = None
        with self._lock:
            if self._journal_poisoned:
                raise RuntimeError(
                    "journal poisoned by a failed append; reopen the store")
            txn = _Txn(self)
            result = fn(txn)  # AbortTransaction propagates; nothing installed
            if __debug__:
                txn._verify_peeks()
            self._tx_id += 1
            # Write-ahead: journal BEFORE installing, so a failed append
            # (disk full, bad fd) aborts the transaction instead of leaving
            # committed in-memory state that silently vanishes on replay.
            # A torn tail line is truncated by recovery on the next open.
            if self._journal_file is not None and (
                    txn._writes or txn._deletes or txn.latch_registrations
                    or txn.latch_pops):
                try:
                    waiter = self._journal_append(txn)
                except ReplicationIndeterminate as e:
                    indeterminate = e  # locally durable: install below
            for (table, key), ent in txn._writes.items():
                getattr(self, "_" + table)[key] = ent
            for table, key in txn._deletes:
                getattr(self, "_" + table).pop(key, None)
            for latch, uuids in txn.latch_registrations:
                self._latches.setdefault(latch, []).extend(uuids)
            for latch in txn.latch_pops:
                self._latches.pop(latch, None)
            if txn.events:
                self._event_queue.append((self._tx_id, txn.events))
        self._drain_events()
        if waiter is not None:
            err = waiter.stage.wait(waiter)
            # attribute the SHARED round's cost into this request's own
            # trace/phase breakdown (rest/instrument.py PHASE_SPANS):
            # the committer measured it once; every waiter reports it
            if tracing.tracer.io_spans \
                    and tracing.tracer.current() is not None:
                if waiter.fsync_s:
                    tracing.tracer.record_finished(
                        "journal.fsync", waiter.fsync_s,
                        batch=waiter.batch_size, offset=waiter.offset)
                if waiter.ack_s:
                    tracing.tracer.record_finished(
                        "repl.ack_wait", waiter.ack_s,
                        batch=waiter.batch_size, offset=waiter.offset)
            if err is not None and indeterminate is None:
                indeterminate = err if isinstance(
                    err, ReplicationIndeterminate) \
                    else ReplicationIndeterminate(str(err))
        if indeterminate is not None:
            raise indeterminate
        return result

    def _journal_append(self, txn: _Txn) -> None:
        """Append one committed transaction to the redo journal (caller holds
        the store lock, so records are in commit order).  Returns a
        :class:`_CommitWaiter` when the durability tail (fsync +
        replication ack) was handed to the group-commit stage — transact
        blocks on it outside the lock — and None when it completed
        inline.

        On a failed append the torn fragment is truncated away so later
        appends stay parseable; if even the truncate fails the journal is
        poisoned (closed) and every subsequent transact raises — recovery
        only repairs a torn TAIL, so writing anything after an unexcised
        fragment would silently discard it and everything later on replay.
        """
        if self._journal_epoch is not None:
            self._check_fence()
        rec: Dict[str, Any] = {"tx": self._tx_id}
        if self._journal_epoch is not None:
            rec["ep"] = self._journal_epoch
        if txn._writes:
            rec["w"] = {f"{table}/{key}": to_json(ent)
                        for (table, key), ent in txn._writes.items()}
        if txn._deletes:
            rec["d"] = [f"{table}/{key}" for table, key in txn._deletes]
        if txn.latch_registrations:
            rec["lr"] = txn.latch_registrations
        if txn.latch_pops:
            rec["lp"] = txn.latch_pops
        if txn.events and self.audit.enabled and self.audit.journal:
            # lifecycle audit docs ride the SAME record as their
            # transaction: replay (and a promoted mirror's replay)
            # rebuilds the per-job timeline with zero extra appends
            from ..utils.audit import tx_event_to_audit
            ts = self.clock()
            docs = []
            for e in txn.events:
                wire = tx_event_to_audit(e)
                if wire is not None:
                    uuid, kind, data = wire
                    docs.append({"u": uuid, "k": kind, "t": ts,
                                 **({"d": data} if data else {})})
            if docs:
                rec["a"] = docs
        f = self._journal_file
        # every append flushes, so the buffer is empty here and tell() is
        # the true end-of-good-records offset
        good_offset = f.tell()
        from ..utils.faults import injector as _faults
        # Pre-write replication gates: failures HERE are clean aborts —
        # the record exists nowhere, so nothing to excise and no phantom
        # possible.  The CP quorum gate moved ahead of the write for
        # exactly that reason: refusing AFTER the write would leave a
        # record some catching-up follower may already be pulling.
        if self._repl_server is not None:
            _faults.fire(
                "repl.stream",
                lambda: ReplicationTimeout("injected replication "
                                           "stream fault"))
            if (self._repl_sync and self._repl_min_followers > 0 and
                    self._repl_server.synced_follower_count
                    < self._repl_min_followers):
                # SYNCED followers: one mid-catch-up neither acks nor
                # counts, else the CP gate would pass while wait_acked
                # ignores it (vacuous durability)
                raise ReplicationTimeout(
                    f"{self._repl_server.synced_follower_count} "
                    "synced follower(s) < required "
                    f"{self._repl_min_followers}")
        # request-path I/O spans (docs/OBSERVABILITY.md serving plane):
        # opened only under an ACTIVE trace — a REST write's http.request
        # root or a scheduler cycle — so bare-store bulk loads and
        # background status txns stay span-free.  tracer.io_spans is the
        # rest_plane bench's A/B gate for exactly this instrumentation.
        _io = tracing.tracer.io_spans and tracing.tracer.current() is not None
        # group commit engages only when there is a durability tail to
        # amortize (an fsync or a sync replication ack); otherwise the
        # inline path below already ends at the flush
        _gc = self._group_commit if (
            self._group_commit is not None
            and (self._journal_fsync
                 or (self._repl_server is not None and self._repl_sync))
        ) else None
        # the ONE blessed appender: every record leaves through
        # seal_record's checksummed v2 frame (state/integrity.py) — the
        # `cs lint` journal-raw-write pass rejects journal writes that
        # bypass it, because an unsealed line replays as v1 and forfeits
        # mid-file corruption detection for itself and its era
        line = seal_record(rec)
        waiter: Optional[_CommitWaiter] = None
        try:
            with (tracing.span("journal.append", bytes=len(line),
                               fsync=(self._journal_fsync and _gc is None)
                               or None)
                  if _io else nullcontext()):
                _faults.fire(
                    "store.journal.append",
                    lambda: OSError("injected journal write failure"))
                _faults.fire(
                    "store.journal.enospc",
                    lambda: OSError(errno.ENOSPC,
                                    "injected disk full on append"))
                if _faults.should_fire("store.journal.torn_write"):
                    # a PREFIX of the frame lands, then the write fails —
                    # exactly the shape a crash mid-append leaves on
                    # disk, driving the except-handler's excision
                    cut = _faults.point_arg("store.journal.torn_write")
                    cut = int(cut) if cut is not None else len(line) // 2
                    # injected torn PREFIX of an already-sealed frame
                    # cs-lint: allow=journal-raw-write
                    f.write(line[:max(1, min(cut, len(line) - 1))])
                    f.flush()
                    raise OSError("injected torn journal write")
                f.write(line)
                f.flush()
                if _faults.should_fire("store.journal.bitflip"):
                    # silent bit rot inside the just-written frame: no
                    # error surfaces here by design — detection belongs
                    # to the CRC at scrub/replay time, never to the
                    # happy path
                    self._flip_bit(good_offset, len(line))
                if self._journal_fsync and _gc is None:
                    if _faults.should_fire("store.journal.fsync_lie"):
                        # the ATC'20 lie: fsync reports EIO, the page
                        # cache silently DROPS the dirty frame, and the
                        # next fsync succeeds as if nothing happened.
                        # Model the loss before raising; the abort path
                        # must not count this record as committed.
                        f.seek(good_offset)
                        f.truncate(good_offset)
                        raise OSError(errno.EIO, "injected fsync lie")
                    _faults.fire(
                        "store.journal.fsync",
                        lambda: OSError("injected journal fsync failure"))
                    os.fsync(f.fileno())
            self._commit_offset = f.tell()
            if self._repl_server is not None:
                # From here on the record is durable locally and visible
                # to followers: an unconfirmed ack is a first-class
                # INDETERMINATE outcome, not an abort.  Excising the
                # record (the pre-PR behavior) could resurrect it as a
                # phantom commit on a mirror that fsynced it before a
                # failover (ADVICE r5) — "aborted" must imply "nowhere".
                # Poked inline even under group commit: followers start
                # pulling while the batch coalesces.
                self._repl_server.poke()
            if _gc is not None:
                # the durability tail (fsync + ack) resolves on the
                # shared committer; transact blocks on the waiter AFTER
                # releasing the store lock and demuxes the outcome
                waiter = _gc.enqueue(self._commit_offset)
            elif self._repl_server is not None and self._repl_sync:
                with (tracing.span(
                        "repl.ack_wait", offset=f.tell(),
                        timeout_s=self._repl_timeout_s)
                      if _io else nullcontext()):
                    _faults.fire(
                        "repl.ack",
                        lambda: ReplicationIndeterminate(
                            "injected replication ack loss"))
                    acked = self._repl_server.wait_acked(
                        f.tell(), self._repl_timeout_s)
                if not acked:
                    raise ReplicationIndeterminate(
                        "followers did not ack within "
                        f"{self._repl_timeout_s}s; the record is in "
                        "the local journal and MAY be mirrored — "
                        "the commit stands if this leader survives "
                        "and resolves at the next failover replay "
                        "otherwise")
                if (self._repl_min_followers > 0 and
                        self._repl_server.synced_follower_count
                        < self._repl_min_followers):
                    # re-check AFTER the wait: a follower dying
                    # between the gate and the ack makes wait_acked
                    # pass vacuously (empty quorum) — that must not
                    # count as a confirmed CP commit
                    raise ReplicationIndeterminate(
                        "follower lost during ack wait; quorum "
                        f"below {self._repl_min_followers} — the "
                        "record is journaled locally and may be "
                        "mirrored")
        except ReplicationIndeterminate:
            raise  # durable locally: transact installs, caller reports
        except Exception as e:
            try:
                if self._journal_epoch is not None and self._journal_shared:
                    # SHARED journal: our tell() may be stale (a successor
                    # could have appended past it) — truncating would chop
                    # its records.  Poison instead; replay's torn-tail and
                    # stale-epoch handling repair the file on next open.
                    # (A LOCAL fenced journal — the replication topology —
                    # has no concurrent appender, so truncation is safe.)
                    raise OSError("fenced journal: no truncate")
                f.seek(good_offset)
                f.truncate(good_offset)
                self._bump_journal_gen()
            except Exception:
                # can't excise the torn fragment: poison the journal so no
                # later record can be appended after it
                self._journal_file = None
                self._journal_poisoned = True
                try:
                    f.close()
                except Exception:
                    pass
            if isinstance(e, OSError) and e.errno == errno.ENOSPC:
                # disk full is an OPERATIONAL condition, not disk damage:
                # the excision above already made it a clean abort, so
                # surface a typed error the REST layer maps to 503 +
                # admission write-shed instead of a dead daemon
                self._enospc_aborts += 1
                _metrics.counter_inc("cook_storage_enospc")
                raise StorageFullError(str(e)) from e
            raise
        return waiter

    def _flip_bit(self, start: int, length: int) -> None:
        """Flip one bit inside the journal byte range ``[start,
        start+length)`` — the ``store.journal.bitflip`` fault body,
        modeling silent media corruption UNDER a live appender.  The
        armed point's ``arg`` picks the byte offset within the frame
        (default: mid-payload, past the header so the CRC — not the
        frame parser — must catch it)."""
        if not self._journal_path or length <= 0:
            return
        from ..utils.faults import injector as _faults
        off = _faults.point_arg("store.journal.bitflip")
        off = int(off) if off is not None else length // 2
        off = max(0, min(off, length - 2))  # keep the newline intact
        try:
            with open(self._journal_path, "r+b") as bf:
                bf.seek(start + off)
                b = bf.read(1)
                if not b:
                    return
                bf.seek(start + off)
                bf.write(bytes([b[0] ^ 0x40]))
        except OSError:
            pass

    def enable_group_commit(self, window_ms: float = 0.5,
                            max_batch: int = 256) -> bool:
        """Arm the group-commit stage (docs/PERFORMANCE.md): concurrent
        write transactions share one journal fsync + one replication ack
        round, with per-request outcomes demultiplexed.  Returns False
        (a no-op) on a store without an attached journal — there is no
        durability tail to amortize.  Idempotent."""
        with self._lock:
            if self._group_commit is not None:
                return True
            if self._journal_file is None:
                return False
            self._group_commit = _GroupCommitStage(
                self, window_ms=window_ms, max_batch=max_batch)
        return True

    def disable_group_commit(self) -> None:
        """Drain and stop the committer; later transactions go back to
        inline fsync/ack."""
        with self._lock:
            gc, self._group_commit = self._group_commit, None
        if gc is not None:
            gc.stop()

    def group_commit_stats(self) -> Optional[Dict[str, Any]]:
        """Committer counters for /debug/replication and the monitor
        sweep (None when group commit is off)."""
        gc = self._group_commit
        return gc.stats() if gc is not None else None

    def commit_offset(self) -> int:
        """Journal byte offset after the most recently committed record.
        0 on journal-less stores."""
        return self._commit_offset

    def partition_label(self) -> Optional[str]:
        """``"p<i>"`` on a partitioned shard, None on the classic
        single-store plane — the metric-label / token-prefix form."""
        return f"p{self.partition}" if self.partition is not None else None

    def commit_token(self) -> str:
        """The read-your-writes token leader write responses carry
        (X-Cook-Commit-Offset; docs/DEPLOY.md): ``<epoch>:<offset>`` on
        epoch-fenced journals, bare ``<offset>`` otherwise.  The epoch
        qualifies the OFFSET SPACE — a follower still mirroring a
        previous leadership must not satisfy a new-space token just
        because its old-space byte count is numerically larger (every
        leadership change mints a higher epoch, and a determinate
        commit survives into every later epoch's journal by the no-loss
        guarantee).

        On a PARTITIONED shard the token is additionally qualified
        ``p<partition>:<epoch>:<offset>`` — the partition names the
        journal the offset lives in; two partitions' offsets are never
        comparable (state/partition.py owns the vector form clients
        carry)."""
        if self._journal_epoch is not None:
            token = f"{self._journal_epoch}:{self._commit_offset}"
        else:
            token = str(self._commit_offset)
        if self.partition is not None:
            return f"p{self.partition}:{token}"
        return token

    def flush_audit(self) -> int:
        """Journal the audit trail's pending ADVISORY events (ranked
        positions, skip/defer attributions) as one ``{"a": [...]}``
        record — called once per scheduler cycle, so pre-failover
        decision context survives a leader kill the same way entity
        state does (lifecycle events already rode their own txn
        records).  The advisory lane must never hurt the store: a
        fenced/deposed leader drops the flush silently, and a failed
        append excises its torn fragment with the same truncate/poison
        discipline as _journal_append (a torn audit line would merge
        with the NEXT committed record at replay and lose it).
        Returns the number of events journaled."""
        self.audit.publish_metrics()
        if not (self.audit.enabled and self.audit.journal) \
                or self._journal_file is None or self._journal_poisoned:
            # no durability to provide: drop the pending refs WITHOUT
            # serializing them (the in-memory lanes keep everything)
            self.audit.discard_pending()
            return 0
        with self._lock:
            if self._journal_file is None or self._journal_poisoned:
                self.audit.discard_pending()
                return 0
            if self._journal_epoch is not None:
                try:
                    self._check_fence()
                except StaleEpochError:
                    return 0  # deposed: advisory events just drop
            # drain UNDER the store lock (store lock -> audit lock is
            # the one ordering used everywhere): drained-but-unappended
            # events outside the lock could race a concurrent
            # checkpoint()'s re-seed and land in the fresh journal twice
            recs = self.audit.drain_durable()
            if not recs:
                return 0
            if not self._write_audit_record_locked(recs):
                return 0
        return len(recs)

    def _write_audit_record_locked(self, recs: List[Dict[str, Any]]
                                   ) -> bool:
        """Append one ``{"a": [...]}`` record; caller holds the store
        lock and has fence-checked.  Shares _journal_append's torn-write
        discipline (truncate the fragment, or poison when it can't be
        excised — a torn line would merge with the NEXT committed
        record at replay and lose it) and honors the fsync setting.
        Returns False on failure (advisory loss, store stays healthy)."""
        f = self._journal_file
        rec: Dict[str, Any] = {"a": recs}
        if self._journal_epoch is not None:
            rec["ep"] = self._journal_epoch
        good_offset = f.tell()
        try:
            f.write(seal_record(rec))
            f.flush()
            if self._journal_fsync:
                os.fsync(f.fileno())
        except Exception:
            try:
                if self._journal_epoch is not None \
                        and self._journal_shared:
                    raise OSError("fenced journal: no truncate")
                f.seek(good_offset)
                f.truncate(good_offset)
                self._bump_journal_gen()
            except Exception:
                self._journal_file = None
                self._journal_poisoned = True
                try:
                    f.close()
                except Exception:
                    pass
            return False
        self._commit_offset = f.tell()
        if self._repl_server is not None:
            # audit records mirror like any journal bytes, but are
            # never waited on — audit must not add commit latency
            self._repl_server.poke()
        return True

    def _bump_journal_gen(self) -> None:
        """Advance ``<dir>/journal_gen`` after ANY journal truncation.
        The replication server folds this counter into its mirror-base
        token, so a truncate-then-reappend (an excised aborted record
        replaced by a later commit of equal byte length) forces followers
        to full-resync instead of silently accepting diverged bytes at
        the same offset."""
        if not self._journal_dir:
            return
        from ..utils.fsatomic import read_int_file, write_atomic_int
        path = os.path.join(self._journal_dir, "journal_gen")
        write_atomic_int(path, (read_int_file(path, 0) or 0) + 1)

    def _drain_events(self) -> None:
        """Deliver queued events in commit order. Whoever holds _notify_lock
        drains everything; other committers' events ride along in order.
        A subscriber that itself transacts enqueues new events and returns —
        the outer drain loop delivers them after the current round, keeping
        every subscriber's view in tx_id order (and avoiding re-entry)."""
        if getattr(self._draining, "active", False):
            return
        while not self._notify_lock.acquire(blocking=False):
            # Another thread is draining and will deliver our events — unless
            # it is just exiting; spin until the queue empties or we win the
            # lock (waiting blocking would serialize commits behind callbacks).
            with self._lock:
                if not self._event_queue:
                    return
            time.sleep(0)
        self._draining.active = True
        try:
            while True:
                with self._lock:
                    if not self._event_queue:
                        return
                    tx_id, events = self._event_queue.pop(0)
                    subscribers = list(self._subscribers)
                for sub in subscribers:
                    sub(tx_id, events)
        finally:
            self._draining.active = False
            self._notify_lock.release()

    def subscribe(self, fn: Callable[[int, List[TxEvent]], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def ensure_index(self):
        """The columnar rank-path projection (state/index.py), attached on
        first use and kept fresh off the tx feed."""
        with self._lock:
            if getattr(self, "_index", None) is None:
                from .index import ColumnarIndex
                self._index = ColumnarIndex(self)
            return self._index

    # ----------------------------------------------------------- submission
    def create_jobs(self, jobs: Iterable[Job], groups: Iterable[Group] = (),
                    latch: Optional[str] = None) -> List[str]:
        """Batch-submit jobs. With ``latch``, jobs are invisible until
        :meth:`commit_latch` (metatransaction semantics)."""
        jobs = list(jobs)

        def _create(txn: _Txn) -> List[str]:
            now = self.clock()  # one clock read per batch, not per job
            for group in groups:
                existing = txn.group(group.uuid)
                if existing is not None:
                    merged = txn.group_w(group.uuid)
                    merged.jobs.extend(j for j in group.jobs if j not in merged.jobs)
                else:
                    txn.put("groups", group.uuid, fast_clone(group))
            uuids = txn.create_new_jobs(jobs, now,
                                        committed=latch is None)
            if latch is not None:
                # applied atomically with the commit, so a snapshot or a
                # concurrent commit_latch can never observe the jobs without
                # their latch entry (which would strand them uncommitted)
                txn.latch_registrations.append((latch, uuids))
            return uuids

        return self.transact(_create)

    def commit_jobs(self, uuids: List[str]) -> int:
        """Mark already-present jobs committed (visible) directly — the
        idempotent-resubmission healer: a replication-indeterminate
        submission can leave jobs created but their latch never
        committed; the client's retry (same uuids) lands here and makes
        them visible instead of stranding them forever."""

        def _commit(txn: _Txn) -> int:
            n = 0
            target = set(uuids)
            for uuid in uuids:
                job = txn.job(uuid)
                if job is not None and not job.committed:
                    job = txn.job_w(uuid)
                    job.committed = True
                    txn.event("job-committed", uuid=uuid)
                    n += 1
            # reap latches the indeterminate submission stranded: once
            # every member is committed (or gone), commit_latch will
            # never pop the entry, and it would otherwise leak into
            # every future checkpoint and replay
            for latch, members in self._latches.items():
                if all(u in target
                       or (j := txn.peek("jobs", u)) is None or j.committed
                       for u in members):
                    txn.latch_pops.append(latch)
            return n

        return self.transact(_commit)

    def commit_latch(self, latch: str) -> None:
        def _commit(txn: _Txn) -> None:
            # transact holds the store lock while fn runs, so the read of
            # _latches and the pop below are atomic with the job writes
            uuids = self._latches.get(latch, [])
            txn.latch_pops.append(latch)
            for uuid in uuids:
                job = txn.job_w(uuid)
                if job is not None:
                    job.committed = True
                    txn.event("job-committed", uuid=uuid)

        self.transact(_commit)

    def discard_latched(self, latch: str) -> int:
        """Abort a latched (still-invisible) sub-batch: delete its
        uncommitted jobs, scrub them out of any group they were merged
        into (dropping groups left empty), and pop the latch.  The
        rollback half of the partitioned facade's cross-partition
        fan-out (state/partition.py): when a LATER partition's
        sub-batch aborts, the earlier partitions' latched jobs were
        never observable — deleting them restores all-or-nothing
        submission semantics.  Jobs already committed (a concurrent
        commit_latch/commit_jobs won the race) are left alone."""

        def _discard(txn: _Txn) -> int:
            doomed = set()
            for uuid in self._latches.get(latch, []):
                job = txn.job(uuid)
                if job is not None and not job.committed:
                    txn.delete("jobs", uuid)
                    doomed.add(uuid)
            if doomed:
                for guuid in list(self._groups):
                    g = txn.group(guuid)
                    if g is None or not (set(g.jobs) & doomed):
                        continue
                    keep = [u for u in g.jobs if u not in doomed]
                    if keep:
                        txn.group_w(guuid).jobs = keep
                    else:
                        txn.delete("groups", guuid)
            txn.latch_pops.append(latch)
            return len(doomed)

        return self.transact(_discard)

    # -------------------------------------------------------------- launches
    def launch_instance(self, job_uuid: str, task_id: str, hostname: str,
                        slave_id: str = "", compute_cluster: str = "",
                        ports: Optional[List[int]] = None,
                        node_location: str = "") -> Instance:
        """Create an instance under the allowed-to-start guard; aborts (and
        therefore blocks the backend launch) if the job state moved
        (reference: scheduler.clj:987-1009 + schema.clj:1311-1325).
        Single-entry form of :meth:`launch_instances` (one body, one
        invariant)."""
        insts, failures = self.launch_instances([dict(
            job_uuid=job_uuid, task_id=task_id, hostname=hostname,
            slave_id=slave_id, compute_cluster=compute_cluster,
            ports=ports, node_location=node_location)])
        if failures:
            raise AbortTransaction(failures[0][1])
        return insts[0]

    def launch_instances(self, entries: List[Dict[str, Any]]
                         ) -> Tuple[List[Instance], List[Tuple[str, str]]]:
        """Batched launch guard: ONE transaction for a whole match cycle's
        launches (reference: launch-matched-tasks! builds every task txn and
        transacts once, scheduler.clj:810-1009), instead of a lock/journal/
        event-drain round per task.  Jobs whose allowed-to-start guard fails
        are skipped and reported — the transactional invariant (guard
        failure blocks the backend launch) holds per job.

        ``entries``: dicts with job_uuid, task_id, hostname and optional
        slave_id, compute_cluster, ports, node_location, gang (gang group
        uuid).  Entries sharing a ``gang`` are all-or-nothing: one
        member's guard denial fails every member in the same transaction
        — no partial gang ever launches (docs/GANG.md).  Returns
        (created instances, [(job_uuid, deny-reason), ...])."""

        def _launch_all(txn: _Txn):
            out: List[Instance] = []
            failures: List[Tuple[str, str]] = []
            t = self.clock()  # one clock read per batch (as create_jobs)
            # the enclosing scheduler cycle's trace: recorded on every
            # launched audit event so /debug/trace?job= can pull the
            # cycle flamegraph that placed the job next to its
            # submission request track (docs/OBSERVABILITY.md)
            _cur = tracing.tracer.current()
            cycle_trace = _cur.trace_id if _cur is not None else None
            # pass 1 — guards only (peek, no writes): gang atomicity needs
            # every member's verdict BEFORE any member's instance is put
            denied: Dict[int, str] = {}
            seen_jobs: set = set()
            for i, e in enumerate(entries):
                # the sequential guard used to catch a duplicate job via
                # its freshly-created live instance; the two-pass form
                # must deny it explicitly
                if e["job_uuid"] in seen_jobs:
                    denied[i] = "duplicate-in-batch"
                    continue
                seen_jobs.add(e["job_uuid"])
                # guard on a non-cloning PEEK: taking write intent first
                # would install (and journal) the unchanged entity even
                # when the guard denies — a lingering denied job would
                # append a no-op record to the redo journal every match
                # cycle — and a cloning read would pay a full Job copy
                # per launch just to inspect it (the hot path at 1000+
                # launches/cycle; txn.job_w below still owns the single
                # defensive clone for the mutation)
                job = txn.peek("jobs", e["job_uuid"])
                if job is None:
                    denied[i] = "no-such-job"
                    continue
                deny = machines.allowed_to_start(
                    job, txn.peek_instances_of(job))
                if deny is not None:
                    denied[i] = deny
            # gang propagation: any denied member denies its whole gang
            by_gang: Dict[str, List[int]] = {}
            for i, e in enumerate(entries):
                g = e.get("gang")
                if g:
                    by_gang.setdefault(g, []).append(i)
            for g, idxs in by_gang.items():
                bad = [i for i in idxs if i in denied]
                if bad:
                    reason = denied[bad[0]]
                    for i in idxs:
                        denied.setdefault(
                            i, f"gang-member-denied:{reason}")
            # pass 2 — create instances for the allowed entries
            for i, e in enumerate(entries):
                if i in denied:
                    failures.append((e["job_uuid"], denied[i]))
                    continue
                job = txn.job_w(e["job_uuid"])
                hostname = e["hostname"]
                inst = Instance(
                    task_id=e["task_id"], job_uuid=e["job_uuid"],
                    hostname=hostname,
                    slave_id=e.get("slave_id") or hostname,
                    compute_cluster=e.get("compute_cluster", ""),
                    status=InstanceStatus.UNKNOWN, start_time_ms=t,
                    ports=e.get("ports") or [],
                    node_location=e.get("node_location", ""),
                    queue_time_ms=max(0, t - job.last_waiting_start_ms))
                txn.put("instances", e["task_id"], inst)
                # launch intent, atomic with the instance: the dispatch to
                # the backend has NOT happened yet.  Cleared by the first
                # status update or an explicit clear_launch_intents after
                # the backend acked; swept by leader-startup reconciliation
                # against actual cluster state otherwise.
                txn.put("intents", e["task_id"], {
                    "task_id": e["task_id"], "job_uuid": e["job_uuid"],
                    "compute_cluster": e.get("compute_cluster", ""),
                    "hostname": hostname, "created_ms": t,
                    # gang group uuid: leader-startup reconciliation
                    # sweeps a gang's intents as one unit (refund any ->
                    # refund all, docs/GANG.md)
                    **({"gang": e["gang"]} if e.get("gang") else {})})
                job.instances.append(e["task_id"])
                job.state = JobState.RUNNING
                txn.event("instance-created", task_id=e["task_id"],
                          job=e["job_uuid"], hostname=hostname,
                          **({"gang": e["gang"]} if e.get("gang")
                             else {}),
                          **({"trace": job.trace_id}
                             if job.trace_id else {}),
                          **({"cycle_trace": cycle_trace}
                             if cycle_trace else {}))
                txn.event("job-state", uuid=e["job_uuid"], old="waiting",
                          new="running", reason=None)
                out.append(inst)
            return out, failures

        return self.transact(_launch_all)

    def update_instance_status(self, task_id: str, new_status: InstanceStatus,
                               reason_code: Optional[int] = None,
                               exit_code: Optional[int] = None,
                               preempted: bool = False,
                               hostname: Optional[str] = None) -> bool:
        """Instance state machine + job writeback (reference:
        :instance/update-state schema.clj:1242-1308). Returns False when the
        transition is illegal (stale status updates are dropped, not errors)."""

        def _update(txn: _Txn) -> bool:
            inst = txn.instance_w(task_id)
            if inst is None:
                return False
            # any backend status proves the dispatch reached the cluster:
            # the launch intent has served its purpose (guarded so the
            # common no-intent case journals nothing extra)
            if task_id in self._intents:
                txn.delete("intents", task_id)
            if inst.status is new_status:
                # Redelivered status (k8s watch replays, mesos re-sends): a
                # pure no-op — must not overwrite end_time/reason/exit_code.
                return True
            if not machines.instance_transition_allowed(inst.status, new_status):
                return False
            old = inst.status
            inst.status = new_status
            if hostname:
                # direct-mode backends report placement with the first status
                inst.hostname = hostname
                if not inst.slave_id:
                    inst.slave_id = hostname
            if reason_code is not None:
                inst.reason_code = reason_code
            if exit_code is not None:
                inst.exit_code = exit_code
            if preempted:
                inst.preempted = True
            if new_status in (InstanceStatus.SUCCESS, InstanceStatus.FAILED):
                inst.end_time_ms = self.clock()
            if new_status is InstanceStatus.RUNNING and inst.mesos_start_time_ms is None:
                inst.mesos_start_time_ms = self.clock()
            if old is not new_status:
                txn.event("instance-status", task_id=task_id, job=inst.job_uuid,
                          old=old.value, new=new_status.value, reason=reason_code)
            job = txn.job_w(inst.job_uuid)
            if job is not None:
                txn.recompute_job_state(job)
            return True

        return self.transact(_update)

    def clear_launch_intents(self, task_ids: List[str]) -> int:
        """Confirm backend dispatch: drop the launch intents for
        ``task_ids`` (a no-op — no transaction at all — for ids whose
        intent was already cleared by a status update)."""
        with self._lock:
            live = [t for t in task_ids if t in self._intents]
        if not live:
            return 0

        def _clear(txn: _Txn) -> int:
            for t in live:
                intent = self._intents.get(t)
                txn.delete("intents", t)
                if intent is not None:
                    # intent -> ack on the job's audit timeline (the
                    # backend confirmed the dispatch; docs/OBSERVABILITY)
                    txn.event("launch-ack", task_id=t,
                              job=intent.get("job_uuid", ""))
            return len(live)

        return self.transact(_clear)

    def launch_intents(self) -> List[Dict[str, Any]]:
        """Open launch intents (dispatch not yet confirmed), oldest first."""
        with self._lock:
            out = [dict(v) for v in self._intents.values()]
        out.sort(key=lambda r: r.get("created_ms", 0))
        return out

    def update_instance_progress(self, task_id: str, progress: int,
                                 message: str = "", sequence: int = 0) -> bool:
        """Progress writeback, monotone by sequence: reordered updates are
        dropped rather than regressing progress (reference: progress
        aggregator keeps latest-by-sequence, progress.clj:34-99)."""

        def _update(txn: _Txn) -> bool:
            inst = txn.instance_w(task_id)
            if inst is None:
                return False
            if sequence < inst.progress_sequence:
                return False
            inst.progress_sequence = sequence
            inst.progress = progress
            if message:
                inst.progress_message = message
            return True

        return self.transact(_update)

    def set_dynamic_config(self, key: str, value: Dict[str, Any]) -> None:
        """Store a dynamic config document (reference: the DB-backed
        no-restart config planes; rebalancer params at
        rebalancer.clj:535-557 are re-read from the DB every cycle)."""

        def _set(txn: _Txn) -> None:
            txn.put("configs", key, dict(value))
            txn.event("config-changed", key=key)

        self.transact(_set)

    def update_dynamic_config(self, key: str,
                              updates: Dict[str, Any]) -> Dict[str, Any]:
        """Atomic read-merge-write of a dynamic config document: concurrent
        updaters of different parameters cannot clobber each other."""

        def _update(txn: _Txn) -> Dict[str, Any]:
            current = dict(txn._get("configs", key, for_write=False) or {})
            current.update(updates)
            txn.put("configs", key, current)
            txn.event("config-changed", key=key)
            return current

        return self.transact(_update)

    def dynamic_config(self, key: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            v = self._configs.get(key)
            return dict(v) if v is not None else None

    def update_instance_ports(self, task_id: str, ports) -> bool:
        """Assigned host-port writeback (reference: instance ports land in
        Datomic from the task launch, schema.clj instance :instance/ports)."""

        def _update(txn: _Txn) -> bool:
            inst = txn.instance_w(task_id)
            if inst is None:
                return False
            inst.ports = list(ports)
            return True

        return self.transact(_update)

    def update_instance_sandbox(self, task_id: str,
                                sandbox_directory: Optional[str] = None,
                                output_url: Optional[str] = None) -> bool:
        """Sandbox/file-server writeback (reference: the sandbox publisher
        batches task->sandbox-dir aggregates into Datomic,
        mesos/sandbox.clj:222-353)."""

        def _update(txn: _Txn) -> bool:
            inst = txn.instance_w(task_id)
            if inst is None:
                return False
            if sandbox_directory is not None:
                inst.sandbox_directory = sandbox_directory
            if output_url is not None:
                inst.output_url = output_url
            return True

        return self.transact(_update)

    def kill_job(self, job_uuid: str) -> bool:
        """User kill: mark killed + recompute state; the tx feed's
        job-state->completed event triggers instance kills in the scheduler
        (reference: monitor-tx-report-queue scheduler.clj:405-447)."""

        def _kill(txn: _Txn) -> bool:
            job = txn.job_w(job_uuid)
            if job is None:
                return False
            if job.state is JobState.COMPLETED:
                return True
            job.user_killed = True
            txn.recompute_job_state(job)
            return True

        return self.transact(_kill)

    def set_placement_investigation(self, job_uuid: str,
                                    under_investigation: Optional[bool] = None,
                                    failure: Optional[Dict] = None) -> bool:
        """Update the unscheduled-explainer investigation state (reference:
        :job/under-investigation + :job/last-fenzo-placement-failure,
        unscheduled.clj check-fenzo-placement + fenzo_utils.clj:75-99)."""

        def _set(txn: _Txn) -> bool:
            job = txn.job_w(job_uuid)
            if job is None:
                return False
            if under_investigation is not None:
                job.under_investigation = under_investigation
            if failure is not None:
                job.last_placement_failure = failure
            return True

        return self.transact(_set)

    def retry_job(self, job_uuid: str, retries: int) -> bool:
        """Set max-retries; resurrect a completed job back to waiting if it
        now has attempts left (reference: tools.clj retry-job!)."""

        def _retry(txn: _Txn) -> bool:
            job = txn.job_w(job_uuid)
            if job is None:
                return False
            job.max_retries = retries
            if job.state is JobState.COMPLETED and not job.user_killed:
                insts = txn.instances_of(job)
                has_success = any(i.status is InstanceStatus.SUCCESS for i in insts.values())
                if not has_success and job.attempts_used(insts) < retries:
                    job.state = JobState.WAITING
                    job.last_waiting_start_ms = self.clock()
                    txn.event("job-state", uuid=job_uuid, old="completed",
                              new="waiting", reason="retry")
            return True

        return self.transact(_retry)

    # --------------------------------------------------------------- queries
    def job(self, uuid: str) -> Optional[Job]:
        with self._lock:
            job = self._jobs.get(uuid)
            return fast_clone(job) if job is not None else None

    def jobs_bulk(self, uuids) -> List[Optional[Job]]:
        """Deep-copied reads of many jobs under ONE lock acquisition (the
        per-cycle considerable-prefix materialization does ~1000 reads;
        per-call locking costs more than the copies)."""
        with self._lock:
            return [fast_clone(j) if (j := self._jobs.get(u)) is not None
                    else None for u in uuids]

    # -- borrowed reads -----------------------------------------------------
    # Commits install whole replacement objects (transact's write loop), so
    # a borrowed reference is always a complete, never-again-mutated entity.
    # Callers must treat it as FROZEN: read fields, never mutate or retain
    # past their own critical section.  This is the no-deepcopy path for
    # trusted high-frequency internals (the columnar index's tx-event
    # handler runs for every event of every transaction).
    def job_ref(self, uuid: str) -> Optional[Job]:
        return self._jobs.get(uuid)

    def instance_ref(self, task_id: str) -> Optional[Instance]:
        return self._instances.get(task_id)

    def instance(self, task_id: str) -> Optional[Instance]:
        with self._lock:
            inst = self._instances.get(task_id)
            return fast_clone(inst) if inst is not None else None

    def group(self, uuid: str) -> Optional[Group]:
        with self._lock:
            g = self._groups.get(uuid)
            return fast_clone(g) if g is not None else None

    def group_is_gang(self, uuid: Optional[str]) -> bool:
        """Gang-membership test without the ``group()`` clone — the
        completion hooks consult this for every grouped terminal job,
        gang or not, so it must not pay a deep copy of the member list."""
        if not uuid:
            return False
        with self._lock:
            g = self._groups.get(uuid)
            return bool(g is not None and getattr(g, "gang", False))

    def gang_size(self, uuid: Optional[str]) -> int:
        """Clone-free gang size: 0 for missing or non-gang groups.  The
        per-cycle admission path consults this once per distinct group,
        so ordinary placement groups must not pay a member-list copy."""
        if not uuid:
            return 0
        with self._lock:
            g = self._groups.get(uuid)
            if g is None or not getattr(g, "gang", False):
                return 0
            return int(getattr(g, "gang_size", 0) or 0)

    def gang_live_members(self, uuid: Optional[str]) -> int:
        """Clone-free count of a gang's members with a LIVE instance
        (unknown/running) — the elastic subsystem's "current size" of a
        running gang (docs/GANG.md elasticity).  0 for missing or
        non-gang groups."""
        if not uuid:
            return 0
        with self._lock:
            g = self._groups.get(uuid)
            if g is None or not getattr(g, "gang", False):
                return 0
            live = 0
            for member_uuid in g.jobs:
                j = self._jobs.get(member_uuid)
                if j is None:
                    continue
                if any((i := self._instances.get(t)) is not None
                       and i.status in (InstanceStatus.UNKNOWN,
                                        InstanceStatus.RUNNING)
                       for t in j.instances):
                    live += 1
            return live

    def gang_admission_size(self, uuid: Optional[str]) -> int:
        """Cohort size queue admission must reserve for this group
        (docs/GANG.md): 0 for non-gang groups; ``gang_size`` for rigid
        gangs (unchanged all-or-nothing semantics); for ELASTIC gangs,
        ``gang_min`` while the gang is not yet satisfied, and 0 once it
        runs at >= gang_min live members — a satisfied elastic gang's
        remaining waiting members admit like group-less singles (the
        grow path), no cohort semantics."""
        if not uuid:
            return 0
        from .schema import gang_bounds, gang_is_elastic
        with self._lock:
            g = self._groups.get(uuid)
            if g is None or not getattr(g, "gang", False):
                return 0
            if not gang_is_elastic(g):
                return int(getattr(g, "gang_size", 0) or 0)
            lo, _hi = gang_bounds(g)
            live = 0
            for member_uuid in g.jobs:
                j = self._jobs.get(member_uuid)
                if j is None:
                    continue
                if any((i := self._instances.get(t)) is not None
                       and i.status in (InstanceStatus.UNKNOWN,
                                        InstanceStatus.RUNNING)
                       for t in j.instances):
                    live += 1
                    if live >= lo:
                        return 0  # satisfied: members grow as singles
            return lo

    def gang_growth_headroom(self, uuid: Optional[str]) -> float:
        """How many MORE members this gang may legally admit
        (docs/GANG.md elasticity): ``gang_max - live`` for elastic
        gangs, floored at 0; infinity for rigid/non-gang groups (their
        admission is bounded by the cohort contract, not a cap).  The
        grow path and surplus-single admission consume this so a gang
        never runs past its declared maximum."""
        if not uuid:
            return float("inf")
        from .schema import gang_bounds, gang_is_elastic
        with self._lock:
            g = self._groups.get(uuid)
            if g is None or not gang_is_elastic(g):
                return float("inf")
            _lo, hi = gang_bounds(g)
            live = 0
            for member_uuid in g.jobs:
                j = self._jobs.get(member_uuid)
                if j is None:
                    continue
                if any((i := self._instances.get(t)) is not None
                       and i.status in (InstanceStatus.UNKNOWN,
                                        InstanceStatus.RUNNING)
                       for t in j.instances):
                    live += 1
            return float(max(hi - live, 0))

    def elastic_gang_groups(self) -> List[Group]:
        """Clone of every ELASTIC gang group with at least one live or
        waiting member job — the resize pass's scan set (docs/GANG.md
        elasticity).  Cheap for non-elastic workloads: the elastic test
        is clone-free and ordinary groups are skipped outright."""
        from .schema import gang_is_elastic
        out: List[Group] = []
        with self._lock:
            for g in self._groups.values():
                if not gang_is_elastic(g):
                    continue
                if any((j := self._jobs.get(u)) is not None
                       and j.state is not JobState.COMPLETED
                       for u in g.jobs):
                    out.append(fast_clone(g))
        return out

    def gang_groups_of(self, jobs) -> Dict[str, Group]:
        """The gang Groups these jobs' ``group`` fields reference, one
        lookup per distinct group — the shared gang-membership test for
        every consumer (scheduler resume/autoscale/direct matching, the
        matcher's launch cohorts, the rebalancer's whole-gang closures),
        so the semantics can't drift between call sites."""
        out: Dict[str, Group] = {}
        seen: set = set()
        for job in jobs:
            guuid = getattr(job, "group", None)
            if not guuid or guuid in seen:
                continue
            seen.add(guuid)
            with self._lock:
                g = self._groups.get(guuid)
                # gang test under the lock so ordinary placement groups
                # never pay the member-list clone
                if g is not None and getattr(g, "gang", False):
                    out[guuid] = fast_clone(g)
        return out

    def jobs_where(self, pred: Callable[[Job], bool],
                   clone: bool = True) -> List[Job]:
        """``clone=False`` returns the LIVE entities (collected under
        the lock, list itself fresh): read-only by contract, for
        aggregate sweeps over tens of thousands of jobs where per-job
        fast_clone dominates the walk (the monitor's gauge sweep was
        ~450 ms of pure cloning at 20k pending jobs — long enough to
        convoy the serving plane it is supposed to protect).  Callers
        must not mutate, and must tolerate fields changing underneath
        them between reads (gauges do; decision paths must clone)."""
        with self._lock:
            if clone:
                return [fast_clone(j) for j in self._jobs.values()
                        if j.committed and pred(j)]
            return [j for j in self._jobs.values()
                    if j.committed and pred(j)]

    def pending_jobs(self, pool: Optional[str] = None,
                     clone: bool = True) -> List[Job]:
        """Committed waiting jobs (reference: queries.clj get-pending-job-ents)."""
        return self.jobs_where(
            lambda j: j.state is JobState.WAITING and (pool is None or j.pool == pool),
            clone=clone)

    def running_jobs(self, pool: Optional[str] = None) -> List[Job]:
        return self.jobs_where(
            lambda j: j.state is JobState.RUNNING and (pool is None or j.pool == pool))

    def running_instances(self, pool: Optional[str] = None,
                          clone: bool = True) -> List[Tuple[Job, Instance]]:
        """(job, instance) for live instances (reference: tools.clj
        get-running-task-ents — includes unknown + running).
        ``clone=False``: live read-only entities, same contract as
        :meth:`jobs_where`."""
        with self._lock:
            out = []
            for inst in self._instances.values():
                if inst.status not in (InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
                    continue
                job = self._jobs.get(inst.job_uuid)
                if job is None or (pool is not None and job.pool != pool):
                    continue
                out.append((fast_clone(job), fast_clone(inst)) if clone
                           else (job, inst))
            return out

    def user_summary(self) -> Dict[str, Dict[str, float]]:
        """Bounded per-user summary of this store's committed jobs —
        the ONLY payload partitions exchange for cross-partition
        invariants (per-user quotas, the monitor's global DRU view;
        state/partition.py UserSummaryExchange): pending/running counts
        and running resource sums, NEVER job state.  Computed under the
        lock without entity clones (one pass over the jobs table, a few
        floats per distinct user)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for j in self._jobs.values():
                if not j.committed:
                    continue
                if j.state is JobState.WAITING:
                    key = "pending"
                elif j.state is JobState.RUNNING:
                    key = "running"
                else:
                    continue
                u = out.setdefault(j.user, {
                    "pending": 0.0, "running": 0.0,
                    "cpus": 0.0, "mem": 0.0, "gpus": 0.0})
                u[key] += 1
                if key == "running":
                    u["cpus"] += j.resources.cpus
                    u["mem"] += j.resources.mem
                    u["gpus"] += j.resources.gpus
        return out

    def user_usage(self, pool: Optional[str] = None) -> Dict[str, Dict[str, float]]:
        """Per-user aggregate usage of running jobs (reference: scheduler.clj
        user->usage)."""
        usage: Dict[str, Dict[str, float]] = {}
        for job, _inst in self.running_instances(pool):
            u = usage.setdefault(job.user, {"count": 0.0, "cpus": 0.0, "mem": 0.0, "gpus": 0.0})
            u["count"] += 1
            u["cpus"] += job.resources.cpus
            u["mem"] += job.resources.mem
            u["gpus"] += job.resources.gpus
        return usage

    # ----------------------------------------------------- pools/shares/quota
    def put_pool(self, pool: Pool) -> None:
        self.transact(lambda txn: txn.put("pools", pool.name, pool))

    def pools(self) -> List[Pool]:
        with self._lock:
            return [fast_clone(p) for p in self._pools.values()]

    def pool(self, name: str) -> Optional[Pool]:
        with self._lock:
            p = self._pools.get(name)
            return fast_clone(p) if p is not None else None

    def set_share(self, user: str, pool: str, resources: Dict[str, float],
                  reason: str = "") -> None:
        entry = ShareEntry(user, pool, dict(resources), reason)
        self.transact(lambda txn: txn.put("shares", f"{user}/{pool}", entry))

    def get_share(self, user: str, pool: str) -> Dict[str, float]:
        """Share with 'default'-user then MAX_VALUE fallback per resource
        (reference: share.clj get-share :105)."""
        with self._lock:
            entry = self._shares.get(f"{user}/{pool}")
            default = self._shares.get(f"default/{pool}")
        out: Dict[str, float] = {}
        for dim in ("cpus", "mem", "gpus"):
            if entry and dim in entry.resources:
                out[dim] = entry.resources[dim]
            elif default and dim in default.resources:
                out[dim] = default.resources[dim]
            else:
                out[dim] = float("inf")  # stands in for Double/MAX_VALUE
        return out

    def retract_share(self, user: str, pool: str) -> None:
        self.transact(lambda txn: txn.delete("shares", f"{user}/{pool}"))

    def set_quota(self, user: str, pool: str, resources: Dict[str, float],
                  count: float = float("inf"), reason: str = "") -> None:
        entry = QuotaEntry(user, pool, dict(resources), count, reason)
        self.transact(lambda txn: txn.put("quotas", f"{user}/{pool}", entry))

    def get_quota(self, user: str, pool: str) -> Dict[str, float]:
        """Quota map incl. :count, default-user fallback, infinite default
        (reference: quota.clj get-quota :82)."""
        with self._lock:
            entry = self._quotas.get(f"{user}/{pool}")
            default = self._quotas.get(f"default/{pool}")
        out: Dict[str, float] = {}
        for dim in ("cpus", "mem", "gpus"):
            if entry and dim in entry.resources:
                out[dim] = entry.resources[dim]
            elif default and dim in default.resources:
                out[dim] = default.resources[dim]
            else:
                out[dim] = float("inf")
        if entry is not None:
            out["count"] = entry.count
        elif default is not None:
            out["count"] = default.count
        else:
            out["count"] = float("inf")
        return out

    def retract_quota(self, user: str, pool: str) -> None:
        self.transact(lambda txn: txn.delete("quotas", f"{user}/{pool}"))

    def shares(self) -> List[ShareEntry]:
        with self._lock:
            return list(self._shares.values())

    def quotas(self) -> List[QuotaEntry]:
        with self._lock:
            return list(self._quotas.values())

    # ------------------------------------------------------ snapshot/restore
    def snapshot(self) -> str:
        """Serialize full state to JSON (leader handoff / checkpoint)."""
        with self._lock:
            state = {
                "tx_id": self._tx_id,
                "jobs": {k: to_json(v) for k, v in self._jobs.items()},
                "instances": {k: to_json(v) for k, v in self._instances.items()},
                "groups": {k: to_json(v) for k, v in self._groups.items()},
                "pools": {k: to_json(v) for k, v in self._pools.items()},
                "shares": {k: to_json(v) for k, v in self._shares.items()},
                "quotas": {k: to_json(v) for k, v in self._quotas.items()},
                "configs": {k: to_json(v) for k, v in self._configs.items()},
                "intents": {k: dict(v) for k, v in self._intents.items()},
                "latches": dict(self._latches),
            }
        return json.dumps(state)

    @classmethod
    def restore(cls, blob: str, partition: Optional[int] = None) -> "Store":
        state = json.loads(blob)
        store = cls(partition=partition)
        store._tx_id = state["tx_id"]
        for table in ("jobs", "instances", "groups", "pools", "shares",
                      "quotas", "configs", "intents"):
            target = getattr(store, "_" + table)
            for k, v in state.get(table, {}).items():
                target[k] = _entity_from_json(table, v)
        store._latches = {k: list(v) for k, v in state.get("latches", {}).items()}
        return store

    # ------------------------------------------------------- epoch fencing
    def _check_fence(self) -> None:
        """Refuse the append when another leader has claimed a higher epoch
        (caller holds the store lock).  One os.stat per append; the epoch
        file is only re-read when its (mtime_ns, ino) changed."""
        try:
            st = os.stat(self._epoch_path)
            sig = (st.st_mtime_ns, st.st_ino)
        except FileNotFoundError:
            return  # nobody has fenced (or fence file removed): allow
        if sig == self._epoch_stat:
            return
        self._epoch_stat = sig
        current = self._read_epoch_file()
        if current is not None and current > self._journal_epoch:
            # deposed: poison so no later append can slip through either
            f, self._journal_file = self._journal_file, None
            self._journal_poisoned = True
            try:
                if f is not None:
                    f.close()
            except Exception:
                pass
            raise StaleEpochError(
                f"journal fenced at epoch {current}; this leader holds "
                f"epoch {self._journal_epoch}")

    def _read_epoch_file(self) -> Optional[int]:
        try:
            with open(self._epoch_path, encoding="utf-8") as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return None

    def _claim_epoch(self, directory: str, epoch) -> int:
        """Claim leadership of the journal dir at ``epoch`` ("auto" = one
        above the current fence).  Raises StaleEpochError when a higher
        epoch is already fenced."""
        self._epoch_path = os.path.join(directory, "epoch")
        current = self._read_epoch_file() or 0
        if epoch == "auto":
            epoch = current + 1
        epoch = int(epoch)
        if current > epoch:
            raise StaleEpochError(
                f"journal dir fenced at epoch {current} > claimed {epoch}")
        if epoch > current:
            from ..utils.fsatomic import write_atomic_int
            write_atomic_int(self._epoch_path, epoch)
        st = os.stat(self._epoch_path)
        self._epoch_stat = (st.st_mtime_ns, st.st_ino)
        self._journal_epoch = epoch
        return epoch

    def attach_fence_authority(self, path: str) -> None:
        """Point the append-time fence check at a SHARED epoch authority
        (the election dir's minted counter) instead of the node-local
        ``<dir>/epoch`` claim file.  In the socket-replication topology
        the journal directory is node-local, so nothing ever bumps the
        local epoch file — without this, a deposed-but-alive leader's
        appends and checkpoints would pass the fence forever and only
        replay-time epoch skipping on the promoted mirror would protect
        the cluster.  With it, the first append after a successor mints
        a higher epoch raises :class:`StaleEpochError` and poisons the
        journal (same contract as the shared-dir topology)."""
        with self._lock:
            self._epoch_path = path
            self._epoch_stat = None  # force a re-read on the next append

    # ------------------------------------------------------- durable journal
    def attach_journal(self, path: str, fsync: bool = False) -> None:
        """Start appending every committed transaction to ``path`` as one
        JSON line. With ``fsync``, each record is fsynced (durable against
        power loss, not just process crash)."""
        with self._lock:
            self._journal_path = path
            self._journal_fsync = fsync
            self._journal_file = open(path, "a", encoding="utf-8")
            try:
                self._commit_offset = max(self._commit_offset,
                                          os.path.getsize(path))
            except OSError:
                pass

    def attach_replication(self, server, sync: bool = True,
                           timeout_s: float = 5.0,
                           min_followers: int = 0) -> None:
        """Stream this store's journal to followers via a running
        :class:`~cook_tpu.state.replication.ReplicationServer` over the
        native framed-TCP carrier.  With ``sync`` (the default), a
        transaction only reports determinate success after every synced
        follower fsynced its record; an unconfirmed ack raises
        :class:`ReplicationIndeterminate` (the record stays journaled
        and applied locally — the ambiguous-outcome contract).
        ``min_followers`` > 0 refuses commits BEFORE writing anything
        when fewer synced followers are connected
        (:class:`ReplicationTimeout`, a clean abort — CP mode; the
        default 0 keeps a lone leader available, like the reference's
        single transactor)."""
        with self._lock:
            self._repl_server = server
            self._repl_sync = sync
            self._repl_timeout_s = timeout_s
            self._repl_min_followers = min_followers

    @classmethod
    def open(cls, directory: str, fsync: bool = False,
             epoch=None, shared: bool = True,
             partition: Optional[int] = None) -> "Store":
        """Open a durable store rooted at ``directory`` (snapshot.json +
        journal.jsonl): load the snapshot if present, replay the journal,
        resume appending. The equivalent of a new leader re-reading Datomic
        (reference: mesos.clj:296-313 — replay nothing, just re-read).

        With ``epoch`` (an election epoch int, or "auto" for one above the
        current fence) the directory is treated as SHARED across leader
        hosts: the claim is written to ``<dir>/epoch`` before replay,
        stale-epoch records interleaved by a deposed leader are skipped
        during replay, and every future append re-checks the fence — a
        paused-then-woken old leader gets StaleEpochError instead of
        corrupting the successor's journal.

        ``shared=False`` marks a fenced journal whose DIRECTORY is
        node-local (the socket-replication topology, where epochs come
        from the shared election authority instead): failed appends may
        then safely truncate, since no other process appends to it.

        A journal with MID-FILE corruption (a failed CRC on a complete
        v2 frame, or garbage with valid records after it) raises
        :class:`~cook_tpu.state.integrity.JournalCorruptionError`
        instead of silently truncating the committed records beyond the
        damage; :func:`cook_tpu.state.repair.open_with_repair` wraps
        this with the pull-from-synced-peer path.  A torn TAIL is still
        excised exactly as before."""
        os.makedirs(directory, exist_ok=True)
        journal_path = os.path.join(directory, "journal.jsonl")
        removed = hygiene_sweep(directory)
        store, prev_records = cls._restore_base(directory, partition)
        store._hygiene_removed = removed
        store._journal_dir = directory
        if epoch is None:
            scan = _scan_journal(journal_path)
            if scan.corrupt:
                raise _corruption_error(journal_path, scan, "leader")
            store._replay_records(prev_records + scan.records)
            if scan.good < scan.size:
                with open(journal_path, "r+b") as f:
                    f.truncate(scan.good)
                store._bump_journal_gen()
            store.attach_journal(journal_path, fsync=fsync)
            return store
        # SHARED-dir takeover. Order matters:
        #   claim epoch -> repair torn tail -> append an epoch BARRIER ->
        #   replay to EOF.
        # The barrier (a no-op record at our epoch) makes any lower-epoch
        # record that lands after it positionally follow a higher-ep
        # record, so every future replay skips it; records that raced in
        # BEFORE the barrier are replayed by us and by every successor
        # alike, so all leaders agree on the committed prefix.
        store._journal_shared = shared
        store._claim_epoch(directory, epoch)
        scan = _scan_journal(journal_path)
        if scan.corrupt:
            raise _corruption_error(journal_path, scan, "leader")
        if scan.good < scan.size:
            # a torn fragment would merge with the barrier line and stop
            # every future replay there — excise it first
            with open(journal_path, "r+b") as f:
                f.truncate(scan.good)
            store._bump_journal_gen()
        store.attach_journal(journal_path, fsync=fsync)
        store._journal_file.write(seal_record(
            {"ep": store._journal_epoch, "barrier": True}))
        store._journal_file.flush()
        if fsync:
            os.fsync(store._journal_file.fileno())
        store._commit_offset = store._journal_file.tell()
        records, _good, _size = _scan_journal(journal_path)
        store._replay_records(prev_records + records)
        return store

    @classmethod
    def _restore_base(cls, directory: str, partition: Optional[int]
                      ) -> Tuple["Store", List[Dict[str, Any]]]:
        """Load the checkpoint snapshot, verified against its manifest
        (state/integrity.py).  Returns ``(store, prev_records)``:
        normally the restored snapshot and no extra records; on a
        manifest mismatch, the PREVIOUS checkpoint generation
        (``snapshot.prev.json`` + the journal rotated at the last
        checkpoint, ``journal.prev.jsonl``) — that chain replays to at
        least the damaged snapshot's state, re-applying any already-
        absorbed records idempotently.  A directory with no manifest
        (legacy, or a replication mirror — manifests are node-local)
        loads unverified exactly as before.  Raises
        :class:`JournalCorruptionError` when no generation verifies."""
        snap_path = os.path.join(directory, "snapshot.json")
        verdict = verify_snapshot(snap_path)
        if verdict is not False:
            if os.path.exists(snap_path):
                with open(snap_path, encoding="utf-8") as f:
                    return cls.restore(f.read(), partition=partition), []
            return cls(partition=partition), []
        _metrics.counter_inc("cook_journal_corruption",
                             labels={"source": "snapshot"})
        prev = os.path.join(directory, "snapshot.prev.json")
        if os.path.exists(prev) and verify_snapshot(prev) is not False:
            with open(prev, encoding="utf-8") as f:
                store = cls.restore(f.read(), partition=partition)
            pscan = scan_journal(
                os.path.join(directory, "journal.prev.jsonl"))
            if pscan.corrupt:
                raise _corruption_error(
                    os.path.join(directory, "journal.prev.jsonl"),
                    pscan, "leader")
            return store, pscan.records
        raise JournalCorruptionError(
            snap_path, 0, "checkpoint manifest mismatch and no usable "
            "previous checkpoint — repair from a synced peer "
            "(docs/DEPLOY.md corrupted-journal runbook)")

    def _replay_records(self, records: List[Dict[str, Any]],
                        max_ep: int = 0) -> int:
        """Apply scanned journal records with epoch-fence skipping: a
        record with a lower epoch than one already seen was appended by a
        deposed leader after its successor fenced — never committed from
        the cluster's point of view.  ``max_ep`` seeds (and the return
        value carries) the epoch high-water mark so an INCREMENTAL
        replayer — the follower read view's apply loop
        (state/read_replica.py) — shares this exact skip rule across
        calls instead of re-implementing it."""
        for rec in records:
            ep = rec.get("ep")
            if ep is not None and ep < max_ep:
                continue
            if ep is not None:
                max_ep = ep
            if not rec.get("barrier"):
                self._apply_journal_record(rec)
        return max_ep

    @classmethod
    def replay_only(cls, directory: str,
                    partition: Optional[int] = None) -> "Store":
        """Load snapshot + journal WITHOUT attaching the journal: the
        follower/read-replica view of a SHARED data dir.  A follower must
        never append (its writes would interleave with the leader's), so
        transactions on this store stay in memory only — leader-only
        writes are 307-redirected at the REST layer anyway.

        Raises :class:`JournalCorruptionError` on mid-file damage — a
        follower must refuse to serve (or promote) poisoned state, not
        silently drop the records beyond the corruption."""
        journal_path = os.path.join(directory, "journal.jsonl")
        store, prev_records = cls._restore_base(directory, partition)
        scan = _scan_journal(journal_path)
        if scan.corrupt:
            raise _corruption_error(journal_path, scan, "mirror")
        store._replay_records(prev_records + scan.records)
        return store

    def _apply_journal_record(self, rec: Dict[str, Any]) -> None:
        for tk, v in rec.get("w", {}).items():
            table, key = tk.split("/", 1)
            getattr(self, "_" + table)[key] = _entity_from_json(table, v)
        for tk in rec.get("d", []):
            table, key = tk.split("/", 1)
            getattr(self, "_" + table).pop(key, None)
        for latch, uuids in rec.get("lr", []):
            self._latches.setdefault(latch, []).extend(uuids)
        for latch in rec.get("lp", []):
            self._latches.pop(latch, None)
        if rec.get("a"):
            # per-job audit docs (utils/audit.py): a promoted leader's
            # replay rebuilds pre-failover timelines from these
            self.audit.load(rec["a"])
        self._tx_id = rec.get("tx", self._tx_id)

    def checkpoint(self) -> None:
        """Compact the journal: atomically write a fresh snapshot, then
        truncate the journal. Safe at any point — the snapshot covers every
        journaled transaction."""
        if self._journal_dir is None or self._journal_file is None:
            raise ValueError(
                "checkpoint() requires an open store from Store.open")
        with self._lock:
            if self._journal_epoch is not None:
                # a deposed leader's graceful shutdown must not overwrite
                # the shared snapshot with stale state / truncate the
                # successor's journal
                self._check_fence()
            snap_path = os.path.join(self._journal_dir, "snapshot.json")
            # keep the PREVIOUS checkpoint generation reachable
            # (snapshot.prev.json + the journal rotated below): a later
            # manifest mismatch on the new snapshot falls back to that
            # chain (_restore_base), which replays to the same state.
            # Hard links BEFORE the replace keep every crash window
            # recoverable — the live snapshot.json is never unlinked.
            self._rotate_prev(snap_path)
            # writer-unique temp + directory fsync (utils/fsatomic.py):
            # a shared ".tmp" name let a deposed leader's last-gasp
            # checkpoint race the successor's on the same temp file
            from ..utils.fsatomic import write_atomic_text
            snap_text = self.snapshot()
            write_atomic_text(snap_path, snap_text)
            # manifest AFTER snapshot: a crash between the two leaves a
            # manifest describing the old content → verification fails →
            # fallback to the prev chain, which is correct (idempotent
            # re-replay), never silently wrong
            write_manifest(snap_path, snap_text)
            self._journal_file.close()
            try:
                # rotate instead of truncating: journal.prev.jsonl is the
                # fallback chain's second half (and the quarantine target
                # when a scrub-detected corruption forced this checkpoint)
                os.replace(self._journal_path,
                           os.path.join(self._journal_dir,
                                        "journal.prev.jsonl"))
            except OSError:
                pass  # fresh dir, or exotic fs: "w" below truncates
            self._journal_file = open(self._journal_path, "w",
                                      encoding="utf-8")
            # the commit position re-bases with the compacted journal
            # (followers full-resync on the new mirror token; a stale
            # read-your-writes token just redirects to the leader)
            self._commit_offset = 0
            self._scrub_offset = 0
            if self.audit.enabled and self.audit.journal:
                # the snapshot carries no audit lane — re-seed the
                # compacted journal with the (bounded) current trail so
                # timeline continuity survives compaction too.  Pending
                # durable events are marked flushed FIRST: the re-seed
                # already carries them, and leaving them pending would
                # journal them a second time at the next flush_audit
                # (duplicated on every later replay)
                self.audit.discard_pending()
                docs = self.audit.export_wire()
                if docs:
                    # same torn-write excision/poison + fsync discipline
                    # as every other audit append: a bare write here
                    # could leave a torn fragment at the fresh journal's
                    # head that swallows the next committed txn record
                    self._write_audit_record_locked(docs)

    def _rotate_prev(self, snap_path: str) -> None:
        """Preserve the current snapshot (+ its manifest) under the
        ``.prev`` names via hard links, so the atomic replace that
        follows never orphans the only verified generation.  Best
        effort: a filesystem without links just shortens the fallback
        chain, it never breaks the primary path."""
        from .integrity import manifest_path
        prev = os.path.join(self._journal_dir, "snapshot.prev.json")
        for src, dst in ((snap_path, prev),
                         (manifest_path(snap_path), manifest_path(prev))):
            if not os.path.exists(src):
                continue
            try:
                tmp = dst + ".lnk"
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                os.link(src, tmp)
                os.replace(tmp, dst)
            except OSError:
                pass

    # ------------------------------------------------------ integrity scrub
    def scrub(self, max_bytes: int = 1 << 20,
              repair: bool = True) -> Dict[str, Any]:
        """One background-scrub step (sched/monitor.py's storage sweep
        drives this): verify the next ``max_bytes`` of journal frames
        beyond the last verified offset (CRC + length framing,
        state/integrity.py) WITHOUT the store lock — the window scan
        reads the path independently and only advances past complete
        valid lines, so a live appender's in-flight tail just waits for
        the next pass.

        On corruption the leader SELF-HEALS when ``repair`` is set: the
        in-memory state is authoritative (every committed record was
        applied before its bytes could rot on disk), so a checkpoint()
        rewrites a fresh verified snapshot and rotates the damaged
        journal aside as ``journal.prev.jsonl`` (kept for forensics;
        docs/DEPLOY.md runbook).  Mirrors repair from peers instead —
        their memory is not authoritative (state/repair.py)."""
        path = self._journal_path
        if not path or self._journal_file is None:
            return {"enabled": False}
        try:
            if os.path.getsize(path) < self._scrub_offset:
                self._scrub_offset = 0  # checkpoint rotated the journal
        except OSError:
            return {"enabled": False}
        max_bytes = int(max_bytes)
        res = verify_window(path, self._scrub_offset, max_bytes)
        while (not res.corrupt and res.good == self._scrub_offset
               and res.size - self._scrub_offset > max_bytes):
            # one frame is larger than the window: a fixed-size pass
            # would sit on it forever.  Grow until the frame fits (an
            # incomplete TAIL frame is excluded by the size check — the
            # live appender finishes that one).
            max_bytes *= 2
            res = verify_window(path, self._scrub_offset, max_bytes)
        self._scrub_last_ts = time.time()
        if not res.corrupt:
            self._scrub_offset = res.good
            return {"enabled": True, "corrupt": False,
                    "verified_offset": self._scrub_offset,
                    "journal_bytes": res.size}
        self._scrub_corruptions += 1
        _metrics.counter_inc("cook_journal_corruption",
                             labels={"source": "scrub"})
        doc: Dict[str, Any] = {
            "enabled": True, "corrupt": True,
            "corrupt_offset": res.corrupt_offset, "reason": res.reason,
            "verified_offset": self._scrub_offset,
            "journal_bytes": res.size, "repaired": False}
        if repair and not self._journal_poisoned:
            try:
                self.checkpoint()
                self._scrub_repairs += 1
                _metrics.counter_inc("cook_storage_repair",
                                     labels={"kind": "checkpoint"})
                doc["repaired"] = True
            except Exception as e:
                # fenced/deposed or the rewrite itself failed: leave the
                # damage reported, never half-heal
                doc["repair_error"] = str(e)
        return doc

    def storage_stats(self) -> Dict[str, Any]:
        """The ``GET /debug/storage`` document for this store (one per
        partition in the partitioned plane): scrub frontier, corruption
        and repair counters, checkpoint manifest verdict."""
        doc: Dict[str, Any] = {
            "journal_bytes": self._commit_offset,
            "journal_poisoned": self._journal_poisoned,
            "scrub_verified_offset": self._scrub_offset,
            "scrub_corruptions": self._scrub_corruptions,
            "scrub_repairs": self._scrub_repairs,
            "scrub_age_s": (round(time.time() - self._scrub_last_ts, 1)
                            if self._scrub_last_ts else None),
            "hygiene_removed": self._hygiene_removed,
            "enospc_aborts": self._enospc_aborts,
        }
        if self.partition is not None:
            doc["partition"] = f"p{self.partition}"
        if self._journal_dir:
            snap = os.path.join(self._journal_dir, "snapshot.json")
            verdict = verify_snapshot(snap)
            if verdict is None:
                doc["manifest"] = ("missing" if os.path.exists(snap)
                                   else "no-checkpoint")
            else:
                doc["manifest"] = "ok" if verdict else "mismatch"
        return doc

    def close(self) -> None:
        self.disable_group_commit()  # drain waiters before the fd goes
        with self._lock:
            if self._journal_file is not None:
                self._journal_file.close()
                self._journal_file = None


def _corruption_error(path: str, scan: ScanResult,
                      source: str) -> JournalCorruptionError:
    """Count + build the refuse-and-repair verdict for a corrupt scan
    (``source`` labels who found it: leader replay, mirror replay, or
    the background scrub)."""
    _metrics.counter_inc("cook_journal_corruption",
                         labels={"source": source})
    return JournalCorruptionError(
        path, scan.corrupt_offset or 0, scan.reason)


def _scan_journal(path: str) -> ScanResult:
    """Parse a journal file into records — the store-local name every
    consumer imports; the framing/CRC logic lives in
    :func:`cook_tpu.state.integrity.scan_journal` (v1 + v2 records, the
    torn-tail vs mid-file-corruption verdict).  The result still
    unpacks as the legacy ``(records, good, size)`` triple."""
    return scan_journal(path)


def _entity_from_json(table: str, v: Dict[str, Any]) -> Any:
    """Inverse of ``to_json`` per entity table (shared by snapshot restore
    and journal replay)."""
    if table == "jobs":
        return _job_from_json(v)
    v = dict(v)
    if table == "instances":
        v["status"] = InstanceStatus(v["status"])
        return Instance(**v)
    if table == "groups":
        v["placement_type"] = GroupPlacementType(v["placement_type"])
        return Group(**v)
    if table == "pools":
        v["dru_mode"] = DruMode(v["dru_mode"])
        v["scheduler"] = SchedulerKind(v["scheduler"])
        return Pool(**v)
    if table == "shares":
        return ShareEntry(**v)
    if table == "quotas":
        v["count"] = float(v["count"]) if v["count"] is not None else float("inf")
        return QuotaEntry(**v)
    if table in ("configs", "intents"):
        return v  # plain dicts: dynamic config documents / launch intents
    raise ValueError(f"unknown entity table {table}")


def _job_from_json(v: Dict[str, Any]) -> Job:
    v = dict(v)
    v["state"] = JobState(v["state"])
    v["resources"] = Resources(**v["resources"])
    v["constraints"] = [Constraint(**c) for c in v.get("constraints") or []]
    if v.get("application"):
        v["application"] = Application(**v["application"])
    if v.get("checkpoint"):
        c = dict(v["checkpoint"])
        c["mode"] = CheckpointMode(c["mode"])
        v["checkpoint"] = Checkpoint(**c)
    v["mea_culpa_failures"] = {int(k): int(n) for k, n in (v.get("mea_culpa_failures") or {}).items()}
    return Job(**v)
