"""Partitioned write plane: shard the store + journal by pool group.

PR 9 bought group-commit admission batching, but every write still
funneled through ONE leader store, one journal, one fsync stream — the
Gray/DeWitt round is amortized, not scaled.  This module shards the
write plane into P independent partitions (the Omega move, Schwarzkopf
et al., EuroSys'13: shared-state scheduling survives partitioned,
optimistically-coordinated writers):

- :class:`PartitionMap` — a deterministic, config-declared ``pool →
  partition`` routing map, validated at boot and persisted next to the
  partition directories so a re-partitioned reopen fails loudly instead
  of silently stranding jobs in the wrong journal.
- :class:`PartitionedStore` — a facade over P :class:`~.store.Store`
  instances, each with its OWN journal file, fsync stream, group-commit
  stage (PR 9's ``_GroupCommitStage`` runs per partition, so concurrent
  batches on different partitions force their logs in parallel),
  replication topology, and leader lease.  Single-pool writes route
  straight to the owning partition; cross-partition reads fan out and
  merge.  Fan-out is STRICTLY SEQUENTIAL — each partition's lock is
  released before the next is touched (the ``store[pN]`` sibling-lock
  rule in utils/locks.py is the sanitizer-enforced form of that
  contract).
- **Partition-qualified commit tokens** — PR 9's epoch-qualified
  read-your-writes tokens become ``(partition, epoch, offset)`` triples
  on the wire (``p0:3:128``); :meth:`PartitionedStore.commit_token`
  returns the comma-joined VECTOR of every partition's position, the
  client carries the per-partition maximum, and the follower wait-gate
  satisfies each entry against the mirror of that entry's partition
  (offsets are NEVER comparable across partitions — the bugfix-rider
  rule this module makes structural).
- :class:`UserSummaryExchange` — cross-partition invariants (per-user
  quotas, the monitor's global DRU view) exchange bounded PER-USER
  summaries between partitions — counts and resource sums, never job
  state — with an explicit, asserted staleness window.
- :class:`PartitionedReadView` — a standby's live read plane over P
  mirrored partition directories (state/read_replica.py per shard), with
  the per-partition token wait-gate.

``P=1`` is the compatibility mode: one partition, classic lock names are
the only difference callers can observe, and the daemon keeps using the
plain :class:`Store` unless partitioning is configured (docs/DEPLOY.md
"partitioned write plane").
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..utils.locks import named_lock
from .schema import Group, Job, Pool, QuotaEntry, ShareEntry
from .store import (
    AbortTransaction,
    Instance,
    ReplicationIndeterminate,
    Store,
)

#: pool name reserved for cross-partition control documents (the global
#: per-user quota plane): always routed to partition 0, visible to every
#: partition through the summary-exchange enforcement path
GLOBAL_POOL = "*"

#: routing-map sidecar persisted next to the partition directories
PARTITION_MAP_FILE = "partition_map.json"


class PartitionRoutingError(ValueError):
    """A write that cannot be routed: a gang/group spanning partitions,
    or a persisted routing map that disagrees with the configured one."""


class SummaryStalenessError(RuntimeError):
    """The cross-shard user-summary table could not be brought under
    its staleness bound (a peer shard's table is too old): global
    enforcement reads must fail loudly rather than consume a view
    whose window the quota refusal would then misquote (ISSUE 19)."""


class PartitionMap:
    """Deterministic ``pool → partition`` routing.

    ``pools`` declares explicit pool groups (pool name → partition
    index, validated at construction); every undeclared pool hashes
    stably (crc32 mod count) so any process — REST node, standby,
    client tooling — computes the same owner without coordination."""

    def __init__(self, count: int = 1,
                 pools: Optional[Dict[str, int]] = None):
        count = int(count)
        if count < 1:
            raise ValueError(f"partition count must be >= 1, got {count}")
        self.count = count
        self.pools: Dict[str, int] = {}
        for pool, idx in (pools or {}).items():
            if not isinstance(idx, int) or isinstance(idx, bool) \
                    or not 0 <= idx < count:
                raise ValueError(
                    f"partition for pool {pool!r} must be an int in "
                    f"[0, {count}), got {idx!r}")
            self.pools[str(pool)] = idx

    def partition_of(self, pool: str) -> int:
        if pool == GLOBAL_POOL:
            return 0  # cross-partition control documents live on p0
        idx = self.pools.get(pool)
        if idx is not None:
            return idx
        return zlib.crc32(pool.encode()) % self.count

    def to_doc(self) -> Dict[str, Any]:
        return {"count": self.count, "pools": dict(self.pools)}

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "PartitionMap":
        return cls(count=doc.get("count", 1), pools=doc.get("pools"))


# --------------------------------------------------------------- tokens
def parse_token_entry(entry: str) -> Tuple[Optional[int], Optional[int],
                                           int]:
    """One commit-token entry → ``(partition, epoch, offset)``.
    Accepted forms: ``p<P>:<epoch>:<offset>``, ``p<P>:<offset>``,
    ``<epoch>:<offset>``, ``<offset>`` (partition/epoch None when
    absent).  Raises ValueError on garbage."""
    part: Optional[int] = None
    if entry.startswith("p"):
        head, sep, rest = entry.partition(":")
        if not sep:
            raise ValueError(f"malformed token entry {entry!r}")
        part = int(head[1:])
        entry = rest
    if ":" in entry:
        ep, _, off = entry.partition(":")
        return part, int(ep), int(off)
    return part, None, int(entry)


def parse_token_vector(token: str) -> List[Tuple[Optional[int],
                                                 Optional[int], int]]:
    """A comma-joined commit-token vector → entry triples.  A legacy
    single token parses to a one-entry list with partition None."""
    return [parse_token_entry(e.strip())
            for e in token.split(",") if e.strip()]


class UserSummaryExchange:
    """Bounded per-user summaries exchanged between partitions.

    Cross-partition invariants must never ship job state between
    partitions (that would rebuild the single write funnel this module
    removes); what crosses is one small dict per user — pending/running
    counts and running resource sums (:meth:`Store.user_summary`) —
    refreshed lazily with an explicit staleness bound.  Consumers that
    enforce (the global per-user quota refusal) assert the window; the
    monitor's global DRU view reads the same merged table.

    ``peer_fetch`` (ISSUE 19 sharded controllers) feeds the tables of
    REMOTE shard processes into the same merge: a zero-arg callable
    returning ``[(users_table, age_s), ...]`` — one entry per peer
    shard, each table stamped with how old it already was when fetched
    (socket carrier locally, ICI/DCN collectives on a real mesh).  The
    merged table's staleness then includes the OLDEST peer age, so the
    bound consumers quote covers the whole fleet, not just the local
    sweep.  With ``assert_bound`` a sweep that cannot get the table
    under ``max_age_s`` raises :class:`SummaryStalenessError` instead
    of serving silently-stale enforcement state."""

    def __init__(self, partitions: List[Store], max_age_s: float = 1.0,
                 peer_fetch: Optional[Callable[
                     [], List[Tuple[Dict[str, Dict[str, float]], float]]]]
                 = None,
                 assert_bound: bool = False):
        self._partitions = partitions
        self._peer_fetch = peer_fetch
        self.assert_bound = bool(assert_bound)
        self.peer_tables = 0       # peers merged into the last sweep
        self.peer_age_s = 0.0      # oldest peer table age at last sweep
        self.max_age_s = max(float(max_age_s), 0.0)
        self._mu = named_lock("partition.summaries")
        # serializes whole sweeps (sweep → install under _mu): two
        # racing refreshes could otherwise install an OLDER sweep over
        # a newer one while stamping it fresh — the staleness the
        # quota refusal quotes must never lie
        self._refresh_mu = named_lock("partition.summaries.refresh")
        self._merged: Dict[str, Dict[str, float]] = {}
        self._refreshed_at: float = float("-inf")
        self.refreshes = 0

    def staleness_s(self) -> float:
        """Seconds since the merged table was last recomputed (inf
        before the first refresh) — the asserted window bound."""
        return time.monotonic() - self._refreshed_at

    def _sweep_locked(self) -> None:
        """Merge every partition's user summary, plus peer shard tables
        when a carrier is attached (caller holds _refresh_mu)."""
        summaries = [p.user_summary() for p in self._partitions]
        peer_age = 0.0
        peers: List[Dict[str, Dict[str, float]]] = []
        if self._peer_fetch is not None:
            for table, age_s in self._peer_fetch():
                peers.append(table)
                peer_age = max(peer_age, max(float(age_s), 0.0))
        merged: Dict[str, Dict[str, float]] = {}
        for summary in summaries + peers:
            for user, u in summary.items():
                m = merged.setdefault(user, {
                    "pending": 0.0, "running": 0.0,
                    "cpus": 0.0, "mem": 0.0, "gpus": 0.0})
                for k, v in u.items():
                    m[k] += v
        with self._mu:
            self._merged = merged
            # a peer table that was already age_s old when it crossed
            # the wire backdates the whole merge: staleness_s() is the
            # fleet-wide bound, never just the local sweep's
            self._refreshed_at = time.monotonic() - peer_age
            self.peer_tables = len(peers)
            self.peer_age_s = peer_age
            self.refreshes += 1

    def refresh(self) -> None:
        """Recompute the merged table: one sequential sweep, each
        partition's summary taken under ITS lock only (no sibling
        nesting — the summaries themselves are the exchange payload).
        Sweeps are serialized so a stalled sweep can never overwrite a
        newer table while stamping it fresh."""
        with self._refresh_mu:
            self._sweep_locked()

    def _ensure_fresh(self) -> None:
        """Refresh when past the window — double-checked under the
        sweep lock so a herd of enforcement reads does one sweep, not
        one each."""
        if self.staleness_s() > self.max_age_s:
            with self._refresh_mu:
                if self.staleness_s() > self.max_age_s:
                    self._sweep_locked()
            if self.assert_bound and self.staleness_s() > self.max_age_s:
                # even a fresh sweep could not get under the window
                # (a peer shard's table is too old — dead peer, wedged
                # carrier): enforcement must not pretend it has a
                # current global view
                raise SummaryStalenessError(
                    f"cross-shard user summary is {self.staleness_s():.3f}s "
                    f"stale (bound {self.max_age_s}s; oldest peer table "
                    f"{self.peer_age_s:.3f}s, {self.peer_tables} peers "
                    "merged)")

    def merged(self) -> Dict[str, Dict[str, float]]:
        """The cross-partition per-user table, refreshed when older
        than ``max_age_s`` (the bounded-staleness contract)."""
        self._ensure_fresh()
        with self._mu:
            return {u: dict(v) for u, v in self._merged.items()}

    def user_totals(self, user: str) -> Dict[str, float]:
        # one user's entry, one small copy — this sits on the REST
        # write hot path (check_user_quota per submission); copying the
        # whole merged table there would scale with total users
        self._ensure_fresh()
        with self._mu:
            u = self._merged.get(user)
            return dict(u) if u else {
                "pending": 0.0, "running": 0.0,
                "cpus": 0.0, "mem": 0.0, "gpus": 0.0}

    def stats(self) -> Dict[str, Any]:
        with self._mu:
            return {"users": len(self._merged),
                    "refreshes": self.refreshes,
                    "max_age_s": self.max_age_s,
                    "peer_tables": self.peer_tables,
                    "peer_age_s": round(self.peer_age_s, 4),
                    "staleness_s": round(min(self.staleness_s(), 1e12),
                                         4)}


class _PartitionedAudit:
    """The facade's audit surface: per-job lanes live on the partition
    that journaled them; pool-keyed planes route; aggregate stats and
    configuration fan out."""

    def __init__(self, ps: "PartitionedStore"):
        self._ps = ps

    @property
    def enabled(self) -> bool:
        return any(s.audit.enabled for s in self._ps.partitions)

    def configure(self, conf) -> None:
        for store in self._ps.partitions:
            store.audit.configure(conf)

    def record(self, uuid: str, kind: str, data=None, **kw) -> None:
        store = self._ps._route_job(uuid)
        if store is not None:
            store.audit.record(uuid, kind, data, **kw)

    def set_user_dru(self, pool: str, table: Dict[str, float]) -> None:
        self._ps._for_pool(pool).audit.set_user_dru(pool, table)

    def ranked(self, uuids, positions, pool: str, users=None) -> None:
        # a rank cycle is per pool, and a pool lives on ONE partition
        self._ps._for_pool(pool).audit.ranked(uuids, positions, pool,
                                              users=users)

    def skips(self, mapping: Dict[str, Any],
              pool: Optional[str] = None) -> None:
        if pool is not None:
            self._ps._for_pool(pool).audit.skips(mapping, pool=pool)
            return
        # poolless attribution (gang resets): split items per owning
        # partition by job membership
        for store in self._ps.partitions:
            sub: Dict[str, List[Any]] = {}
            for reason, items in mapping.items():
                keep = [it for it in items
                        if (it[0] if isinstance(it, tuple) else it)
                        in store._jobs]
                if keep:
                    sub[reason] = keep
            if sub:
                store.audit.skips(sub)

    def last_reasons(self, uuids) -> Dict[str, Optional[str]]:
        out: Dict[str, Optional[str]] = {u: None for u in uuids}
        by_part: Dict[int, List[str]] = {}
        for u in uuids:
            p = self._ps._partition_of_job(u)
            if p is not None:
                by_part.setdefault(p, []).append(u)
        for p, batch in by_part.items():
            out.update(self._ps.partitions[p].audit.last_reasons(batch))
        return out

    def publish_metrics(self) -> None:
        for store in self._ps.partitions:
            store.audit.publish_metrics()

    def timeline(self, uuid: str) -> List[Dict[str, Any]]:
        p = self._ps._partition_of_job(uuid)
        if p is not None:
            return self._ps.partitions[p].audit.timeline(uuid)
        for store in self._ps.partitions:
            tl = store.audit.timeline(uuid)
            if tl:
                return tl
        return []

    def user_dru(self, pool: str, user: str):
        return self._ps._for_pool(pool).audit.user_dru(pool, user)

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"jobs": 0, "pending_durable": 0,
                               "shed_advisory": False, "shed_count": 0,
                               "by_kind": {}}
        for store in self._ps.partitions:
            s = store.audit.stats()
            out["jobs"] += s.get("jobs", 0)
            out["pending_durable"] += s.get("pending_durable", 0)
            out["shed_advisory"] |= bool(s.get("shed_advisory"))
            out["shed_count"] += s.get("shed_count", 0)
            for k, v in (s.get("by_kind") or {}).items():
                out["by_kind"][k] = out["by_kind"].get(k, 0) + v
        return out

    def skip_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for store in self._ps.partitions:
            for k, v in store.audit.skip_counts().items():
                out[k] = out.get(k, 0) + v
        return out


class PartitionedStore:
    """Facade over P partition :class:`Store` shards (module doc).

    Write routing: pool-carrying writes go straight to the owning
    partition; entity-keyed writes (job uuid / task id) resolve the
    owner by membership probe (P is small; the probe is one dict hit
    per partition).  Reads fan out sequentially and merge.  A
    cross-partition batch is NOT one atomic transaction — each
    partition's sub-batch keeps the all-or-nothing guarantee, and
    client retries stay idempotent on job uuid (the same contract an
    indeterminate commit already forces on the wire)."""

    def __init__(self, partitions: List[Store], pmap: PartitionMap,
                 summary_max_age_s: float = 1.0):
        if len(partitions) != pmap.count:
            raise ValueError(
                f"{len(partitions)} stores for a {pmap.count}-partition "
                "map")
        for i, store in enumerate(partitions):
            if store.partition != i:
                raise ValueError(
                    f"store at slot {i} carries partition id "
                    f"{store.partition!r}; open each shard with "
                    "partition=i")
        self.partitions = partitions
        self.pmap = pmap
        self.summaries = UserSummaryExchange(
            partitions, max_age_s=summary_max_age_s)
        self._directory: Optional[str] = None

    # ------------------------------------------------------------- open
    @classmethod
    def open(cls, directory: str, pmap: PartitionMap,
             fsync: bool = False, epoch=None, shared: bool = True,
             summary_max_age_s: float = 1.0) -> "PartitionedStore":
        """Open (or create) a partitioned data dir: one ``p<i>/``
        shard directory per partition, each a full durable Store
        (snapshot + journal + optional epoch fence — the per-partition
        lease claim).  The routing map is persisted at the root and
        re-validated on every open: silently reopening P shards under a
        different map would strand every previously-routed pool."""
        os.makedirs(directory, exist_ok=True)
        map_path = os.path.join(directory, PARTITION_MAP_FILE)
        if os.path.exists(map_path):
            with open(map_path, encoding="utf-8") as f:
                persisted = json.load(f)
            if persisted.get("count") != pmap.count \
                    or (persisted.get("pools") or {}) != pmap.pools:
                raise PartitionRoutingError(
                    f"partition map mismatch: directory {directory!r} "
                    f"was laid out as {persisted}, configured "
                    f"{pmap.to_doc()} — re-partitioning requires an "
                    "explicit migration, not a reopen")
        else:
            from ..utils.fsatomic import write_atomic_text
            write_atomic_text(map_path, json.dumps(pmap.to_doc()))
        stores = [Store.open(os.path.join(directory, f"p{i}"),
                             fsync=fsync, epoch=epoch, shared=shared,
                             partition=i)
                  for i in range(pmap.count)]
        ps = cls(stores, pmap, summary_max_age_s=summary_max_age_s)
        ps._directory = directory
        return ps

    # ---------------------------------------------------------- routing
    def _for_pool(self, pool: str) -> Store:
        return self.partitions[self.pmap.partition_of(pool)]

    def _partition_of_job(self, uuid: str) -> Optional[int]:
        # membership probe: a bare dict hit per partition (GIL-atomic;
        # commits install whole replacement objects, so a hit is a
        # complete entity and a miss is authoritative at probe time)
        for i, store in enumerate(self.partitions):
            if uuid in store._jobs:
                return i
        return None

    def _partition_of_instance(self, task_id: str) -> Optional[int]:
        for i, store in enumerate(self.partitions):
            if task_id in store._instances:
                return i
        return None

    def _route_job(self, uuid: str) -> Optional[Store]:
        p = self._partition_of_job(uuid)
        return self.partitions[p] if p is not None else None

    def _route_instance(self, task_id: str) -> Optional[Store]:
        p = self._partition_of_instance(task_id)
        return self.partitions[p] if p is not None else None

    # ------------------------------------------------------------ clock
    @property
    def clock(self) -> Callable[[], int]:
        return self.partitions[0].clock

    @clock.setter
    def clock(self, fn: Callable[[], int]) -> None:
        for store in self.partitions:
            store.clock = fn

    @property
    def audit(self) -> _PartitionedAudit:
        return _PartitionedAudit(self)

    # ------------------------------------------------------- submission
    def create_jobs(self, jobs: Iterable[Job], groups: Iterable[Group] = (),
                    latch: Optional[str] = None) -> List[str]:
        """Route each job to its pool's partition; one transaction per
        TOUCHED partition (a single-pool batch — the hot path the REST
        fleet routes — stays exactly one transaction on one journal).
        Groups ride with their member jobs and must not span partitions
        (a gang split across journals could never launch atomically).
        Indeterminate outcomes demux PER PARTITION: sub-batches on
        healthy partitions commit determinately; the ambiguous ones
        re-raise after every partition was attempted.

        All-or-nothing across partitions: duplicates are pre-checked
        against EVERY partition before anything mutates, and an abort
        that still fires mid-fan-out (a concurrent same-uuid race)
        rolls the earlier partitions' latched sub-batches back
        (:meth:`Store.discard_latched` — they were never visible), so a
        409 keeps meaning "nothing was created", exactly as on the
        single store.  The latchless direct-call path keeps only
        per-partition atomicity (callers that want the full guarantee
        pass a latch, as the REST tier always does)."""
        jobs = list(jobs)
        for job in jobs:
            if self._partition_of_job(job.uuid) is not None:
                # the same check create_new_jobs makes per shard, made
                # BEFORE any shard mutates: a cross-partition batch
                # must not strand sub-batches behind a late duplicate
                raise AbortTransaction(f"duplicate job uuid {job.uuid}")
        by_part: Dict[int, List[Job]] = {}
        for job in jobs:
            by_part.setdefault(
                self.pmap.partition_of(job.pool), []).append(job)
        groups_by_part: Dict[int, List[Group]] = {}
        members = {j.uuid: j for j in jobs}
        for group in groups:
            owner: Optional[int] = None
            for uuid in group.jobs:
                j = members.get(uuid)
                if j is None:
                    continue
                p = self.pmap.partition_of(j.pool)
                if owner is None:
                    owner = p
                elif owner != p:
                    raise PartitionRoutingError(
                        f"group {group.uuid} spans partitions {owner} "
                        f"and {p}: a group's jobs must share a pool "
                        "group (declare the pools in the same "
                        "partition)")
            # a MERGE into an existing group must land on the partition
            # already holding it (membership probe, as _route_job)
            existing = next((i for i, s in enumerate(self.partitions)
                             if group.uuid in s._groups), None)
            if existing is not None:
                if owner is not None and owner != existing:
                    raise PartitionRoutingError(
                        f"group {group.uuid} lives on partition "
                        f"{existing} but its new jobs route to "
                        f"{owner}: a group's pools may not change "
                        "partition")
                owner = existing
            groups_by_part.setdefault(
                owner if owner is not None else 0, []).append(group)
        indeterminate: Optional[ReplicationIndeterminate] = None
        done: List[int] = []
        for p in sorted(set(by_part) | set(groups_by_part)):
            try:
                self.partitions[p].create_jobs(
                    by_part.get(p, []), groups=groups_by_part.get(p, ()),
                    latch=latch)
                done.append(p)
            except ReplicationIndeterminate as e:
                # locally durable on that partition: keep going — the
                # other partitions' writers must not be held hostage
                indeterminate = e
                done.append(p)
            except AbortTransaction:
                # a duplicate raced past the pre-check (or the shard
                # refused for its own reasons): earlier partitions'
                # sub-batches are latched-invisible — roll them back so
                # the abort means NOTHING was created
                if latch is not None:
                    for q in done:
                        try:
                            self.partitions[q].discard_latched(latch)
                        except Exception:
                            # best-effort: a partition that cannot
                            # confirm the discard leaves its jobs
                            # latched-invisible; the client's
                            # idempotent retry path still heals them
                            pass
                raise
        if indeterminate is not None:
            raise ReplicationIndeterminate(
                f"partitioned submission partially unconfirmed: "
                f"{indeterminate}")
        return [j.uuid for j in jobs]

    def commit_jobs(self, uuids: List[str]) -> int:
        by_part: Dict[int, List[str]] = {}
        for uuid in uuids:
            p = self._partition_of_job(uuid)
            if p is not None:
                by_part.setdefault(p, []).append(uuid)
        return sum(self.partitions[p].commit_jobs(batch)
                   for p, batch in sorted(by_part.items()))

    def commit_latch(self, latch: str) -> None:
        for store in self.partitions:
            if latch in store._latches:
                store.commit_latch(latch)

    # --------------------------------------------------------- launches
    def launch_instance(self, job_uuid: str, task_id: str, hostname: str,
                        **kw) -> Instance:
        store = self._route_job(job_uuid)
        if store is None:
            raise AbortTransaction("no-such-job")
        return store.launch_instance(job_uuid, task_id, hostname, **kw)

    def launch_instances(self, entries: List[Dict[str, Any]]
                         ) -> Tuple[List[Instance],
                                    List[Tuple[str, str]]]:
        by_part: Dict[int, List[Dict[str, Any]]] = {}
        failures: List[Tuple[str, str]] = []
        gang_part: Dict[str, int] = {}
        for e in entries:
            p = self._partition_of_job(e["job_uuid"])
            if p is None:
                failures.append((e["job_uuid"], "no-such-job"))
                continue
            g = e.get("gang")
            if g:
                if gang_part.setdefault(g, p) != p:
                    raise PartitionRoutingError(
                        f"gang {g} spans partitions — group routing "
                        "admitted a cross-partition gang")
            by_part.setdefault(p, []).append(e)
        out: List[Instance] = []
        for p, batch in sorted(by_part.items()):
            insts, fails = self.partitions[p].launch_instances(batch)
            out.extend(insts)
            failures.extend(fails)
        return out, failures

    def update_instance_status(self, task_id: str, *a, **kw) -> bool:
        store = self._route_instance(task_id)
        return store.update_instance_status(task_id, *a, **kw) \
            if store is not None else False

    def update_instance_progress(self, task_id: str, *a, **kw) -> bool:
        store = self._route_instance(task_id)
        return store.update_instance_progress(task_id, *a, **kw) \
            if store is not None else False

    def update_instance_ports(self, task_id: str, ports) -> bool:
        store = self._route_instance(task_id)
        return store.update_instance_ports(task_id, ports) \
            if store is not None else False

    def update_instance_sandbox(self, task_id: str, **kw) -> bool:
        store = self._route_instance(task_id)
        return store.update_instance_sandbox(task_id, **kw) \
            if store is not None else False

    def clear_launch_intents(self, task_ids: List[str]) -> int:
        return sum(store.clear_launch_intents(task_ids)
                   for store in self.partitions)

    def launch_intents(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for store in self.partitions:
            out.extend(store.launch_intents())
        out.sort(key=lambda r: r.get("created_ms", 0))
        return out

    def kill_job(self, job_uuid: str) -> bool:
        store = self._route_job(job_uuid)
        return store.kill_job(job_uuid) if store is not None else False

    def retry_job(self, job_uuid: str, retries: int) -> bool:
        store = self._route_job(job_uuid)
        return store.retry_job(job_uuid, retries) \
            if store is not None else False

    def set_placement_investigation(self, job_uuid: str, **kw) -> bool:
        store = self._route_job(job_uuid)
        return store.set_placement_investigation(job_uuid, **kw) \
            if store is not None else False

    # --------------------------------------------------- dynamic config
    # control-plane documents are global: partition 0 is the authority
    # (the same slot the GLOBAL_POOL quota plane uses)
    def set_dynamic_config(self, key: str, value: Dict[str, Any]) -> None:
        self.partitions[0].set_dynamic_config(key, value)

    def update_dynamic_config(self, key: str,
                              updates: Dict[str, Any]) -> Dict[str, Any]:
        return self.partitions[0].update_dynamic_config(key, updates)

    def dynamic_config(self, key: str) -> Optional[Dict[str, Any]]:
        return self.partitions[0].dynamic_config(key)

    # ---------------------------------------------------------- queries
    def job(self, uuid: str) -> Optional[Job]:
        store = self._route_job(uuid)
        return store.job(uuid) if store is not None else None

    def jobs_bulk(self, uuids) -> List[Optional[Job]]:
        # keep the batched-read contract the scheduler's hot paths
        # rely on: ONE lock round + clone pass per touched partition,
        # not a probe + lock per uuid
        uuids = list(uuids)
        out: List[Optional[Job]] = [None] * len(uuids)
        by_part: Dict[int, List[int]] = {}
        for i, u in enumerate(uuids):
            p = self._partition_of_job(u)
            if p is not None:
                by_part.setdefault(p, []).append(i)
        for p, idxs in sorted(by_part.items()):
            got = self.partitions[p].jobs_bulk([uuids[i] for i in idxs])
            for i, j in zip(idxs, got):
                out[i] = j
        return out

    def job_ref(self, uuid: str) -> Optional[Job]:
        for store in self.partitions:
            j = store.job_ref(uuid)
            if j is not None:
                return j
        return None

    def instance_ref(self, task_id: str) -> Optional[Instance]:
        for store in self.partitions:
            i = store.instance_ref(task_id)
            if i is not None:
                return i
        return None

    def instance(self, task_id: str) -> Optional[Instance]:
        store = self._route_instance(task_id)
        return store.instance(task_id) if store is not None else None

    def group(self, uuid: str) -> Optional[Group]:
        for store in self.partitions:
            g = store.group(uuid)
            if g is not None:
                return g
        return None

    def group_is_gang(self, uuid: Optional[str]) -> bool:
        return any(store.group_is_gang(uuid) for store in self.partitions)

    def gang_size(self, uuid: Optional[str]) -> int:
        for store in self.partitions:
            n = store.gang_size(uuid)
            if n:
                return n
        return 0

    def gang_groups_of(self, jobs) -> Dict[str, Group]:
        out: Dict[str, Group] = {}
        for store in self.partitions:
            out.update(store.gang_groups_of(jobs))
        return out

    def gang_live_members(self, uuid: Optional[str]) -> int:
        # a gang lives whole inside ONE partition (group routing refuses
        # cross-partition gangs), so the first non-gang-free shard wins
        for store in self.partitions:
            if store.group_is_gang(uuid):
                return store.gang_live_members(uuid)
        return 0

    def gang_admission_size(self, uuid: Optional[str]) -> int:
        for store in self.partitions:
            if store.group_is_gang(uuid):
                return store.gang_admission_size(uuid)
        return 0

    def gang_growth_headroom(self, uuid: Optional[str]) -> float:
        for store in self.partitions:
            if store.group_is_gang(uuid):
                return store.gang_growth_headroom(uuid)
        return float("inf")

    def elastic_gang_groups(self) -> List[Group]:
        out: List[Group] = []
        for store in self.partitions:
            out.extend(store.elastic_gang_groups())
        return out

    def jobs_where(self, pred: Callable[[Job], bool],
                   clone: bool = True) -> List[Job]:
        out: List[Job] = []
        for store in self.partitions:
            out.extend(store.jobs_where(pred, clone=clone))
        return out

    def pending_jobs(self, pool: Optional[str] = None,
                     clone: bool = True) -> List[Job]:
        if pool is not None:
            # single-pool fast path: one partition owns the pool
            return self._for_pool(pool).pending_jobs(pool, clone=clone)
        out: List[Job] = []
        for store in self.partitions:
            out.extend(store.pending_jobs(clone=clone))
        return out

    def running_jobs(self, pool: Optional[str] = None) -> List[Job]:
        if pool is not None:
            return self._for_pool(pool).running_jobs(pool)
        out: List[Job] = []
        for store in self.partitions:
            out.extend(store.running_jobs())
        return out

    def running_instances(self, pool: Optional[str] = None,
                          clone: bool = True
                          ) -> List[Tuple[Job, Instance]]:
        if pool is not None:
            return self._for_pool(pool).running_instances(pool, clone=clone)
        out: List[Tuple[Job, Instance]] = []
        for store in self.partitions:
            out.extend(store.running_instances(clone=clone))
        return out

    def user_usage(self, pool: Optional[str] = None
                   ) -> Dict[str, Dict[str, float]]:
        if pool is not None:
            return self._for_pool(pool).user_usage(pool)
        merged: Dict[str, Dict[str, float]] = {}
        for store in self.partitions:
            for user, u in store.user_usage().items():
                m = merged.setdefault(user, {"count": 0.0, "cpus": 0.0,
                                             "mem": 0.0, "gpus": 0.0})
                for k, v in u.items():
                    m[k] = m.get(k, 0.0) + v
        return merged

    # ------------------------------------------------ pools/shares/quota
    def put_pool(self, pool: Pool) -> None:
        self._for_pool(pool.name).put_pool(pool)

    def pools(self) -> List[Pool]:
        out: List[Pool] = []
        for store in self.partitions:
            out.extend(store.pools())
        return out

    def pool(self, name: str) -> Optional[Pool]:
        return self._for_pool(name).pool(name)

    def set_share(self, user: str, pool: str, resources, reason: str = ""
                  ) -> None:
        self._for_pool(pool).set_share(user, pool, resources, reason)

    def get_share(self, user: str, pool: str) -> Dict[str, float]:
        return self._for_pool(pool).get_share(user, pool)

    def retract_share(self, user: str, pool: str) -> None:
        self._for_pool(pool).retract_share(user, pool)

    def set_quota(self, user: str, pool: str, resources,
                  count: float = float("inf"), reason: str = "") -> None:
        self._for_pool(pool).set_quota(user, pool, resources,
                                       count=count, reason=reason)

    def get_quota(self, user: str, pool: str) -> Dict[str, float]:
        return self._for_pool(pool).get_quota(user, pool)

    def retract_quota(self, user: str, pool: str) -> None:
        self._for_pool(pool).retract_quota(user, pool)

    def shares(self) -> List[ShareEntry]:
        out: List[ShareEntry] = []
        for store in self.partitions:
            out.extend(store.shares())
        return out

    def quotas(self) -> List[QuotaEntry]:
        out: List[QuotaEntry] = []
        for store in self.partitions:
            out.extend(store.quotas())
        return out

    # ------------------------------------- cross-partition invariants
    def check_user_quota(self, user: str, n_new: int) -> Optional[str]:
        """The cross-partition per-user quota gate (docs/DEPLOY.md): a
        finite ``count`` quota on the reserved pool ``"*"`` caps the
        user's TOTAL footprint (pending + running) across every
        partition.  Enforcement reads the summary exchange — bounded
        staleness, never job state — so a user at quota on partitions
        {0,1} is refused on BOTH, by whichever REST node asks.  Returns
        None when allowed, else the refusal message."""
        quota = self.get_quota(user, GLOBAL_POOL)
        cap = quota.get("count", float("inf"))
        if cap == float("inf"):
            return None
        totals = self.summaries.user_totals(user)
        have = totals["pending"] + totals["running"]
        if have + n_new > cap:
            return (f"global quota exceeded for user {user}: "
                    f"{int(have)} jobs across {self.pmap.count} "
                    f"partition(s) + {n_new} new > count quota "
                    f"{int(cap)} (summary staleness "
                    f"{self.summaries.staleness_s():.3f}s, bound "
                    f"{self.summaries.max_age_s}s)")
        return None

    # ------------------------------------------------------- durability
    def subscribe(self, fn: Callable[[int, List[Any]], None]) -> None:
        for store in self.partitions:
            store.subscribe(fn)

    def ensure_index(self):
        raise NotImplementedError(
            "the columnar index is per-store; the partitioned facade "
            "serves the entity path (configure columnar_index=False "
            "with partitions, or run P=1 compatibility mode)")

    def enable_group_commit(self, window_ms: float = 0.5,
                            max_batch: int = 256) -> bool:
        ok = True
        for store in self.partitions:
            ok = store.enable_group_commit(
                window_ms=window_ms, max_batch=max_batch) and ok
        return ok

    def disable_group_commit(self) -> None:
        for store in self.partitions:
            store.disable_group_commit()

    def group_commit_stats(self) -> Optional[Dict[str, Any]]:
        per = [store.group_commit_stats() for store in self.partitions]
        live = [s for s in per if s is not None]
        if not live:
            return None
        return {
            "pending": sum(s["pending"] for s in live),
            "batches": sum(s["batches"] for s in live),
            "commits": sum(s["commits"] for s in live),
            "indeterminate": sum(s["indeterminate"] for s in live),
            "max_batch": max(s["max_batch"] for s in live),
            "window_ms": live[0]["window_ms"],
            "per_partition": per,
        }

    def commit_offset(self) -> int:
        """Total journaled bytes across partitions — a LIVENESS datum
        (is anything journaled / did it advance), NEVER a position to
        compare offsets against: per-partition positions live in the
        commit-token vector (each partition is its own offset space)."""
        return sum(store.commit_offset() for store in self.partitions)

    def commit_token(self) -> str:
        """The partition-qualified token VECTOR: each journaled
        partition's ``p<i>:<epoch>:<offset>`` position, comma-joined.
        Write responses carry the vector (cheap at small P) so a client
        holds read-your-writes over every partition it may have
        touched; the follower wait-gate satisfies entries per
        partition.  Partitions with zero journaled bytes are omitted —
        there is nothing to read behind them, and their entry would
        force a single-partition follower to redirect for no reason."""
        return ",".join(store.commit_token()
                        for store in self.partitions
                        if store.commit_offset() > 0)

    def flush_audit(self) -> int:
        return sum(store.flush_audit() for store in self.partitions)

    def checkpoint(self) -> None:
        for store in self.partitions:
            store.checkpoint()

    def partition_stats(self) -> List[Dict[str, Any]]:
        """Per-partition observability block (/debug/replication
        ``partitions``, the monitor's labeled gauges): journal head,
        epoch, group-commit stage state, declared pools."""
        declared: Dict[int, List[str]] = {}
        for pool, idx in self.pmap.pools.items():
            declared.setdefault(idx, []).append(pool)
        out = []
        for i, store in enumerate(self.partitions):
            out.append({
                "partition": f"p{i}",
                "journal_bytes": store.commit_offset(),
                "epoch": store._journal_epoch,
                "group_commit": store.group_commit_stats(),
                "declared_pools": sorted(declared.get(i, [])),
            })
        return out

    def close(self) -> None:
        for store in self.partitions:
            store.close()


def substores(store) -> List[Store]:
    """The physical shards behind ``store``: the partition list of a
    :class:`PartitionedStore`, else the store itself — the one idiom
    for call sites that iterate raw entity tables under the store lock
    (they must take each partition's lock in turn, never nested)."""
    return list(getattr(store, "partitions", None) or [store])


class PartitionedReadView:
    """A standby's live read plane over P mirrored partition dirs: one
    :class:`~.read_replica.FollowerReadView` per partition, a
    :class:`PartitionedStore` facade over the per-partition view stores
    for merged GETs, and the per-partition token wait-gate.

    The facade is REBUILT on any member view's store swap (mirror
    re-base) — ``on_swap`` subscribers get the fresh facade, exactly
    like the single-view contract."""

    def __init__(self, directory: str, pmap: PartitionMap,
                 interval_s: float = 0.02,
                 on_swap: Optional[Callable[[Any], None]] = None,
                 start: bool = True):
        from .read_replica import FollowerReadView
        self.directory = str(directory)
        self.pmap = pmap
        self._on_swap: List[Callable[[Any], None]] = []
        if on_swap is not None:
            self._on_swap.append(on_swap)
        self.views = [
            FollowerReadView(os.path.join(directory, f"p{i}"),
                             interval_s=interval_s, start=start,
                             partition_id=i)
            for i in range(pmap.count)]
        self.store = self._build_facade()
        for view in self.views:
            view.on_swap(self._member_swapped)

    def _build_facade(self) -> PartitionedStore:
        # each member view's replica store was born with its partition
        # id (FollowerReadView(partition_id=...)), so routing and lock
        # families stay coherent through rebuilds
        return PartitionedStore(
            [view.store for view in self.views], self.pmap)

    def _member_swapped(self, _store) -> None:
        self.store = self._build_facade()
        for fn in self._on_swap:
            fn(self.store)

    def on_swap(self, fn: Callable[[Any], None]) -> None:
        self._on_swap.append(fn)
        fn(self.store)

    # ------------------------------------------------------- staleness
    @property
    def offset(self) -> int:
        return sum(view.offset for view in self.views)

    def lag_bytes(self) -> int:
        return sum(view.lag_bytes() for view in self.views)

    def age_ms(self) -> float:
        return max(view.age_ms() for view in self.views)

    def stats(self) -> Dict[str, Any]:
        return {
            "offset": self.offset,
            "lag_bytes": self.lag_bytes(),
            "age_ms": round(self.age_ms(), 1),
            "applied_records": sum(v.applied_records
                                   for v in self.views),
            "rebuilds": sum(v.rebuilds for v in self.views),
            "partitions": [dict(v.stats(), partition=f"p{i}")
                           for i, v in enumerate(self.views)],
        }

    # ------------------------------------------------- token wait-gate
    def wait_commit_token(self, token: str, timeout_s: float = 1.0
                          ) -> bool:
        """Satisfy a commit-token VECTOR per partition: each
        ``(partition, epoch, offset)`` entry waits against the mirror
        of THAT partition (legacy partitionless entries can only be
        satisfied by a partitionless view — redirect).  False on any
        unsatisfied entry (caller redirects to the leader)."""
        entries = parse_token_vector(token)
        deadline = time.monotonic() + max(timeout_s, 0.0)
        for part, ep, off in entries:
            if part is None or not 0 <= part < len(self.views):
                return False
            remaining = max(deadline - time.monotonic(), 0.0)
            if not self.views[part].wait_token(ep, off,
                                               timeout_s=remaining):
                return False
        return True

    def wait_token(self, epoch: Optional[int], offset: int,
                   timeout_s: float = 1.0) -> bool:
        """Legacy single-entry gate: a partitionless token cannot name
        which partition's offset space it lives in — unsatisfiable
        here (the leader is the only safe server for it)."""
        return False

    def stop(self) -> None:
        for view in self.views:
            view.stop()
