from .schema import (  # noqa: F401
    Application,
    Checkpoint,
    CheckpointMode,
    Constraint,
    DruMode,
    Group,
    GroupPlacementType,
    Instance,
    InstanceStatus,
    Job,
    JobState,
    Pool,
    QuotaEntry,
    Reason,
    Reasons,
    Resources,
    RESOURCE_DIMS,
    SchedulerKind,
    ShareEntry,
    below_quota,
    add_usage,
    job_usage,
    new_uuid,
    now_ms,
    to_json,
)
from .store import (AbortTransaction, ReplicationIndeterminate,  # noqa: F401
                    ReplicationTimeout, StaleEpochError, Store, TxEvent)
from .partition import (GLOBAL_POOL, PartitionedReadView,  # noqa: F401
                        PartitionedStore, PartitionMap,
                        PartitionRoutingError, UserSummaryExchange,
                        parse_token_vector, substores)
from .index import ColumnarIndex  # noqa: F401
from . import machines  # noqa: F401
