"""Pure state-machine transition functions for jobs and instances.

These mirror the reference's transactional Datomic db-fns
(reference: schema.clj :instance/update-state :1242-1308 and
:job/update-state :1202-1239) as pure functions over entity values.  The
store applies them inside a transaction so the "txn aborts if state moved"
discipline is preserved (SURVEY.md section 5, race handling #4).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from .schema import (
    Instance,
    InstanceStatus,
    Job,
    JobState,
    Reasons,
)

# Legal instance transitions (reference: schema.clj:1242-1308). A transition
# request to the current state is a no-op; anything not listed is rejected.
_INSTANCE_TRANSITIONS = {
    InstanceStatus.UNKNOWN: {InstanceStatus.RUNNING, InstanceStatus.SUCCESS, InstanceStatus.FAILED},
    InstanceStatus.RUNNING: {InstanceStatus.SUCCESS, InstanceStatus.FAILED},
    InstanceStatus.SUCCESS: set(),
    InstanceStatus.FAILED: set(),
}


def instance_transition_allowed(cur: InstanceStatus, new: InstanceStatus) -> bool:
    return new is cur or new in _INSTANCE_TRANSITIONS[cur]


def next_job_state(
    job: Job,
    instances: Dict[str, Instance],
) -> Tuple[JobState, Optional[str]]:
    """Recompute job state from its instances.

    Returns (state, reason) where reason explains a COMPLETED verdict.
    Mirrors :job/update-state (schema.clj:1202-1239):
      - any live (unknown/running) instance  -> RUNNING
      - a successful instance                -> COMPLETED
      - all attempts consumed                -> COMPLETED
      - user killed the job                  -> COMPLETED
      - otherwise                            -> WAITING (retry)
    """
    if job.user_killed:
        return JobState.COMPLETED, "user-killed"
    success = False
    live = False
    for tid in job.instances:
        inst = instances.get(tid)
        if inst is None:
            continue
        if inst.status is InstanceStatus.SUCCESS:
            success = True
        elif inst.status in (InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
            live = True
    if success:
        return JobState.COMPLETED, "success"
    if live:
        return JobState.RUNNING, None
    if job.attempts_used(instances) >= job.max_retries:
        return JobState.COMPLETED, "attempts-consumed"
    return JobState.WAITING, None


def allowed_to_start(job: Job, instances: Dict[str, Instance]) -> Optional[str]:
    """Launch guard (reference: :job/allowed-to-start? schema.clj:1311-1325).

    Returns None when the job may start a new instance, else a rejection
    reason string.  Applied inside the launch transaction so a concurrent
    kill/complete aborts the launch (scheduler.clj:987-1009 invariant).
    """
    if job.state is not JobState.WAITING:
        return f"job-state-{job.state.value}"
    if not job.committed:
        return "uncommitted"
    for tid in job.instances:
        inst = instances.get(tid)
        if inst is not None and inst.status in (InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
            return "has-live-instance"
    return None


def classify_failure(reason_code: Optional[int]) -> Tuple[bool, Optional[int]]:
    """Return (mea_culpa?, failure_limit) for a failure reason code."""
    reason = Reasons.by_code(reason_code if reason_code is not None else Reasons.UNKNOWN.code)
    return reason.mea_culpa, reason.failure_limit
